"""Benchmark: deferred_init → sharded JAX materialization on TPU.

The BASELINE workload family (BASELINE.md): construct a torch model under
deferred init (zero allocation), then materialize its parameters directly as
``jax.Array``s on the TPU.  The measured baseline is the workflow this
replaces — eager torch CPU init followed by host→device transfer of every
parameter.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` > 1 means the deferred path beats eager-init-and-transfer.
"""

from __future__ import annotations

import json
import time

import torch
import torch.nn as nn


class Block(nn.Module):
    def __init__(self, dim: int, ffn: int):
        super().__init__()
        self.ln_1 = nn.LayerNorm(dim)
        self.attn_qkv = nn.Linear(dim, 3 * dim)
        self.attn_proj = nn.Linear(dim, dim)
        self.ln_2 = nn.LayerNorm(dim)
        self.mlp_fc = nn.Linear(dim, ffn)
        self.mlp_proj = nn.Linear(ffn, dim)


class GPT2Small(nn.Module):
    """GPT-2-small-shaped init workload (~124M params, BASELINE config 3's
    little sibling sized for the single-chip bench)."""

    def __init__(self, vocab=50257, dim=768, n_layer=12, seq=1024):
        super().__init__()
        self.wte = nn.Embedding(vocab, dim)
        self.wpe = nn.Embedding(seq, dim)
        self.h = nn.ModuleList([Block(dim, 4 * dim) for _ in range(n_layer)])
        self.ln_f = nn.LayerNorm(dim)
        self.lm_head = nn.Linear(dim, vocab, bias=False)


def _rss_mb() -> float:
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024


def main():
    import jax

    from torchdistx_tpu.deferred_init import deferred_init
    from torchdistx_tpu.materialize import materialize_module_jax

    # --- baseline: eager torch init on host + transfer every param ---------
    t0 = time.perf_counter()
    eager = GPT2Small()
    moved = [
        jax.device_put(p.detach().numpy()) for p in eager.parameters()
    ]
    jax.block_until_ready(moved)
    baseline_s = time.perf_counter() - t0
    n_params = sum(p.numel() for p in eager.parameters())
    del eager, moved

    # --- ours: deferred init (fake, zero alloc) + JAX materialize ----------
    rss_before = _rss_mb()
    t0 = time.perf_counter()
    model = deferred_init(GPT2Small)
    fake_s = time.perf_counter() - t0
    rss_fake = _rss_mb()
    # rbg RNG: single-chip init, no cross-topology determinism needed;
    # roughly halves XLA compile time of the init program.
    arrays = materialize_module_jax(model, dtype=torch.float32, rng_impl="rbg")
    jax.block_until_ready(list(arrays.values()))
    ours_s = time.perf_counter() - t0

    print(
        json.dumps(
            {
                "metric": "deferred_init_materialize_gpt2s_1chip",
                "value": round(ours_s, 4),
                "unit": "s",
                "vs_baseline": round(baseline_s / ours_s, 3),
                "details": {
                    "params": n_params,
                    "eager_init_transfer_s": round(baseline_s, 4),
                    "fake_construction_s": round(fake_s, 4),
                    "fake_rss_growth_mb": round(rss_fake - rss_before, 1),
                    "peak_rss_mb": round(_rss_mb(), 1),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
