"""Benchmark: deferred_init → JAX materialization + train-step MFU on TPU.

The BASELINE workload family (BASELINE.md): construct a torch model under
deferred init (zero allocation), then materialize its parameters directly as
``jax.Array``s on the TPU.  The measured baseline is the workflow this
replaces — eager torch CPU init followed by host→device transfer of every
parameter (cast to bf16 on host, the standard TPU-training recipe).

Headline config: GPT-2-XL-shaped (~1.6B params, BASELINE config 3's scale) in
bf16 on one chip.  At this scale eager init+transfer is dominated by host RNG
and PCIe/host bandwidth while the deferred path generates parameters on-device
from a compact compiled program (compile time O(unique layer kinds) via the
grouped materializer — see materialize.py), so the ratio reflects the
framework's actual pitch.

Also measured (reported in details): the 124M config for round-over-round
continuity, fake-construction time, peak host RSS, and a training-step
throughput probe (tokens/s + MFU) of the flagship Llama stack with the Pallas
flash-attention kernel.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "details"}.
``vs_baseline`` > 1 means the deferred path beats eager-init-and-transfer.
"""

from __future__ import annotations

import json
import time

import torch
import torch.nn as nn


class Block(nn.Module):
    def __init__(self, dim: int, ffn: int):
        super().__init__()
        self.ln_1 = nn.LayerNorm(dim)
        self.attn_qkv = nn.Linear(dim, 3 * dim)
        self.attn_proj = nn.Linear(dim, dim)
        self.ln_2 = nn.LayerNorm(dim)
        self.mlp_fc = nn.Linear(dim, ffn)
        self.mlp_proj = nn.Linear(ffn, dim)


class GPT2(nn.Module):
    """GPT-2-shaped init workload (BASELINE config 3 family)."""

    def __init__(self, vocab=50257, dim=768, n_layer=12, seq=1024):
        super().__init__()
        self.wte = nn.Embedding(vocab, dim)
        self.wpe = nn.Embedding(seq, dim)
        self.h = nn.ModuleList([Block(dim, 4 * dim) for _ in range(n_layer)])
        self.ln_f = nn.LayerNorm(dim)
        self.lm_head = nn.Linear(dim, vocab, bias=False)


def GPT2Small():
    return GPT2()


def GPT2XL():
    return GPT2(vocab=50257, dim=1600, n_layer=48, seq=1024)


def _rss_mb() -> float:
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024


def _rss_now_mb() -> float:
    """CURRENT resident set (VmRSS), not the lifetime peak — usable for
    configs measured after another config's multi-GB eager baseline."""
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024
    return 0.0


# Peak dense bf16 TFLOP/s per chip by device_kind substring (public specs).
_PEAK_TFLOPS = [
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0),
    ("v5e", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
]


def _peak_tflops(device_kind: str):
    kind = device_kind.lower()
    for sub, tf in _PEAK_TFLOPS:
        if sub in kind:
            return tf
    return None


def bench_materialize_ours(model_fn, *, dtype, rng_impl="rbg", report_rss=True):
    """OUR side of the materialize comparison: deferred + JAX materialize,
    then a warm re-materialization.

    RSS is reported as a CURRENT-VmRSS delta around the materialize (not
    ``ru_maxrss``): configs after the first would otherwise echo an
    earlier config's eager host allocation in the lifetime peak.
    """
    import jax

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.deferred_init import deferred_init
    from torchdistx_tpu.materialize import materialize_module_jax

    # Phase breakdown and cache/fastpath counts come from telemetry, not
    # bench-side bookkeeping: the bench reports what the system measured
    # about itself.  No sink needed — last_profile is the phase-span view
    # (assembled on every call, sinks off) and counters() reads the live
    # registry.
    import torchdistx_tpu.materialize as _mat

    c0 = telemetry.counters()

    rss_before = _rss_now_mb()
    t0 = time.perf_counter()
    model = deferred_init(model_fn)
    fake_s = time.perf_counter() - t0
    arrays = materialize_module_jax(model, dtype=dtype, rng_impl=rng_impl)
    jax.block_until_ready(list(arrays.values()))
    ours_s = time.perf_counter() - t0
    rss_ours = _rss_now_mb()
    del model, arrays

    c1 = telemetry.counters()
    phases = {
        k: round(v, 4)
        for k, v in _mat.last_profile.items()
        if k.endswith("_s")
    }
    counters_delta = {
        k: c1[k] - c0.get(k, 0)
        for k in (
            "materialize.exec_cache_hits",
            "materialize.fill_fastpath_hits",
        )
        if c1.get(k, 0) - c0.get(k, 0)
    }

    # Warm re-materialization of the same architecture (sweep/restart/
    # re-shard flows): the executable cache skips trace + compile, leaving
    # fake construction + replay execution.  Min of 3: the measurement is
    # a fraction of a second, and single tunnel windows read 2-3× slow.
    warm_s = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        model = deferred_init(model_fn)
        arrays = materialize_module_jax(
            model, dtype=dtype, rng_impl=rng_impl
        )
        jax.block_until_ready(list(arrays.values()))
        warm_s = min(warm_s, time.perf_counter() - t0)
        del model, arrays

    out = {
        "ours_s": round(ours_s, 4),
        "ours_warm_s": round(warm_s, 4),
        "fake_construction_s": round(fake_s, 4),
        "phases": phases,
        "telemetry_counters": counters_delta,
    }
    if report_rss:
        out["rss_ours_mb"] = round(rss_ours, 1)
        out["rss_before_mb"] = round(rss_before, 1)
        out["rss_ours_growth_mb"] = round(rss_ours - rss_before, 1)
    return out


def bench_materialize_eager(model_fn, *, dtype, out):
    """EAGER baseline: torch init on host, cast, transfer every param.
    Fills ``eager_*`` and the ``vs_baseline*`` ratios into ``out``.

    The INIT component takes min-of-2 (torch's CPU init was measured
    swinging 10.9 ↔ 34 s for the same 1.6B model — pure host CPU noise,
    no tunnel involvement), so the ratio uses the baseline's best case.
    The TRANSFER runs exactly once: a second multi-GB transfer would
    deepen the tunnel-degradation window the NEXT config's (single-shot)
    ours_s is measured in — an asymmetric bias against us.
    """
    import jax
    import numpy as np

    import ml_dtypes

    np_dtype = (
        ml_dtypes.bfloat16 if dtype == torch.bfloat16 else np.float32
    )
    eager_init_s = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        eager = model_fn()
        eager_init_s = min(eager_init_s, time.perf_counter() - t0)
    t0 = time.perf_counter()
    moved = [
        jax.device_put(p.detach().numpy().astype(np_dtype))
        for p in eager.parameters()
    ]
    jax.block_until_ready(moved)
    baseline_s = eager_init_s + (time.perf_counter() - t0)
    n_params = sum(p.numel() for p in eager.parameters())
    del eager, moved

    out.update(
        eager_init_transfer_s=round(baseline_s, 4),
        eager_init_only_s=round(eager_init_s, 4),
        vs_baseline=round(baseline_s / out["ours_s"], 3),
        vs_baseline_warm=round(baseline_s / out["ours_warm_s"], 3),
        params=n_params,
    )
    return out


def bench_cold_uncached():
    """First-ever-run materialization cost, honestly measured: a fresh
    process with BOTH the persistent XLA cache and the in-process executable
    cache disabled, backend pre-warmed so only the materialization is timed.

    The in-process ``ours_s`` numbers ride the persistent compilation cache
    (legitimate: restarts/sweeps are the common case) — this subprocess
    measurement is the ratchet's floor, so cache behavior can't silently
    degrade first-ever-run cost (VERDICT r2 weak #7).
    """
    import json as _json
    import os
    import subprocess
    import sys

    env = dict(
        os.environ, TDX_NO_COMPILATION_CACHE="1", TDX_NO_EXEC_CACHE="1"
    )
    code = r"""
import json, time, torch, torch.nn as nn
import jax
from torchdistx_tpu.deferred_init import deferred_init
from torchdistx_tpu.materialize import materialize_module_jax
from bench import GPT2XL, GPT2Small
from torchdistx_tpu.models.resnet_torch import resnet50
deferred_init(nn.Linear, 8, 8)
jax.block_until_ready(jax.device_put(1.0))
jax.block_until_ready(jax.random.key(0, impl="rbg"))
out = {}
for label, fn, dt in [
    ("gpt2xl_bf16", GPT2XL, torch.bfloat16),
    ("gpt2small_f32", GPT2Small, torch.float32),
    ("resnet50_f32", resnet50, torch.float32),
]:
    m = deferred_init(fn)
    t0 = time.perf_counter()
    arrs = materialize_module_jax(m, dtype=dt, rng_impl="rbg")
    jax.block_until_ready(list(arrs.values()))
    out[label] = round(time.perf_counter() - t0, 3)
    del m, arrs
print(json.dumps(out))
"""
    # Best of 2 fresh subprocesses: the cold probe runs LAST (after the
    # big eager transfers), where a degraded tunnel window once inflated
    # the XL number 2.2× (22.6 s vs 10.2 s re-measured minutes later).
    # The WHOLE run with the smaller headline (XL) number wins — a
    # per-key min would stitch numbers from different processes together,
    # and the derived *_vs_baseline ratios would no longer describe any
    # run that actually happened (ADVICE round 5).
    headline = "gpt2xl_bf16"
    best = None
    samples = 0
    err = None
    for _ in range(2):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                env=env,
                capture_output=True,
                text=True,
                timeout=900,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except (OSError, subprocess.SubprocessError) as e:
            err = err or {"error": f"{type(e).__name__}: {e}"}
            continue
        lines = r.stdout.strip().splitlines()
        if r.returncode != 0 or not lines:
            err = err or {
                "error": f"subprocess exited {r.returncode}",
                "stderr_tail": r.stderr[-2000:],
            }
            continue
        try:
            got = _json.loads(lines[-1])
        except ValueError as e:
            err = err or {
                "error": f"unparseable probe output: {e}",
                "stdout_tail": r.stdout[-2000:],
            }
            continue
        samples += 1
        if best is None or got.get(headline, float("inf")) < best.get(
            headline, float("inf")
        ):
            best = got
    if best is not None:
        best["samples"] = samples
        if samples < 2 and err is not None:
            # One sample only — say so, the best-of-2 claim didn't apply.
            best["second_sample_error"] = err.get("error", "unknown")
        return best
    return err


def bench_train_step():
    """Train-step throughput of the flagship Llama stack on one chip.

    ~350M-param model, bf16, Pallas flash attention; reports tokens/s and
    MFU against the chip's public peak bf16 FLOP/s.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from torchdistx_tpu.models import llama
    from torchdistx_tpu.parallel import train_step as ts
    from torchdistx_tpu.parallel.mesh import make_mesh, MeshSpec

    cfg = llama.LlamaConfig(
        vocab_size=32000,
        dim=1024,
        n_layers=16,
        n_heads=16,
        n_kv_heads=16,
        ffn_dim=4096,
        max_seq_len=1024,
        # 350M at batch 8 fits HBM with all activations saved; remat would
        # re-run every block's forward in the backward (~1/3 more FLOPs)
        # for memory this config doesn't need.  Measured: 0.345 → 0.381 MFU.
        remat=False,
    )
    batch, seq = 8, 1024
    mesh = make_mesh(MeshSpec(fsdp=1))
    init_fn, step_fn = ts.make_train_step(
        cfg, mesh, optax.adamw(1e-3), attn_impl="pallas"
    )
    state = init_fn(jax.random.PRNGKey(0))
    n_params = sum(
        int(jnp.size(p)) for p in jax.tree.leaves(state.params)
    )
    tokens = jax.device_put(
        jax.random.randint(
            jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size
        ),
        ts.batch_sharding(mesh),
    )
    batch_dict = {"tokens": tokens, "targets": tokens}

    # Warmup (compile) then timed steps.  Sync via host transfer of the loss
    # (block_until_ready alone does not reliably block on the tunneled
    # backend); the state dependency chain serializes all steps before it.
    for _ in range(2):
        state, metrics = step_fn(state, batch_dict)
    float(metrics["loss"])
    n_steps = 10
    # Min of 3 chained runs: tunnel throughput drifts on the scale of
    # seconds-to-minutes, and a single window can read 20-30% slow.
    dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            state, metrics = step_fn(state, batch_dict)
        float(metrics["loss"])
        dt = min(dt, time.perf_counter() - t0)

    tokens_per_s = n_steps * batch * seq / dt
    # fwd+bwd matmul FLOPs ≈ 6·N per token, plus attention
    # 12·B·S²·D per layer per step (QKᵀ + PV, fwd 4·B·S²·D, bwd ×2).
    flops_per_step = (
        6.0 * n_params * batch * seq
        + 12.0 * batch * seq * seq * cfg.dim * cfg.n_layers
    )
    flops_per_s = flops_per_step * n_steps / dt
    kind = jax.devices()[0].device_kind
    peak = _peak_tflops(kind)
    out = {
        "params": n_params,
        "tokens_per_s": round(tokens_per_s, 1),
        "step_time_s": round(dt / n_steps, 4),
        "tflops_per_s": round(flops_per_s / 1e12, 2),
        "device_kind": kind,
        "loss_finite": bool(jnp.isfinite(metrics["loss"])),
    }
    if peak is not None:
        out["mfu"] = round(flops_per_s / (peak * 1e12), 4)
    # Publish through the same gauges parallel/fit.py feeds, so a trace
    # or snapshot taken around the bench reads the train numbers from the
    # system's registry rather than from this probe's locals.
    from torchdistx_tpu import telemetry

    telemetry.gauge("train.steps_per_s").set(round(n_steps / dt, 4))
    telemetry.gauge("train.tokens_per_s").set(out["tokens_per_s"])
    if "mfu" in out:
        telemetry.gauge("train.mfu").set(out["mfu"])
    return out


def bench_generate():
    """KV-cache decode throughput of the flagship stack on one chip.

    The serving-side number: batch-8 greedy decode (prefill 128, 256 new
    tokens) through the single-program prefill+scan generator
    (models/generate.py).  Decode is memory-bandwidth-bound; report
    decode tokens/s and the implied HBM utilization (params read once per
    step is the traffic floor).
    """
    import jax
    import jax.numpy as jnp

    from torchdistx_tpu.models import llama
    from torchdistx_tpu.models.generate import generate
    from torchdistx_tpu.parallel.mesh import make_mesh, MeshSpec

    cfg = llama.LlamaConfig(
        vocab_size=32000, dim=1024, n_layers=16, n_heads=16, n_kv_heads=16,
        ffn_dim=4096, max_seq_len=1024, remat=False,
    )
    batch, prompt_len, new = 8, 128, 256
    params = llama.init_sharded(
        jax.random.PRNGKey(0), cfg, make_mesh(MeshSpec(fsdp=1))
    )
    n_params = sum(int(jnp.size(p)) for p in jax.tree.leaves(params))
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size
    )
    key = jax.random.PRNGKey(2)

    def one_pass(new_tokens, n_iters=8):
        # Iterations chain on device (each call's output tokens feed the
        # next prompt) with ONE host sync at the end — per-call syncs
        # would measure tunnel round-trips, not decode time (same
        # discipline as the other probes).
        p = prompt
        t0 = time.perf_counter()
        for i in range(n_iters):
            out = generate(
                params, p, key, model=llama, cfg=cfg,
                max_new_tokens=new_tokens,
            )
            p = out[:, :prompt_len]
        int(p[0, 0])  # host sync
        return (time.perf_counter() - t0) / n_iters

    # Warmup/compile both lengths, syncing via host transfer like the
    # other probes (block_until_ready does not reliably block on the
    # tunneled backend).
    for n in (new // 2, new):
        out = generate(
            params, prompt, key, model=llama, cfg=cfg, max_new_tokens=n
        )
        int(out[0, 0])

    # Pure decode rate as the MARGINAL between two generation lengths —
    # the shared prefill (and its 128-token forward) cancels out of the
    # difference, so the number moves only when decode moves.  The two
    # lengths are measured in INTERLEAVED passes (min-of-3 each): tunnel
    # throughput drifts on the scale of seconds, and subtracting
    # measurements from different drift regimes would dominate the
    # difference.
    dt_half = float("inf")
    dt_full = float("inf")
    for _ in range(3):
        dt_half = min(dt_half, one_pass(new // 2))
        dt_full = min(dt_full, one_pass(new))
    out = {
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new,
        "e2e_tokens_per_s": round(batch * new / dt_full, 1),
        "sequences_per_s": round(batch / dt_full, 2),
    }
    if dt_full > dt_half:
        decode_step_s = (dt_full - dt_half) / (new - new // 2)
        out["decode_tokens_per_s"] = round(batch / decode_step_s, 1)
        # Per decode step every parameter is read once (bf16): HBM floor.
        out["param_read_gb_per_s"] = round(
            n_params * 2.0 / decode_step_s / 1e9, 1
        )
    else:
        # Drift swamped the marginal in every interleaved pass: flag it
        # rather than reporting an absurd clamped rate.
        out["decode_rate_error"] = "non-positive marginal (tunnel drift)"
    return out


def bench_serving():
    """Continuous-batching serving throughput of the flagship stack.

    The serving-path decode ratchet: the same 350M llama as
    ``bench_generate``, but behind the serving engine — 8 decode slots
    over a paged KV cache, mixed prompt/output lengths, Poisson-ish
    arrivals from a fixed seed.  Reports SUSTAINED decode tok/s
    (committed tokens / decode-dispatch time, slots kept full by
    continuous batching), TTFT p50/p95 (queue wait included), and peak
    block utilization — plus a **prefix-heavy phase**: 80% of requests
    share a 96-token system prompt, run against a cache-off and a
    cache-on engine on the SAME trace (``prefix_hit_rate``,
    mixed-traffic ``ttft_p95_s`` both ways, the cache's p95 speedup) —
    plus a **multi-tenant QoS phase**: a burst tenant's t=0 backlog vs
    a steady tenant's deadline-bearing higher-priority requests, FIFO
    and QoS engines paired on the SAME trace, reporting per-tenant
    TTFT p95, the steady tenant's deadline-hit rate both ways, and the
    preemption counts (``deadline_hit_improvement`` is the acceptance
    number — QoS must not lose to FIFO).
    Contrast with ``generate_llama_350m_decode``:
    there the whole batch finishes together and the cache is allocated
    at ``prompt+max_new`` per row; here slots recycle the moment a
    request's budget lands and pages free with them.

    Latency numbers come from the telemetry layer, not ad-hoc lists:
    TTFT/TPOT percentiles read back from the per-engine ``serve.*``
    histograms (via ``Engine.stats()``), and the per-tenant QoS numbers
    from :func:`scripts.trace_report.reconstruct` over the run's own
    event stream — the same reconstruction path a production trace or
    chaos soak goes through, so bench and post-mortem numbers can never
    drift apart.
    """
    import os
    import sys

    import jax
    import numpy as np

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.parallel.mesh import make_mesh, MeshSpec
    from torchdistx_tpu.serving import (
        Engine,
        init_paged_cache,
        swap_in_pages,
        swap_out_pages,
    )

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts")
    )
    from trace_report import reconstruct

    from torchdistx_tpu.telemetry import ops as tdx_ops

    # Collect the run's own trace in memory: the reconstruction below
    # reads the SAME event stream a production TDX_TELEMETRY trace
    # carries (restored to the caller's settings at the end).
    prev_telemetry = telemetry.configure(collect=True, max_spans=65536)
    telemetry.drain()
    # Per-tick utilization attribution WITHOUT an HTTP listener: the
    # drive loop below samples serve.occupancy / serve.goodput each tick
    # for the utilization numbers (restored at the end).
    prev_attr = tdx_ops.enable_tick_attribution(True)

    cfg = llama.LlamaConfig(
        vocab_size=32000, dim=1024, n_layers=16, n_heads=16, n_kv_heads=16,
        ffn_dim=4096, max_seq_len=1024, remat=False,
    )
    params = llama.init_sharded(
        jax.random.PRNGKey(0), cfg, make_mesh(MeshSpec(fsdp=1))
    )
    num_slots, block_size, max_model_len, chunk = 8, 32, 512, 16
    # 87.5% of dense capacity: paging has to work (requests queue when
    # pages run out), without starving the slots.
    num_blocks = 1 + int(num_slots * (max_model_len // block_size) * 7 / 8)

    def make_engine():
        return Engine(
            params, model=llama, cfg=cfg, num_slots=num_slots,
            block_size=block_size, num_blocks=num_blocks,
            max_model_len=max_model_len, decode_chunk=chunk,
            min_prefill_bucket=32,
        )

    rng = np.random.default_rng(0)
    n_req = 32
    plens = rng.integers(32, 192, size=n_req)
    outs = rng.integers(64, 256, size=n_req)
    prompts = [
        rng.integers(0, cfg.vocab_size, size=int(p)).astype(np.int32)
        for p in plens
    ]
    # Poisson-ish arrivals: inter-arrival gaps in engine ticks.
    arrival = np.cumsum(rng.poisson(1.0, size=n_req))

    # Warm every compiled program (prefill per bucket + the decode chunk)
    # on a throwaway engine; the measured engine reuses the jit cache.
    warm = make_engine()
    wrng = np.random.default_rng(1)
    for p in (32, 64, 128, 192):  # covers every prefill bucket used below
        warm.submit(
            wrng.integers(0, cfg.vocab_size, size=p).astype(np.int32),
            max_new_tokens=4, key=0,
        )
    warm.drain()
    # Compile observatory baseline (docs/observability.md, "Perf
    # plane"): everything below reuses the warm jit cache, so ANY
    # decode-chunk compile from here on is a steady-state recompile —
    # the invariant the engine's perf model rests on, asserted at the
    # end of this bench.
    compiles_before = {
        k: v for k, v in telemetry.counters().items()
        if k.startswith("compile.")
    }

    def run_trace(eng, trace_prompts, trace_outs, trace_arrival):
        peak_util = 0.0
        # The per-tick attribution gauges (docs/observability.md, "Ops
        # plane"), sampled every tick: mean decode-batch occupancy,
        # mean goodput, and the time plane's host/device split over the
        # ticks that actually decoded.
        g_occ = telemetry.gauge("serve.occupancy", engine=eng.engine_id)
        g_good = telemetry.gauge("serve.goodput", engine=eng.engine_id)
        g_host = telemetry.gauge(
            "serve.host_overhead_frac", engine=eng.engine_id
        )
        occ_sum = good_sum = host_sum = 0.0
        decode_ticks = 0
        t0 = time.perf_counter()
        i, tick = 0, 0
        n = len(trace_prompts)
        while (
            i < n or len(eng.scheduler) or eng.stats()["running"]
            or eng.audit_backlog()
        ):
            while i < n and trace_arrival[i] <= tick:
                eng.submit(
                    trace_prompts[i], max_new_tokens=int(trace_outs[i]), key=i
                )
                i += 1
            eng.step()
            tick += 1
            peak_util = max(peak_util, eng.allocator.utilization())
            occ = g_occ.value or 0.0
            if occ > 0:
                decode_ticks += 1
                occ_sum += occ
                good_sum += g_good.value or 0.0
                host_sum += g_host.value or 0.0
        st = eng.stats()
        if decode_ticks:
            st["mean_decode_batch_occupancy"] = round(
                occ_sum / decode_ticks, 4
            )
            st["goodput_tokens_per_s"] = round(good_sum / decode_ticks, 1)
            st["host_overhead_frac"] = round(host_sum / decode_ticks, 4)
        return time.perf_counter() - t0, peak_util, st

    def tick_phase_rows(eng):
        """The time plane's per-tick phase breakdown for one engine
        (docs/observability.md, "Time plane"): per-phase count/total/
        p50/p95 from timeplane.phase_summaries — the one readback over
        the serve.tick_phase_s{engine=,phase=} histogram family."""
        from torchdistx_tpu.telemetry import timeplane

        return {
            phase: {
                "count": summ["count"],
                "total_s": round(summ["sum"], 4),
                "p50_s": round(summ["p50"], 6),
                "p95_s": round(summ["p95"], 6),
            }
            for phase, summ in timeplane.phase_summaries(
                eng.engine_id
            ).items()
        }

    telemetry.drain()  # warm-up records are not the measured trace
    eng = make_engine()
    wall, peak_util, st = run_trace(eng, prompts, outs, arrival)
    headline_phases = tick_phase_rows(eng)
    total_tokens = int(sum(outs))
    # Reconstruct the measured run's own event stream — bench numbers
    # ride the same per-request timeline path as a production trace.
    trace_summary = reconstruct(telemetry.drain()).summary()

    # Prefix-heavy phase (the production shape: ~80% of traffic behind
    # one system prompt): the SAME trace runs against a cache-off and a
    # cache-on engine — hit rate, TTFT p95, and sustained decode read
    # off each, so the cache's effect is a paired comparison on one
    # trace, not a cross-trace guess.
    prng = np.random.default_rng(2)
    system = prng.integers(0, cfg.vocab_size, size=96).astype(np.int32)
    p_prompts = []
    for _ in range(n_req):
        tail = prng.integers(
            0, cfg.vocab_size, size=int(prng.integers(8, 64))
        ).astype(np.int32)
        p_prompts.append(
            np.concatenate([system, tail]) if prng.random() < 0.8 else tail
        )
    p_outs = prng.integers(32, 128, size=n_req)
    p_arrival = np.cumsum(prng.poisson(1.0, size=n_req))
    prefix = {"system_prompt_tokens": 96, "shared_fraction": 0.8}
    for label, cache_on in (("cache_off", False), ("cache_on", True)):
        peng = Engine(
            params, model=llama, cfg=cfg, num_slots=num_slots,
            block_size=block_size, num_blocks=num_blocks,
            max_model_len=max_model_len, decode_chunk=chunk,
            min_prefill_bucket=32, prefix_cache=cache_on,
        )
        p_wall, p_peak, p_st = run_trace(peng, p_prompts, p_outs, p_arrival)
        row = {
            "wall_s": round(p_wall, 3),
            "ttft_p50_s": p_st.get("ttft_p50_s"),
            "ttft_p95_s": p_st.get("ttft_p95_s"),
            "sustained_decode_tokens_per_s": p_st.get("decode_tokens_per_s"),
            "peak_block_utilization": round(p_peak, 4),
            "mean_decode_batch_occupancy": p_st.get(
                "mean_decode_batch_occupancy"
            ),
            "goodput_tokens_per_s": p_st.get("goodput_tokens_per_s"),
            "host_overhead_frac": p_st.get("host_overhead_frac"),
        }
        if cache_on:
            row["prefix_hit_rate"] = round(p_st["prefix_hits"] / n_req, 3)
            row["prefix_hit_tokens"] = p_st["prefix_hit_tokens"]
            row["cow_copies"] = p_st["cow_copies"]
            row["prefix_evictions"] = p_st["prefix_evictions"]
        prefix[label] = row
    off_p95 = prefix["cache_off"].get("ttft_p95_s")
    on_p95 = prefix["cache_on"].get("ttft_p95_s")
    if off_p95 and on_p95:
        prefix["ttft_p95_speedup"] = round(off_p95 / on_p95, 3)

    # Multi-tenant QoS phase (ISSUE 8): a burst tenant dumping its whole
    # backlog at t=0 against a steady tenant submitting higher-priority,
    # deadline-bearing requests — the SAME trace against a FIFO engine
    # (tenant/priority inert) and a QoS engine (weighted fair queueing +
    # priority preemption), so per-tenant TTFT p95 and the steady
    # tenant's deadline-hit rate are a paired comparison.  The deadline
    # is calibrated from one solo steady-sized request on the warm
    # engine: generous for a promptly-served request, hopeless behind
    # the whole burst.
    mrng = np.random.default_rng(3)
    n_burst, n_steady = 24, 8
    b_prompts = [
        mrng.integers(
            0, cfg.vocab_size, size=int(mrng.integers(64, 161))
        ).astype(np.int32)
        for _ in range(n_burst)
    ]
    b_outs = mrng.integers(64, 129, size=n_burst)
    s_prompts = [
        mrng.integers(
            0, cfg.vocab_size, size=int(mrng.integers(32, 65))
        ).astype(np.int32)
        for _ in range(n_steady)
    ]
    s_outs = mrng.integers(32, 65, size=n_steady)
    s_arrival = np.arange(n_steady) * 2  # engine ticks between arrivals

    # Warm the preemption programs against the MEASURED pool shape: the
    # swap gather/scatter jits specialize on (pool shape, page bucket),
    # so drive them directly on a throwaway pool of the same shape, one
    # round per power-of-two bucket a victim's private page count can
    # hit.  A drill engine with a smaller pool would compile for the
    # wrong shape and the measured QoS run would pay first-preemption
    # compile stalls out of its deadlines.
    pool = init_paged_cache(llama, cfg, num_blocks, block_size)
    bucket = 1
    while bucket <= max_model_len // block_size:
        pages = list(range(1, bucket + 1))
        host = swap_out_pages(pool, pages)
        pool = swap_in_pages(pool, host, pages)
        bucket *= 2
    del pool
    # A drop-and-replay resume re-prefills prompt + generated-so-far in
    # one chunk — up to ~288 tokens here, the 512 bucket, which the
    # 32..192 warm prompts above never reach.
    warm2 = make_engine()
    warm2.submit(
        mrng.integers(0, cfg.vocab_size, size=320).astype(np.int32),
        max_new_tokens=4, key=0,
    )
    warm2.drain()

    cal = make_engine()
    t0 = time.perf_counter()
    cal.submit(s_prompts[0], max_new_tokens=int(s_outs[0]), key=0).result()
    unit_s = time.perf_counter() - t0
    deadline_s = max(1.0, 8.0 * unit_s)

    def run_multi_tenant(eng):
        telemetry.drain()
        burst_handles = [
            eng.submit(
                p, max_new_tokens=int(o), key=100 + i, tenant="burst",
                priority=0,
            )
            for i, (p, o) in enumerate(zip(b_prompts, b_outs))
        ]
        steady_handles = []
        i, tick = 0, 0
        while i < n_steady or len(eng.scheduler) or eng.stats()["running"]:
            while i < n_steady and s_arrival[i] <= tick:
                steady_handles.append(
                    eng.submit(
                        s_prompts[i], max_new_tokens=int(s_outs[i]),
                        key=200 + i, tenant="steady", priority=1,
                        deadline_s=deadline_s,
                    )
                )
                i += 1
            eng.step()
            tick += 1
        # Per-tenant numbers from the run's reconstructed timelines (the
        # tenant rides each req.submitted event) — not ad-hoc handle
        # lists: the trace is the single source of latency truth.
        rep = reconstruct(telemetry.drain())
        ttfts = {"burst": [], "steady": []}
        n_seen = {"burst": 0, "steady": 0}
        n_done = {"burst": 0, "steady": 0}
        for tl in rep.requests.values():
            sub = next(
                e for e in tl._sorted() if e["name"] == "req.submitted"
            )
            tenant = (sub.get("attrs") or {}).get("tenant", "default")
            n_seen[tenant] += 1
            if tl.outcome == "finished":
                n_done[tenant] += 1
            if tl.ttft_s is not None:
                ttfts[tenant].append(tl.ttft_s)
        out = {}
        for tenant in ("burst", "steady"):
            row = {"n": n_seen[tenant], "completed": n_done[tenant]}
            if ttfts[tenant]:
                row["ttft_p95_s"] = round(
                    float(np.percentile(ttfts[tenant], 95)), 4
                )
            out[tenant] = row
        out["steady"]["deadline_hit_rate"] = round(
            n_done["steady"] / n_steady, 3
        )
        out["trace_complete"] = not rep.problems()
        st = eng.stats()
        out["preemptions_swap"] = st.get("preemptions_swap", 0)
        out["preemptions_replay"] = st.get("preemptions_replay", 0)
        return out

    multi = {
        "n_burst": n_burst,
        "n_steady": n_steady,
        "steady_deadline_s": round(deadline_s, 3),
        "fifo": run_multi_tenant(make_engine()),
        "qos": run_multi_tenant(
            Engine(
                params, model=llama, cfg=cfg, num_slots=num_slots,
                block_size=block_size, num_blocks=num_blocks,
                max_model_len=max_model_len, decode_chunk=chunk,
                min_prefill_bucket=32, scheduler="qos",
                tenant_weights={"steady": 4.0, "burst": 1.0},
            )
        ),
    }
    # The acceptance number: QoS must not hit FEWER steady deadlines
    # than FIFO on the same trace (it should hit strictly more under
    # any real burst).
    multi["deadline_hit_improvement"] = round(
        multi["qos"]["steady"]["deadline_hit_rate"]
        - multi["fifo"]["steady"]["deadline_hit_rate"],
        3,
    )

    # Audit-overhead phase (ISSUE 14): the SAME headline trace against
    # an engine shadow-auditing at 100% sampling — every completed
    # request re-executes once through the same compiled programs when
    # the queue is quiet.  Paired sustained tok/s plus the wall-clock
    # multiple; the sustained ratio is the acceptance number
    # bench_gate's tolerance band holds (auditing reuses the warm
    # programs, so it also rides the decode-recompile assert below).
    telemetry.drain()
    aeng = Engine(
        params, model=llama, cfg=cfg, num_slots=num_slots,
        block_size=block_size, num_blocks=num_blocks,
        max_model_len=max_model_len, decode_chunk=chunk,
        min_prefill_bucket=32, audit_sample=1.0,
    )
    a_wall, _a_peak, a_st = run_trace(aeng, prompts, outs, arrival)
    assert a_st.get("audit_divergences", 0) == 0, (
        "shadow audit diverged during the bench — determinism broke"
    )
    audit_row = {
        "audit_sample": 1.0,
        "wall_s": round(a_wall, 3),
        "sustained_decode_tokens_per_s": a_st.get("decode_tokens_per_s"),
        "audit_checked": a_st.get("audit_checked"),
        "audit_divergences": a_st.get("audit_divergences"),
        "wall_overhead_x": round(a_wall / wall, 3) if wall else None,
    }
    if st.get("decode_tokens_per_s") and a_st.get("decode_tokens_per_s"):
        audit_row["sustained_ratio"] = round(
            a_st["decode_tokens_per_s"] / st["decode_tokens_per_s"], 3
        )

    # Perf plane (ISSUE 12): per-program compile counts across the
    # measured phases, the steady-state decode-recompile invariant, and
    # the HBM ledger's component attribution.  The decode chunk was
    # compiled by the warm engine; the measured engines share its jit
    # cache, so a nonzero delta here means shape churn leaked into the
    # decode path — exactly what the recompile-storm detector guards
    # live, asserted hard at bench time.
    compile_counts = {
        k: v - compiles_before.get(k, 0)
        for k, v in telemetry.counters().items()
        if k.startswith("compile.count") and v - compiles_before.get(k, 0)
    }
    decode_recompiles = compile_counts.get(
        "compile.count{program=decode_chunk}", 0
    )
    assert decode_recompiles == 0, (
        f"steady-state decode chunk recompiled {decode_recompiles}x "
        "during the measured serving phases (shape leak)"
    )
    hbm_rows = {
        k: v for k, v in telemetry.gauges().items()
        if k.startswith("mem.hbm_bytes")
    }

    tdx_ops.enable_tick_attribution(prev_attr)
    telemetry.configure(**prev_telemetry)
    return {
        "n_requests": n_req,
        "num_slots": num_slots,
        "block_size": block_size,
        "num_blocks": num_blocks,
        "decode_chunk": chunk,
        "total_new_tokens": total_tokens,
        "wall_s": round(wall, 3),
        "e2e_tokens_per_s": round(total_tokens / wall, 1),
        # TTFT/TPOT percentiles read back from the per-engine telemetry
        # histograms (stats() is a view over them since ISSUE 9).
        "sustained_decode_tokens_per_s": st.get("decode_tokens_per_s"),
        "ttft_p50_s": st.get("ttft_p50_s"),
        "ttft_p95_s": st.get("ttft_p95_s"),
        "tpot_p50_s": st.get("tpot_p50_s"),
        "tpot_p95_s": st.get("tpot_p95_s"),
        "peak_block_utilization": round(peak_util, 4),
        # Per-tick utilization attribution (ISSUE 10): how full the
        # decode batch ran, and committed decode tokens per tick-second
        # — the serving analogue of train-side MFU.
        "mean_decode_batch_occupancy": st.get("mean_decode_batch_occupancy"),
        "goodput_tokens_per_s": st.get("goodput_tokens_per_s"),
        # Time plane (ISSUE 15): host vs device split of the tick loop
        # (mean over decoding ticks — near 1 means host-bound, a faster
        # kernel buys nothing) and the per-phase tick decomposition.
        "host_overhead_frac": st.get("host_overhead_frac"),
        "tick_phase_s": headline_phases,
        # The run's own reconstructed timelines (scripts/trace_report.py):
        # every request must reconstruct complete, and the phase totals
        # say where the wall time went.
        "trace": {
            "n_requests": trace_summary["n_requests"],
            "complete": trace_summary["complete"],
            "phase_totals_s": trace_summary["phase_totals_s"],
            "problems": len(trace_summary["problems"]),
        },
        "prefix_heavy": prefix,
        "multi_tenant": multi,
        # Audit plane (ISSUE 14): auditor overhead, sustained tok/s
        # audit on vs off on the same trace.
        "audit": audit_row,
        # Perf plane: what compiled (per program) during the measured
        # phases, the asserted steady-state invariant, and where the
        # device bytes sit (the HBM ledger's component attribution).
        "compile_counts": compile_counts,
        "decode_recompiles_steady": decode_recompiles,
        "hbm_bytes": hbm_rows,
    }


def bench_fleet_failover():
    """Fleet resilience probe: failover + hot-swap cost under load.

    Mixed traffic over a 2-engine :class:`~torchdistx_tpu.fleet
    .FleetRouter`; one engine is killed (device failure + close) at 50%
    of the pulls and a zero-downtime hot swap retires the survivor at
    75%.  Reports completed / failed-typed counts (both failure counts
    must be 0 — the probe injects no deadlines or cancels, so every
    request must complete somewhere), the p95 pull latency of
    failed-over vs clean requests and their delta (the failover tax:
    backoff + re-submit + token-identical replay — measured from
    sequential pulls, so queue position is in both groups' baseline),
    and the hot-swap request-drop count, which must be 0.
    """
    import jax
    import numpy as np

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.fleet import FleetRouter, hot_swap
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.serving import Engine, RequestError

    cfg = llama.LlamaConfig(
        vocab_size=32000, dim=512, n_layers=8, n_heads=8, n_kv_heads=8,
        ffn_dim=2048, max_seq_len=512, remat=False,
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg)

    def make_engine():
        return Engine(
            params, model=llama, cfg=cfg, num_slots=4, block_size=16,
            max_model_len=256, decode_chunk=8, min_prefill_bucket=32,
            handle_preemption=False,
        )

    # Warm the compiled programs on a throwaway engine (shared jit cache).
    warm = make_engine()
    wrng = np.random.default_rng(1)
    for p in (32, 64, 128):
        warm.submit(
            wrng.integers(0, cfg.vocab_size, size=p).astype(np.int32),
            max_new_tokens=4, key=0,
        )
    warm.drain()
    warm.close()

    rng = np.random.default_rng(0)
    n_req = 32
    eng_a, eng_b = make_engine(), make_engine()
    router = FleetRouter([eng_a, eng_b], version="v1", max_hops=4)
    failovers_before = telemetry.counter("fleet.failovers").value
    handles = []
    for i in range(n_req):
        plen = int(rng.integers(16, 97))
        prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)
        mnt = int(rng.integers(32, 97))
        handles.append(router.submit(prompt, max_new_tokens=mnt, key=i))

    eng_c = {"eng": None}
    swap_s = None
    lat_clean, lat_failover = [], []
    n_done = n_failed = 0
    for idx, h in enumerate(handles):
        if idx == n_req // 2:
            for leaf in jax.tree.leaves(eng_a._cache):
                leaf.delete()
            eng_a.close()
            router.poll()
        if idx == (3 * n_req) // 4:
            eng_c["eng"] = make_engine()
            t0 = time.perf_counter()
            hot_swap(router, lambda: eng_c["eng"], version="v2")
            swap_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        try:
            h.result()
            n_done += 1
            (lat_failover if h.hops else lat_clean).append(
                time.perf_counter() - t0
            )
        except RequestError:
            n_failed += 1

    out = {
        "n_requests": n_req,
        "completed": n_done,
        "failed_typed": n_failed,  # must be 0: no deadlines/cancels here
        "hot_swap_dropped": n_failed,  # the acceptance number (must be 0)
        "hot_swap_s": round(swap_s, 3) if swap_s is not None else None,
        "failovers": telemetry.counter("fleet.failovers").value
        - failovers_before,
    }
    if lat_clean:
        out["clean_pull_p95_s"] = round(
            float(np.percentile(lat_clean, 95)), 4
        )
    if lat_failover:
        out["failover_pull_p95_s"] = round(
            float(np.percentile(lat_failover, 95)), 4
        )
    if lat_clean and lat_failover:
        out["failover_added_latency_p95_s"] = round(
            float(np.percentile(lat_failover, 95))
            - float(np.percentile(lat_clean, 95)),
            4,
        )
    # The direct measurement (the fleet.failover_added_s histogram times
    # failure→re-placement per hop, backoff included) alongside the
    # derived pull-latency delta above.
    h = telemetry.histogram("fleet.failover_added_s")
    if h.count:
        out["failover_added_p95_s_hist"] = round(h.percentile(95), 4)
    return out


def bench_migration():
    """Stream-migration probe: what the warm hand-off costs vs the cold
    replay it replaces, and what prefill/decode disaggregation buys the
    decode tier's tail latency.

    Part 1 — identical decoding streams over a 2-engine fleet, two
    arms: (a) every stream is warm-migrated engine→engine mid-decode
    (``router.migrate_stream``: pages shipped, zero recomputed tokens);
    (b) the source engine is killed instead and the streams take the
    cold key-pinned replay.  Reports the per-stream hand-off wall time
    and each arm's consumer-visible p95 pull latency — the
    migration-vs-replay tax docs/fleet.md's failure matrix argues
    about.

    Part 2 — decode interference: p95/max inter-token gap of a chatty
    stream on the decode tier while a 192-token prompt lands, (a)
    prefilled on the SAME engine (fused baseline: the prefill rides the
    decode tick loop) vs (b) prefilled on a ``role="prefill"`` peer and
    warm-migrated in for its decode phase (disaggregated).  Only the
    chat pulls are timed in both arms — per-tier latency, not
    whole-host throughput (one host runs both engines here).
    """
    import jax
    import numpy as np

    from torchdistx_tpu.fleet import FleetRouter
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.serving import Engine, RequestError

    cfg = llama.LlamaConfig(
        vocab_size=32000, dim=512, n_layers=8, n_heads=8, n_kv_heads=8,
        ffn_dim=2048, max_seq_len=512, remat=False,
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg)

    def make_engine(role="mixed"):
        return Engine(
            params, model=llama, cfg=cfg, num_slots=4, block_size=16,
            max_model_len=256, decode_chunk=8, min_prefill_bucket=32,
            handle_preemption=False, role=role,
        )

    warm = make_engine()
    wrng = np.random.default_rng(1)
    for p in (32, 64, 128, 192):
        warm.submit(
            wrng.integers(0, cfg.vocab_size, size=p).astype(np.int32),
            max_new_tokens=4, key=0,
        )
    warm.drain()
    warm.close()

    rng = np.random.default_rng(0)
    n_req = 4  # one per slot: the whole set decodes (and moves) at once
    prompts = [
        rng.integers(
            0, cfg.vocab_size, size=int(rng.integers(16, 97))
        ).astype(np.int32)
        for _ in range(n_req)
    ]
    mnts = [int(rng.integers(32, 97)) for _ in range(n_req)]

    def run_arm(kill):
        eng_a, eng_b = make_engine(), make_engine()
        router = FleetRouter([eng_a, eng_b], version="v1", max_hops=4)
        rid_a = next(
            rid for rid, rep in router._replicas.items()
            if rep.engine is eng_a
        )
        eng_b.detector.observe_tick(50.0)  # pin routing to A
        handles = []
        for i, (p, mnt) in enumerate(zip(prompts, mnts)):
            handles.append(router.submit(p, max_new_tokens=mnt, key=i))
            eng_b.detector.observe_tick(50.0)
        for _ in range(10_000):
            if (
                not len(eng_a.scheduler)
                and eng_a._n_running()
                and eng_a._n_running() == eng_a._n_decoding()
            ):
                break
            eng_a.step()
        hand_off = []
        if kill:
            for leaf in jax.tree.leaves(eng_a._cache):
                leaf.delete()
            eng_a.close()
            router.poll()
        else:
            for slot in list(eng_a.migratable_slots()):
                t0 = time.perf_counter()
                if router.migrate_stream(rid_a, slot):
                    hand_off.append(time.perf_counter() - t0)
        lats, n_done = [], 0
        for h in handles:
            t0 = time.perf_counter()
            try:
                h.result()
                n_done += 1
            except RequestError:
                pass
            lats.append(time.perf_counter() - t0)
        router.close()
        return n_done, lats, hand_off

    n_mig_done, mig_lats, hand_off = run_arm(kill=False)
    n_cold_done, cold_lats, _ = run_arm(kill=True)

    out = {
        "n_streams": n_req,
        # Both arms must complete everything — warm or cold, no stream
        # is ever lost.
        "migrated_completed": n_mig_done,
        "cold_replay_completed": n_cold_done,
        "migrated_pull_p95_s": round(
            float(np.percentile(mig_lats, 95)), 4
        ),
        "cold_replay_pull_p95_s": round(
            float(np.percentile(cold_lats, 95)), 4
        ),
        "migration_saved_p95_s": round(
            float(np.percentile(cold_lats, 95))
            - float(np.percentile(mig_lats, 95)),
            4,
        ),
    }
    if hand_off:
        out["migration_handoff_p95_s"] = round(
            float(np.percentile(hand_off, 95)), 4
        )

    # ---- Part 2: decode-tier tail while a long prompt lands ----
    chat_prompt = rng.integers(0, cfg.vocab_size, size=12).astype(np.int32)
    long_prompt = rng.integers(0, cfg.vocab_size, size=192).astype(np.int32)
    CHAT_NEW, LONG_AT = 48, 8

    def chat_gaps(pull_iter, on_token):
        gaps, last = [], time.perf_counter()
        for i, _tok in enumerate(pull_iter):
            gaps.append(time.perf_counter() - last)
            on_token(i)
            last = time.perf_counter()  # driver work stays untimed
        return gaps

    # (a) fused: the long prefill rides the chat stream's engine.
    eng = make_engine()
    chat = eng.submit(chat_prompt, max_new_tokens=CHAT_NEW, key=100)
    pending = {}

    def fused_on_token(i):
        if i == LONG_AT:
            pending["h"] = eng.submit(
                long_prompt, max_new_tokens=8, key=101
            )

    fused = chat_gaps(chat.tokens(), fused_on_token)
    if "h" in pending:
        while not pending["h"].done:
            eng.step()
    eng.close()

    # (b) disaggregated: the long prompt prefills on the prefill peer
    # and warm-migrates in for its decode phase.
    eng_p, eng_d = make_engine("prefill"), make_engine("decode")
    router = FleetRouter(
        [eng_p, eng_d], version="v1", max_hops=4, long_prompt_tokens=128,
    )
    chat = router.submit(chat_prompt, max_new_tokens=CHAT_NEW, key=100)
    state = {}

    def disagg_on_token(i):
        if i == LONG_AT:
            state["h"] = router.submit(
                long_prompt, max_new_tokens=8, key=101
            )
        elif "h" in state and not state["h"].done:
            eng_p.step()  # the prefill tier does its own work
            router.rebalance()  # decode-phase streams ship over

    disagg = chat_gaps(chat.tokens(), disagg_on_token)
    if "h" in state:
        try:
            state["h"].result()
        except RequestError:
            pass
    router.close()

    # gaps[0] is the chat TTFT (queue + its own prefill) — TPOT starts
    # at the second token in both arms.
    out["fused_chat_tpot_p95_ms"] = round(
        float(np.percentile(fused[1:], 95)) * 1e3, 2
    )
    out["disagg_chat_tpot_p95_ms"] = round(
        float(np.percentile(disagg[1:], 95)) * 1e3, 2
    )
    out["disagg_tpot_saved_p95_ms"] = round(
        out["fused_chat_tpot_p95_ms"] - out["disagg_chat_tpot_p95_ms"], 2
    )
    out["fused_chat_tpot_max_ms"] = round(max(fused[1:]) * 1e3, 2)
    out["disagg_chat_tpot_max_ms"] = round(max(disagg[1:]) * 1e3, 2)
    return out


def bench_autoscale():
    """Elastic fleet probe: what the observe→act loop buys in a flash
    crowd.

    Two arms over identical traffic (same seed, prompts, and token
    budgets): a FIXED single-engine fleet, then the same fleet with the
    signal-driven :class:`~torchdistx_tpu.fleet.Autoscaler` attached.  A
    10× flash crowd with a microsecond-deadline subset lights the SLO
    burn; the probe reports the autonomous time-to-recover (burn edge →
    recovery edge, from the autoscaler's own burn-event log), the peak
    replica count the loop reached, ramp TTFT p95 for both arms and
    their ratio, and the dropped count, which must be 0 — deadline
    misses are typed, anything else the elastic fleet must absorb.
    Scale-in back to one replica is part of the measurement: the probe
    fails the arm if the fleet does not land back at min.
    """
    import jax
    import numpy as np

    from torchdistx_tpu.fleet import AutoscaleConfig, Autoscaler, FleetRouter
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.serving import (
        DeadlineExceeded,
        Engine,
        RequestCancelled,
        RequestError,
    )
    from torchdistx_tpu.telemetry import ops as tdx_ops

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)

    def make_engine():
        return Engine(
            params, model=llama, cfg=cfg, num_slots=4, block_size=8,
            num_blocks=33, max_model_len=64, decode_chunk=4,
            drain_deadline_s=120.0, handle_preemption=False,
        )

    # Warm the compiled programs (shared jit cache across both arms).
    warm = make_engine()
    warm.submit(
        np.arange(8, dtype=np.int32) % cfg.vocab_size,
        max_new_tokens=4, key=0,
    )
    warm.drain()
    warm.close()

    n_crowd = 30

    def arm(autoscale):
        rng = np.random.default_rng(7)
        router = FleetRouter(
            [make_engine()], version="v1", max_hops=4,
            ops_port=0, ops_config=tdx_ops.OpsConfig(
                watchdog=False,
                slo=tdx_ops.SLOConfig(
                    slo=0.9, fast_window_s=2.0, slow_window_s=8.0,
                    burn_threshold=2.0, min_samples=4,
                ),
            ),
        )
        scaler = None
        if autoscale:
            scaler = Autoscaler(
                router, make_engine, version="v1",
                config=AutoscaleConfig(
                    min_replicas=1, max_replicas=3, fast_ticks=2,
                    slope_window=4, slope_high=3.0, slow_ticks=6,
                    scale_out_cooldown=4, scale_in_cooldown=6,
                    queue_low_per_replica=1.0,
                ),
            )

        handles, doomed, t_submit = [], set(), {}
        for i in range(n_crowd):
            plen = int(rng.integers(3, 14))
            prompt = rng.integers(0, cfg.vocab_size, size=plen).astype(
                np.int32
            )
            d = None
            if rng.random() < 0.3:
                d = 1e-6  # typed misses that light the burn
            h = router.submit(
                prompt, max_new_tokens=int(rng.choice((4, 8, 12))),
                key=i, deadline_s=d,
            )
            handles.append(h)
            if d is not None:
                doomed.add(id(h))
            t_submit[id(h)] = time.perf_counter()

        ttfts, dropped, typed, done = [], 0, 0, 0
        peak = 1
        gens = [(h, h.tokens(), True) for h in handles]
        pulls = 0
        while gens:
            nxt = []
            for h, g, first in gens:
                try:
                    next(g)
                    if first and id(h) not in doomed:
                        ttfts.append(
                            time.perf_counter() - t_submit[id(h)]
                        )
                    nxt.append((h, g, False))
                except StopIteration:
                    done += 1
                except RequestError as e:
                    if isinstance(e, (DeadlineExceeded, RequestCancelled)):
                        typed += 1
                    else:
                        dropped += 1
                pulls += 1
                if scaler is not None and pulls % 8 == 0:
                    scaler.tick()
                    peak = max(peak, len(router.replicas()))
            gens = nxt

        out = {
            "completed": done,
            "deadline_typed": typed,
            "dropped": dropped,  # the acceptance number (must be 0)
            "ramp_ttft_p95_s": round(
                float(np.percentile(ttfts, 95)), 4
            ) if ttfts else None,
        }
        if scaler is not None:
            # Recovery: trickle good traffic until the burn clears.
            t0 = time.perf_counter()
            k = 10_000
            while scaler.recoveries < 1:
                if time.perf_counter() - t0 > 60.0:
                    out["recover_timeout"] = True
                    break
                trio = [
                    router.submit(
                        rng.integers(0, cfg.vocab_size, size=6).astype(
                            np.int32
                        ),
                        max_new_tokens=4, key=k + j,
                    )
                    for j in range(3)
                ]
                k += 3
                for h in trio:
                    for _ in h.tokens():
                        pass
                scaler.tick()
                time.sleep(0.2)
            edges = {}
            for t, tenant, burning in scaler.burn_events:
                edges.setdefault(burning, t)
            if True in edges and False in edges:
                out["time_to_recover_s"] = round(
                    edges[False] - edges[True], 3
                )
            # Quiet down: the loop must land back at min replicas.
            t0 = time.perf_counter()
            while (
                len(router.replicas()) > scaler.config.min_replicas
                and time.perf_counter() - t0 < 120.0
            ):
                scaler.tick()
                router.step()
                time.sleep(0.02)
            out["landed_at_min"] = (
                len(router.replicas()) == scaler.config.min_replicas
            )
            out["peak_replicas"] = peak
            out["scale_outs"] = scaler.scale_outs
            out["scale_ins"] = scaler.scale_ins
            scaler.close()
        router.close()
        return out

    fixed = arm(autoscale=False)
    auto = arm(autoscale=True)
    out = {
        "n_requests": n_crowd,
        "fixed": fixed,
        "autoscaled": auto,
        "dropped": fixed["dropped"] + auto["dropped"],  # must be 0
        "time_to_recover_s": auto.get("time_to_recover_s"),
        "peak_replicas": auto.get("peak_replicas"),
        "ramp_ttft_p95_s": auto.get("ramp_ttft_p95_s"),
    }
    if fixed.get("ramp_ttft_p95_s") and auto.get("ramp_ttft_p95_s"):
        out["ttft_p95_vs_fixed"] = round(
            auto["ramp_ttft_p95_s"] / fixed["ramp_ttft_p95_s"], 3
        )
    return out


def bench_models():
    """Model-plane probe: many models on one engine's page pool.

    One engine serves its own weights plus three deferred-init pool
    models (same geometry, different seeds) with ``max_resident=2`` —
    every cold demand past the budget thrashes the LRU weight eviction,
    so the probe prices exactly what the model plane trades: a
    materialize stall on first (or re-warmed) demand against near-zero
    HBM for cold models.  Reported: cold TTFT per model (includes the
    stall), warm TTFT p95 over a mixed four-model wave, the materialize
    stall p95 from the pool's own clock, eviction count, the decode
    recompile delta across models (must be 0 — same-geometry models
    share the one compiled decode chunk), and the n=4 parallel-sampling
    page amplification vs a solo request (prompt pages are shared via
    the fork donor; only divergence CoW-copies).
    """
    import jax
    import numpy as np

    from torchdistx_tpu import telemetry
    from torchdistx_tpu.models import llama
    from torchdistx_tpu.serving import Engine, ModelPool

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)

    def seeded(seed):
        def materialize():
            return llama.init_params(jax.random.PRNGKey(seed), cfg)
        return materialize

    pool = ModelPool(max_resident=2)
    for i, tag in enumerate(("m1", "m2", "m3"), start=1):
        pool.register(
            tag, model=llama, cfg=cfg, materialize=seeded(i),
            model_version=f"{tag}@v1",
        )
    eng = Engine(
        params, model=llama, cfg=cfg, num_slots=8, block_size=8,
        num_blocks=81, max_model_len=64, decode_chunk=4,
        handle_preemption=False, temperature=1.0, top_k=40,
        model_pool=pool,
    )
    rng = np.random.default_rng(3)

    def prompt(plen):
        return rng.integers(0, cfg.vocab_size, size=plen).astype(np.int32)

    def ttft(h):
        t0 = time.perf_counter()
        gen = h.tokens()
        next(gen)
        dt = time.perf_counter() - t0
        for _ in gen:
            pass
        return dt

    try:
        # Cold pass: first demand per tag pays materialize + compile.
        # The default model's weights are resident, so its cold TTFT is
        # the compile-only baseline the stall reads against.
        cold = {}
        for tag in (None, "m1", "m2", "m3"):
            cold[tag or "default"] = ttft(
                eng.submit(prompt(8), max_new_tokens=4, key=0, model=tag)
            )

        c0 = {
            k: v
            for k, v in telemetry.snapshot()["counters"].items()
            if "compile.count" in k and "decode" in k
        }

        # Warm wave: mixed four-model traffic.  m3 displaced one of
        # m1/m2 during the cold pass, so round-robin demand here keeps
        # re-warming evicted weights — warm p95 includes those stalls.
        warm = []
        tags = (None, "m1", "m2", "m3")
        for i in range(24):
            warm.append(ttft(eng.submit(
                prompt(int(rng.integers(4, 16))),
                max_new_tokens=int(rng.choice((4, 8))),
                key=100 + i, model=tags[i % 4],
            )))

        c1 = {
            k: v
            for k, v in telemetry.snapshot()["counters"].items()
            if "compile.count" in k and "decode" in k
        }
        decode_recompiles = sum(c1.values()) - sum(c0.values())

        # Fork amplification: n=4 over a 4-page prompt vs one solo.
        solo_h = eng.submit(prompt(32), max_new_tokens=8, key=7)
        solo_peak = 0
        while not solo_h.done:
            eng.step()
            solo_peak = max(solo_peak, eng.allocator.num_in_use)
        fork_h = eng.submit(prompt(32), max_new_tokens=8, key=7, n=4)
        fork_peak = 0
        while not all(s.done for s in fork_h.siblings):
            eng.step()
            fork_peak = max(fork_peak, eng.allocator.num_in_use)
        for s in fork_h.siblings:
            s.result()

        stats = pool.stats()
        out = {
            "n_models": 1 + stats["n_registered"],
            "cold_ttft_s": {k: round(v, 4) for k, v in cold.items()},
            "warm_ttft_p95_s": round(float(np.percentile(warm, 95)), 4),
            "materialize_p95_s": stats["materialize_p95_s"],
            "evictions": sum(
                m["evictions"] for m in stats["models"].values()
            ),
            "decode_recompiles": decode_recompiles,  # must be 0
            "fork_n4_peak_pages": fork_peak,
            "solo_peak_pages": solo_peak,
            "fork_page_amplification_vs_4x": round(
                fork_peak / (4 * solo_peak), 3
            ) if solo_peak else None,
        }
        eng.drain()
        return out
    finally:
        eng.close()


def bench_flash_attention(s=16384, b=1, h=8, d=128):
    """Long-context flash attention fwd+bwd at S=16k on one chip.

    The kernel streams KV through VMEM scratch (O(bq·d + bkv·d) VMEM at any
    S); this probe is the perf ratchet for the long-context regime.  Sync is
    via host transfer (block_until_ready alone does not reliably block on
    the tunneled backend).
    """
    import jax
    import jax.numpy as jnp

    from torchdistx_tpu.ops.pallas.flash_attention import flash_attention

    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(jax.random.fold_in(key, i), (b, s, h, d),
                          dtype=jnp.bfloat16)
        for i in range(3)
    )

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()

    # All three grads, so neither backward kernel is dead-code-eliminated
    # out of the timed program.
    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    gq, gk, gv = step(q, k, v)
    float(gq.astype(jnp.float32).sum())
    # Iterations chain on device (grads feed back into the inputs) with ONE
    # host sync at the end: per-iteration syncs would measure tunnel
    # round-trips, not kernel time.  Min of 3 runs: single windows can
    # read 20-30% slow when the tunnel drifts.
    n = 20
    dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        x, y, z = q, k, v
        for _ in range(n):
            gq, gk, gv = step(x, y, z)
            x = gq.astype(x.dtype)
            y = gk.astype(y.dtype)
            z = gv.astype(z.dtype)
        float(x.astype(jnp.float32).sum())
        dt = min(dt, (time.perf_counter() - t0) / n)
    # Causal fwd QK^T+PV = 2·2·b·h·s²·d·½; bwd ≈ 2.5× fwd (dq,dk,dv + p
    # recompute).
    flops = 3.5 * 2.0 * b * h * s * s * d
    kind = jax.devices()[0].device_kind
    peak = _peak_tflops(kind)
    out = {
        "seq_len": s,
        "fwd_bwd_ms": round(dt * 1e3, 2),
        "tflops_per_s": round(flops / dt / 1e12, 2),
    }
    if peak is not None:
        out["attn_mfu"] = round(flops / dt / (peak * 1e12), 4)
    return out


def main():
    import jax
    import torch.nn as nn

    from torchdistx_tpu import telemetry

    jax.block_until_ready(jax.device_put(1.0))  # backend warm-up

    # Dispatch warm-up: the first op recorded under deferred init triggers
    # torch's lazy imports (dynamo/distributed/sympy, ~1.5s).  That is
    # torch's one-time process cost, not this framework's per-op record
    # cost; warm it so fake_construction_s measures the latter.
    from torchdistx_tpu.deferred_init import deferred_init

    deferred_init(nn.Linear, 8, 8)

    from torchdistx_tpu.models.resnet_torch import resnet50

    # Measurement order is deliberate (measured, round 4): big host→device
    # transfers degrade the tunneled backend for minutes, so (a) each
    # config's OURS and EAGER run ADJACENTLY — both sides of a ratio see
    # the same tunnel state (running all eager baselines at the end was
    # measured to inflate eager by 5-20×, flattering us dishonestly), and
    # (b) configs run smallest-transfer-first (resnet 0.1 GB → small
    # 0.65 GB → XL 3.2 GB), so the XL transfer — the big degrader — lands
    # after every smaller config is done.  The compute probes
    # (train/flash/decode) chain iterations with one end sync and were
    # measured robust to post-XL tunnel state; the cold subprocess runs
    # last, in r03's position, keeping the ratchet comparable.
    resnet = bench_materialize_ours(resnet50, dtype=torch.float32)
    bench_materialize_eager(resnet50, dtype=torch.float32, out=resnet)
    small = bench_materialize_ours(GPT2Small, dtype=torch.float32)
    bench_materialize_eager(GPT2Small, dtype=torch.float32, out=small)
    xl = bench_materialize_ours(GPT2XL, dtype=torch.bfloat16)
    bench_materialize_eager(GPT2XL, dtype=torch.bfloat16, out=xl)
    try:
        train = bench_train_step()
    except Exception as e:  # noqa: BLE001 — report, don't sink the bench
        train = {"error": f"{type(e).__name__}: {e}"}
    try:
        flash16k = bench_flash_attention()
    except Exception as e:  # noqa: BLE001
        flash16k = {"error": f"{type(e).__name__}: {e}"}
    try:
        gen = bench_generate()
    except Exception as e:  # noqa: BLE001
        gen = {"error": f"{type(e).__name__}: {e}"}
    try:
        serving = bench_serving()
        # The serving ratchet reads directly against the solo-generate
        # row it shares hardware (and a model config) with.
        if "error" not in gen and gen.get("e2e_tokens_per_s"):
            sus = serving.get("sustained_decode_tokens_per_s")
            if sus:
                serving["vs_generate_e2e"] = round(
                    sus / gen["e2e_tokens_per_s"], 3
                )
    except Exception as e:  # noqa: BLE001
        serving = {"error": f"{type(e).__name__}: {e}"}
    try:
        fleet = bench_fleet_failover()
    except Exception as e:  # noqa: BLE001
        fleet = {"error": f"{type(e).__name__}: {e}"}
    try:
        autoscale = bench_autoscale()
    except Exception as e:  # noqa: BLE001
        autoscale = {"error": f"{type(e).__name__}: {e}"}
    try:
        migration = bench_migration()
    except Exception as e:  # noqa: BLE001
        migration = {"error": f"{type(e).__name__}: {e}"}
    try:
        model_plane = bench_models()
    except Exception as e:  # noqa: BLE001
        model_plane = {"error": f"{type(e).__name__}: {e}"}
    # Second flash probe, minutes after the first (same compiled program,
    # deterministic work): tunnel windows last minutes, so two temporally
    # separated samples of the same measurement keep one bad window from
    # defining the artifact.  min = the best observed hardware rate.
    try:
        flash2 = bench_flash_attention()
    except Exception as e:  # noqa: BLE001
        flash2 = {"error": f"{type(e).__name__}: {e}"}
    if "error" not in flash2 and (
        "error" in flash16k
        or flash2["fwd_bwd_ms"] < flash16k["fwd_bwd_ms"]
    ):
        # Keep the first probe's error when both fail (it is the
        # earlier, usually more informative one).
        flash16k = flash2
    cold = bench_cold_uncached()
    # Honest cold ratios: first-ever-run (fresh process, all caches off)
    # against the same eager baselines measured above.
    if "error" not in cold:
        for label, eager_s in (
            ("gpt2xl_bf16", xl["eager_init_transfer_s"]),
            ("gpt2small_f32", small["eager_init_transfer_s"]),
            ("resnet50_f32", resnet["eager_init_transfer_s"]),
        ):
            if label in cold:
                cold[f"{label}_vs_baseline"] = round(
                    eager_s / cold[label], 3
                )

    print(
        json.dumps(
            {
                "metric": "deferred_init_materialize_gpt2xl_bf16_1chip",
                "value": xl["ours_s"],
                "unit": "s",
                "vs_baseline": xl["vs_baseline"],
                "details": {
                    "gpt2xl_1p6b_bf16": xl,
                    "gpt2small_124m_f32": small,
                    "resnet50_25m_f32": resnet,
                    "train_step_llama_350m_pallas": train,
                    "flash_attention_16k": flash16k,
                    "generate_llama_350m_decode": gen,
                    "serving_llama_350m_continuous": serving,
                    "fleet_failover": fleet,
                    "fleet_autoscale": autoscale,
                    "fleet_migration": migration,
                    "model_plane": model_plane,
                    "cold_uncached_s": cold,
                    "peak_rss_mb": round(_rss_mb(), 1),
                    "device": str(jax.devices()[0]),
                    # Whole-process counters/gauges from the telemetry
                    # registry — the numbers the system measured about
                    # itself (docs/observability.md has the catalog).
                    "telemetry": {
                        "counters": telemetry.counters(),
                        "gauges": telemetry.gauges(),
                    },
                },
            }
        )
    )


if __name__ == "__main__":
    main()
