"""Fake tensors: metadata-only tensors that claim a real (possibly absent) device.

TPU-native rebuild of the reference's fake-tensor feature
(/root/reference/src/cc/torchdistx/fake.cc, src/python/torchdistx/fake.py).

Design
------
The reference implements a C++ ``TensorImpl`` subclass with no storage
(``FakeTensorImpl``, fake.cc:73-245) plus a boxed dispatch-key fallback
(``FakeHandler``, fake.cc:256-548) that diverts every op to the *meta* backend
and converts the meta results back into fake tensors, and a device-guard spoof
so fake CUDA tensors can exist on CUDA-less hosts (fake.cc:554-586).

Here the same capability is built on the idiomatic seams this stack offers:

* ``torch.Tensor._make_wrapper_subclass`` creates a storage-less tensor that
  *reports* an arbitrary device — the ``FakeTensorImpl`` analog.  Each fake
  carries a shadow **meta** tensor used for all shape/stride/dtype dispatch
  (the analog of fake.cc:69-72's meta shadow).
* ``__torch_dispatch__`` (subclass + mode) is the interception seam — the
  analog of the boxed ``Fake``-key fallback.  Ops on fakes run on the meta
  shadows; factory ops under ``fake_mode()`` are redirected to the meta
  backend and their outputs wrapped as fakes claiming the requested device
  (fake.cc:419-432's output-device rules).
* The device-guard spoof becomes trivial: claiming ``cuda``/``tpu`` devices
  requires no guard because the wrapper subclass never touches a backend.
  ``tpu`` devices are made nameable by renaming torch's ``privateuse1``
  backend — the analog of installing ``NoOpDeviceGuardImpl`` (fake.cc:556-572):
  we "lie to PyTorch" in the same way, just through a supported hook.

The TPU story: a model faked on ``tpu:k`` devices is later materialized by the
JAX backend (:mod:`torchdistx_tpu.materialize`) directly as sharded
``jax.Array`` leaves on a ``jax.sharding.Mesh`` — no host round-trip.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, Optional

import torch
import torch.utils._pytree as pytree
from torch.utils._mode_utils import no_dispatch
from torch.utils._python_dispatch import TorchDispatchMode

from . import _native

__all__ = [
    "FakeTensor",
    "fake_mode",
    "is_fake",
    "meta_like",
    "current_fake_mode",
]

_tls = threading.local()


def _ensure_tpu_device_registered() -> None:
    """Make ``torch.device("tpu")`` nameable on hosts with no TPU torch backend.

    Analog of the reference's fake-CUDA device-guard spoof
    (fake.cc:556-572): it installs a no-op device guard so PyTorch accepts
    CUDA tensors on CUDA-less hosts; we rename the ``privateuse1`` backend so
    PyTorch accepts ``tpu`` as a device string.  No kernels are registered —
    fake tensors never dispatch to their claimed device.
    """
    try:
        torch.utils.rename_privateuse1_backend("tpu")
    except (RuntimeError, AttributeError):
        # Already renamed (possibly by us) or unsupported; if "tpu" parses we
        # are fine either way.
        pass
    if getattr(torch, "tpu", None) is None:
        # Factory bindings lazy-init the claimed device's backend module; a
        # no-op module is the exact spirit of the reference's
        # NoOpDeviceGuardImpl ("we basically lie to PyTorch", fake.cc:556-572).
        import types

        spoof = types.ModuleType("torch.tpu")
        spoof.is_available = lambda: True
        spoof.is_initialized = lambda: True
        spoof._lazy_init = lambda: None
        spoof.device_count = lambda: 0
        spoof.current_device = lambda: 0
        spoof._is_in_bad_fork = lambda: False
        spoof.manual_seed_all = lambda seed: None
        try:
            torch._register_device_module("tpu", spoof)
        except RuntimeError:
            pass


# The rename must precede any `torch.device("tpu")` string parse, which
# happens inside factory bindings before our handler runs — register at
# import, like the reference registers its dispatch fallbacks at library
# load (fake.cc:546-548, §3.5 of SURVEY.md).
_ensure_tpu_device_registered()


@contextlib.contextmanager
def _suppress_cuda_lazy_init():
    """Suppress CUDA lazy initialization while a fake mode is active.

    Analog of the reference's ``set_requires_cuda_init(false)``
    (_C/fake.cc:18-36): factory bindings eagerly call
    ``torch.cuda._lazy_init`` for ``device="cuda"`` *before* dispatch
    reaches our interception seam, which would fail on CUDA-less hosts.
    The op itself never touches CUDA — the mode diverts it to meta.
    """
    if torch.cuda.is_available():
        yield
        return
    prev = torch.cuda._lazy_init
    torch.cuda._lazy_init = lambda: None
    try:
        yield
    finally:
        torch.cuda._lazy_init = prev


class FakeTensor(torch.Tensor):
    """A tensor with no storage that claims to live on ``fake_device``.

    Analog of ``FakeTensorImpl`` (fake.cc:73-245): holds a shadow meta tensor
    (``_meta``) used for dispatch, reports the claimed device, and carries a
    per-subsystem side-data dict ``_slots`` — the analog of the reference's
    per-dispatch-key ``dispatch_data`` map (fake.cc:118-121) that deferred
    init uses to attach its graph record.
    """

    _meta: torch.Tensor
    fake_device: torch.device
    _slots: Dict[str, Any]

    @staticmethod
    def __new__(cls, meta: torch.Tensor, fake_device: torch.device):
        assert meta.device.type == "meta", "FakeTensor shadow must be a meta tensor"
        r = torch.Tensor._make_wrapper_subclass(  # type: ignore[attr-defined]
            cls,
            meta.shape,
            strides=meta.stride(),
            storage_offset=meta.storage_offset(),
            dtype=meta.dtype,
            layout=meta.layout,
            device=fake_device,
            requires_grad=meta.requires_grad,
        )
        r._meta = meta
        r.fake_device = fake_device
        r._slots = {}
        return r

    # --- Tensor.data interception — the ProxyVariableHooks analog ---------
    # The reference swaps autograd's global VariableHooksInterface for a
    # recording proxy because `Tensor.data` reads/writes bypass the
    # dispatcher (deferred_init.cc:888-1127).  Here the read path
    # (`variable_data`) already flows through the wrapper subclass and the
    # dispatched ops on the alias record normally; only the *setter*
    # (`set_data`) needs interception: it swaps the TensorImpl underneath
    # the Python object, which would orphan the fake's meta shadow and
    # deferred-init record (the new tensor's record would be silently lost).

    @property
    def data(self):
        return torch.Tensor.data.__get__(self)

    @data.setter
    def data(self, new):
        if not isinstance(new, FakeTensor):
            # A real tensor assigned into a fake param: lift it onto the
            # tape as `aten.clone(new)` (external-guarded), the synthetic-op
            # treatment of deferred_init.cc:905-947.
            from . import _tape

            tape = _tape.current_tape()
            if tape is None:
                raise RuntimeError(
                    "Cannot assign a real tensor to `.data` of a fake "
                    "tensor outside of a deferred-init context: the "
                    "assignment could not be recorded for materialization."
                )
            with no_dispatch():
                meta = torch.empty_strided(
                    new.shape, new.stride(), dtype=new.dtype, device="meta"
                )
            lifted = FakeTensor(meta, self.fake_device)
            _tape.record_op(
                tape, torch.ops.aten.clone.default, (new,), {}, [lifted]
            )
            new = lifted
        # Swap the impl (shape/dtype may change — set_data semantics), then
        # rebind the Python-side shadow state to the new tensor's.
        torch.Tensor.data.__set__(self, new)
        self._meta = new._meta
        self._slots = dict(new._slots)
        self.fake_device = new.fake_device

    # Like the reference's repr patch (fake.py:15-40) but scoped to the
    # subclass instead of monkey-patching torch.Tensor.__repr__ globally.
    def __repr__(self, *, tensor_contents=None):  # noqa: D105
        grad = ", requires_grad=True" if self.requires_grad else ""
        return (
            f"tensor(..., device='{self.fake_device}', size={tuple(self.shape)}, "
            f"dtype={self.dtype}{grad}, fake=True)"
        )

    __str__ = __repr__

    @classmethod
    def __torch_dispatch__(cls, func, types, args=(), kwargs=None):
        # Ops touching fake tensors outside of any active mode still hit this
        # seam — the analog of the Fake dispatch key living in the *tensor's*
        # key set (fake.cc:129-150), not only in TLS.
        return _fake_handler(func, args, kwargs or {}, default_device=None)


class _FakeMode(TorchDispatchMode):
    """Catch-all interception while ``fake_mode()`` is active.

    Analog of ``enterFakeMode`` TLS-including the ``Fake`` key
    (fake.cc:595-605): with the mode pushed, *factory* ops (no tensor args)
    are also intercepted and produce fakes.
    """

    def __init__(self, default_device: Optional[torch.device] = None):
        super().__init__()
        self.default_device = default_device

    def __torch_dispatch__(self, func, types, args=(), kwargs=None):
        return _fake_handler(
            func, args, kwargs or {}, default_device=self.default_device
        )


def _flat_leaves(obj):
    """Flatten containers to leaves — native stack walk when available.

    The per-op hot path (this module + the deferred-init recorder) runs
    three tree traversals per dispatched op; the native module
    (src/cc/tdx_core/stack.cc, the stack_utils.cc analog) does the container
    recursion in C.
    """
    s = _native.stack_ops()
    if s is not None:
        return s.leaves(obj)
    return pytree.tree_leaves(obj)


def _convert_tensors(obj, fn, *, strict: bool = False):
    """Map ``fn`` over every tensor leaf of ``obj`` (copy-on-write).

    Non-tensor leaves pass through untouched; with ``strict`` the native
    walker additionally validates leaves against the immutable domain and
    signals fallback for anything else.  Falls back to ``pytree.tree_map``
    (applying ``fn`` to tensor leaves only) for exotic containers.
    """
    s = _native.stack_ops()
    if s is not None:
        try:
            return s.convert(obj, fn, strict)
        except s.Fallback:
            pass
    if strict:
        raise _StrictFallback
    return pytree.tree_map(
        lambda a: fn(a) if isinstance(a, torch.Tensor) else a, obj
    )


class _StrictFallback(Exception):
    """Raised when a strict convert must be retried by the caller's own
    full-domain path (the recorder's deep-copy validation)."""


def _tensor_to_meta(t: torch.Tensor) -> torch.Tensor:
    # Real (non-fake) tensor mixed into a faked op: use its metadata only.
    with no_dispatch():
        return torch.empty_strided(
            t.shape, t.stride(), dtype=t.dtype, device="meta"
        ).requires_grad_(t.requires_grad and t.is_leaf)


def _fake_handler(func, args, kwargs, *, default_device: Optional[torch.device]):
    """The per-op handler — analog of ``FakeHandler::run`` (fake.cc:318-536).

    Device rules follow fake.cc:419-432: explicit ``device`` argument wins,
    else the first fake argument's claimed device, else the mode's default
    claimed device (for factories), else the op runs for real untouched
    (fake.cc:534-536).
    """
    flat_args = _flat_leaves((args, kwargs))
    fakes = [a for a in flat_args if isinstance(a, FakeTensor)]
    has_tensor_args = any(isinstance(a, torch.Tensor) for a in flat_args)

    device_kwarg = kwargs.get("device")
    if device_kwarg is not None:
        out_device = torch.device(device_kwarg)
        if out_device.type == "tpu":
            _ensure_tpu_device_registered()
    elif fakes:
        out_device = fakes[0].fake_device
        for f in fakes[1:]:
            if f.fake_device != out_device:
                raise RuntimeError(
                    f"Cannot run '{func}' with fake tensors on mixed devices "
                    f"({out_device} and {f.fake_device})."
                )
    elif default_device is not None and not has_tensor_args:
        # The mode's default claimed device applies to *factories* only —
        # an op over real tensors must run for real (fake.cc:534-536), not
        # be hijacked onto meta with its data discarded.
        out_device = torch.device(default_device)
    else:
        out_device = None

    if out_device is None and not fakes:
        # Pure real-tensor op under the mode: forward untouched
        # (fake.cc:534-536).
        return func(*args, **kwargs)
    if out_device is None:
        out_device = torch.device("cpu")
    if out_device.type == "meta":
        # User explicitly asked for meta — not our business to wrap.
        return func(*args, **kwargs)

    # Swap fake args for their meta shadows (fake.cc:434-460), keeping an
    # identity map so in-place ops hand back the original fake wrapper — the
    # analog of the ``meta_to_fake_`` map (fake.cc:507-523).
    meta_to_fake: Dict[int, FakeTensor] = {}

    def unwrap(a):
        if isinstance(a, FakeTensor):
            meta_to_fake[id(a._meta)] = a
            return a._meta
        if isinstance(a, torch.Tensor) and a.device.type != "meta":
            return _tensor_to_meta(a)
        return a

    u_args, u_kwargs = _convert_tensors((tuple(args), dict(kwargs)), unwrap)
    if u_kwargs.get("device") is not None:
        # Redispatch the factory to the meta backend (fake.cc:466-489).
        # Copy first: the copy-on-write convert may have returned the input
        # dict itself when no tensor leaf changed.
        u_kwargs = dict(u_kwargs)
        u_kwargs["device"] = torch.device("meta")

    try:
        out = func(*u_args, **u_kwargs)
    except NotImplementedError as e:
        # Friendly error like fake.cc:484-486.
        raise RuntimeError(
            f"The operator '{func}' has no meta-backend support, so it cannot "
            f"be run with fake tensors."
        ) from e

    def wrap(o):
        if isinstance(o, torch.Tensor) and o.device.type == "meta":
            existing = meta_to_fake.get(id(o))
            if existing is not None:
                return existing
            return FakeTensor(o, out_device)
        return o

    return _convert_tensors(out, wrap)


@contextlib.contextmanager
def fake_mode(*, fake_cuda: bool = False, device: Optional[Any] = None):
    """Context manager within which newly constructed tensors are fake.

    Analog of the reference's ``fake_mode`` (fake.py:44-56).  ``fake_cuda``
    is honored for API parity (it makes ``device="cuda"`` claims legal on
    CUDA-less hosts, which the wrapper-subclass design gives us for free).
    ``device`` optionally sets the claimed device for factory calls that do
    not pass one — e.g. ``fake_mode(device="tpu")`` builds a whole model
    "on TPU" with zero allocation anywhere.
    """
    if device is not None:
        device = torch.device(device)
        if device.type == "tpu":
            _ensure_tpu_device_registered()
    mode = _FakeMode(default_device=device)
    mode_stack = getattr(_tls, "mode_stack", None)
    if mode_stack is None:
        mode_stack = _tls.mode_stack = []
    mode_stack.append(mode)
    try:
        with contextlib.ExitStack() as stack:
            stack.enter_context(_suppress_cuda_lazy_init())
            if device is not None:
                # Route the claimed default through torch's own DeviceContext
                # so factory calls arrive at the handler already carrying it
                # (the binding otherwise fills in `cpu` before dispatch).
                stack.enter_context(torch.device(device))
            stack.enter_context(mode)
            yield mode
    finally:
        mode_stack.pop()


def current_fake_mode() -> Optional[_FakeMode]:
    stack = getattr(_tls, "mode_stack", None)
    return stack[-1] if stack else None


def is_fake(tensor: torch.Tensor) -> bool:
    """True if ``tensor`` is fake — analog of fake.py:59-66 / fake.cc:625."""
    return isinstance(tensor, FakeTensor)


def meta_like(fake: torch.Tensor) -> torch.Tensor:
    """Detached meta clone of a fake tensor — analog of fake.py:69-82,
    fake.cc:640-648 (``FakeTensor::toMeta``)."""
    if not is_fake(fake):
        raise ValueError("`fake` is not a fake tensor.")
    with no_dispatch():
        return fake._meta.detach().clone()
