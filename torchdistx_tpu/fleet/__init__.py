"""Fleet layer: health-aware routing, typed-error failover, hot swap.

One :class:`~torchdistx_tpu.serving.engine.Engine` is a single point of
failure, and upgrading its weights means downtime.  This package fronts
N engine replicas with a :class:`~.router.FleetRouter` that speaks the
same ``submit()/tokens()`` streaming API:

* :mod:`.router` — least-estimated-TTFT routing over the per-engine
  health/TTFT hooks (OVERLOADED avoided, DRAINING/STOPPED excluded),
  failover of ``retryable`` typed errors to peers under a per-request
  hop budget with :class:`~torchdistx_tpu.resilience.retry.RetryPolicy`
  backoff, version-pinned mid-stream replays (token-identical, prefix
  verified), and typed — never silent — failure when no replica can
  take a request;
* :mod:`.hot_swap` — zero-downtime weight upgrade: the next version is
  recorded with :func:`~torchdistx_tpu.deferred_init.deferred_init`
  (zero allocation) and materialized into a standby engine while the
  old version serves, admission flips at a chunk boundary, the old
  engines drain gracefully and retire — no dropped requests, no stream
  ever mixing two versions;
* :mod:`.autoscale` — the observe→act control loop: SLO burn signals,
  occupancy, and a queue-depth-slope predictor drive elastic scale-out
  (engine factory → ``add_replica``) and scale-in (``begin_drain`` →
  reap) under hysteresis bands, cooldowns, and min/max bounds, with
  latched-diverging replicas replaced rather than counted as capacity.

Quick start::

    from torchdistx_tpu.fleet import FleetRouter, hot_swap

    router = FleetRouter([make_engine(), make_engine()], version="v1")
    h = router.submit(prompt_ids, max_new_tokens=128, key=0)
    for tok in h.tokens():      # streams; fails over transparently
        print(tok)

    hot_swap(router, make_v2_engine, version="v2")  # zero requests dropped

Telemetry: ``fleet.*`` counters/gauges and the ``fleet.swap`` span
(docs/observability.md).  Full design: docs/fleet.md.
"""

from .autoscale import Autoscaler, AutoscaleConfig  # noqa: F401
from .hot_swap import hot_swap, materialize_standby  # noqa: F401
from .router import (  # noqa: F401
    FailoverDiverged,
    FailoverExhausted,
    FleetHandle,
    FleetRouter,
    NoReplicaAvailable,
    Replica,
)

__all__ = [
    "AutoscaleConfig",
    "Autoscaler",
    "FailoverDiverged",
    "FailoverExhausted",
    "FleetHandle",
    "FleetRouter",
    "NoReplicaAvailable",
    "Replica",
    "hot_swap",
    "materialize_standby",
]
