"""Health-aware multi-engine router with typed-error failover.

One :class:`~torchdistx_tpu.serving.engine.Engine` is a single point of
failure and a single admission queue.  :class:`FleetRouter` fronts N
engine replicas behind the same ``submit()/tokens()`` streaming API and
makes the typed-error taxonomy of :mod:`torchdistx_tpu.serving.lifecycle`
*actionable*: a request that fails with ``retryable=True`` anywhere in
its life — shed by an overloaded replica, flushed by a drain, aborted by
a crashed/closed engine, beyond a recovery budget — is re-submitted to a
peer under a per-request **hop budget**, with
:class:`~torchdistx_tpu.resilience.retry.RetryPolicy` backoff between
hops.  When no replica can take it, the failure is **typed**
(:class:`NoReplicaAvailable` / :class:`FailoverExhausted`) — never a
silent drop, never a hang.

**Routing policy** (least-estimated-TTFT): among replicas with open
admission, DRAINING/STOPPED are excluded outright, OVERLOADED replicas
are avoided (used only when nothing healthier exists — their shed is
retryable, so the failover path covers a wrong guess), and the rest are
ranked by ``(est_ttft_s, queued+running, replica id)`` — the per-engine
:meth:`~torchdistx_tpu.serving.engine.Engine.est_ttft_s` hook, NOT the
process-global ``serve.est_ttft_s`` gauge, which N replicas in one
process would clobber.

**Failover token parity**: engine output is token-identical to solo
``generate()`` with the same key, so a replay on a peer reproduces the
stream from the start.  The fleet handle pins the request key at
submission, skips the already-yielded prefix of the replacement stream,
and the consumer's iterator continues mid-stream as if nothing
happened.  The prefix is verified against the handle's rolling
**determinism digest** (:class:`torchdistx_tpu.telemetry.audit
.DeterminismDigest`): the replayed prefix re-hashes into one digest
and ONE compare at the skip point decides.  The serving engine's
``model_version`` folds into every token of the digest, so a
deliberately version-mixed replay is rejected even when the token ids
happen to agree; a plain token mismatch additionally short-circuits at
the first wrong token (the committed list ``result()`` retains anyway
doubles as an early exit, so a broken replay never decodes a long
prefix to its end).  Any mismatch fails typed as
:class:`FailoverDiverged`, never silently.  A stream that has already
yielded tokens is also version-pinned at routing time: it may only
fail over to a replica serving the SAME weights version, so tokens
from two model versions never interleave within one stream (see
:mod:`.hot_swap`).

**Replica supervision**: a crashed or :meth:`close`-d replica is
detected via its health state; :meth:`FleetRouter.poll` (called by every
:meth:`FleetRouter.step`) reaps STOPPED replicas.  Its queued and live
work was already failed with retryable typed errors by the engine's own
close/drain choreography, so each affected fleet handle re-routes itself
on its next pull.  A replacement can be respawned into the fleet with
:meth:`FleetRouter.add_replica` at any time.

**Stream migration & role disaggregation** (docs/fleet.md,
"Disaggregation & stream migration"): a live decoding stream can move
between same-version replicas WITHOUT recompute —
:meth:`FleetRouter.migrate_stream` drives the engine pair's
``migrate_out()``/``migrate_in()`` (pages gather to host, digest-verify
on arrival, scatter into the peer's pool; the same ``fold_in(key,
n_gen)`` schedule continues token-identically).  Graceful drains
(:meth:`FleetRouter.migrate_out_streams` — hot swap and autoscaler
scale-in call it) prefer migration over waiting streams out; a failed
import falls back to the cold key-pinned replay this module already
owns, counted on ``fleet.migration_fallbacks`` — cold replay also
remains the ONLY path when the source pool is gone (crash), since there
is nothing left to export.  Engines advertise a ``role``
(prefill/decode/mixed): routing steers long prompts to prefill-role
replicas, keeps short/chatty work off them, and
:meth:`FleetRouter.rebalance` (run by every :meth:`FleetRouter.step`)
ships decode-phase streams from prefill-role replicas to decode-role
peers mid-stream — the DistServe/vLLM-lineage prefill/decode split.

Telemetry: ``fleet.submitted`` / ``fleet.failovers`` /
``fleet.hops_exhausted`` / ``fleet.migrations`` /
``fleet.migration_fallbacks`` counters, the ``fleet.replicas_ready``
gauge, and the ``fleet.failover_added_s`` / ``fleet.migration_s``
histograms (docs/observability.md); the hot-swap machinery adds
``fleet.swaps`` and the ``fleet.swap`` span (:mod:`.hot_swap`).
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from .. import telemetry as _telemetry
from ..resilience.retry import RetryPolicy
from ..telemetry import audit as _audit
from ..telemetry import ops as _ops
from ..serving.lifecycle import (
    DeadlineExceeded,
    DeterminismDiverged,
    Health,
    JournalOwned,
    RecoveryFailed,
    RequestCancelled,
    RequestError,
    RequestPreempted,
)

__all__ = [
    "FailoverDiverged",
    "FailoverExhausted",
    "FleetHandle",
    "FleetRouter",
    "NoReplicaAvailable",
    "Replica",
]

_T_SUBMITTED = _telemetry.counter("fleet.submitted")
_T_FAILOVERS = _telemetry.counter("fleet.failovers")
_T_HOPS_EXHAUSTED = _telemetry.counter("fleet.hops_exhausted")
_G_REPLICAS_READY = _telemetry.gauge("fleet.replicas_ready")
# Wall-clock a failover adds to its stream: from catching the replica's
# typed failure to the successful re-submission on a peer (backoff
# sleeps included — they are part of what the consumer waits).
_H_FAILOVER_ADDED = _telemetry.histogram("fleet.failover_added_s")
# Warm stream migrations: completed page-level moves vs. imports that
# failed and fell back to the cold key-pinned replay.  The histogram is
# the full export→import wall clock — what a migrated stream's consumer
# waited, the number the bench compares against cold-replay added
# latency.
_T_MIGRATIONS = _telemetry.counter("fleet.migrations")
_T_MIGRATION_FALLBACKS = _telemetry.counter("fleet.migration_fallbacks")
_H_MIGRATION = _telemetry.histogram("fleet.migration_s")

# Migration destination preference by engine role: decode-role replicas
# exist to absorb mid-stream work, mixed take anything, prefill-role
# replicas are what migration is shipping work AWAY from (last resort).
_ROLE_DEST_ORDER = {"decode": 0, "mixed": 1, "prefill": 2}

# Fleet-wide trace-id mint ("fleet-r0", "fleet-r1", ...): ONE id pinned
# at fleet submission and forwarded on every failover hop, so every
# engine's spans/events for the request reconstruct into one timeline.
_TRACE_SEQ = itertools.count()

# Health states a replica may be routed to.  DRAINING/STOPPED are
# excluded outright; OVERLOADED is routable but avoided (last resort).
_ROUTABLE = (Health.STARTING, Health.READY, Health.OVERLOADED)
_PREFERRED = (Health.STARTING, Health.READY)


class NoReplicaAvailable(RequestError):
    """No replica can take the request: every candidate is draining,
    stopped, excluded by a failed hop, or (for a mid-stream failover)
    serves a different weights version.  Retryable — the fleet may heal
    (a respawn, a finished swap) and the identical request succeed."""

    retryable = True


class FailoverExhausted(RequestError):
    """The request burned through its per-request hop budget without
    completing; ``__cause__`` is the last underlying typed failure.
    Retryable at a higher level — the budget bounds THIS submission."""

    retryable = True


class FailoverDiverged(RequestError):
    """A failover replay's prefix did not match the tokens already
    yielded to the consumer — the token-parity invariant broke (wrong
    weights on a same-version peer, or a correctness bug).  NOT
    retryable: the stream cannot be continued without interleaving two
    different generations."""


@dataclasses.dataclass
class Replica:
    """One engine in the fleet (router-side bookkeeping)."""

    rid: int
    engine: Any
    version: str
    admitting: bool = True  # router-level admission gate (hot swap)

    def load(self) -> int:
        """Queued + running requests — the routing tiebreak."""
        eng = self.engine
        return len(eng.scheduler) + eng._n_running()


class FleetHandle:
    """Streaming view of one fleet request, across failovers.

    Mirrors :class:`~torchdistx_tpu.serving.scheduler.RequestHandle`
    (``tokens()`` / ``result()`` / ``cancel()`` / ``done`` / ``error``)
    but survives the death of the engine serving it: a retryable typed
    failure re-binds the handle to a peer and the iterator continues
    where it left off.  ``done``/``error`` reflect what the *consumer*
    has observed — a handle is done once its stream was pulled to
    completion or failed terminally.
    """

    def __init__(
        self,
        router: "FleetRouter",
        prompt,
        max_new_tokens: int,
        key,
        deadline_s: Optional[float],
        max_hops: int,
        tenant: str = "default",
        priority: int = 0,
        model: Optional[str] = None,
        n: int = 1,
    ):
        self._router = router
        self._prompt = np.asarray(prompt, np.int32).reshape(-1)
        self._max_new_tokens = int(max_new_tokens)
        self._key = key
        # QoS context: pinned at fleet submission and forwarded on
        # EVERY re-submission, so a preempted-then-failed-over stream
        # keeps its class, tenant share, and remaining deadline on the
        # peer (inert on FIFO-scheduled engines).
        self.tenant = str(tenant)
        self.priority = int(priority)
        # Model-plane context (docs/serving.md, "Model plane"): the
        # target pool model and the fork fan-out, pinned exactly like
        # tenant/priority and forwarded on EVERY re-submission.  This
        # handle streams sibling 0 (the parent); its key is
        # fold_in(base, 0) when n > 1 — deterministic on any replica,
        # so failover replay stays token-identical.  Siblings on a dead
        # replica die with it and are re-forked by the re-submission.
        self.model = model
        self.n = int(n)
        self._deadline = (
            time.perf_counter() + deadline_s if deadline_s is not None else None
        )
        self._max_hops = int(max_hops)
        self._committed: List[int] = []  # tokens yielded to the consumer
        self._inner = None  # current engine-side RequestHandle
        self._cancelled = False
        self._done = False
        self.error: Optional[BaseException] = None
        self.hops = 0  # re-submissions consumed (first binding is free)
        self.replica_id: Optional[int] = None
        self.version: Optional[str] = None
        # Trace context: minted at first bind (lazily — only once
        # something is recording) and forwarded on every hop.
        self.trace_id: Optional[str] = None
        # Determinism digest over the YIELDED stream (audit plane):
        # seeded lazily from the first bound engine's normalized key
        # (every engine normalizes identically, so any bind works),
        # updated per yielded token with the serving engine's
        # model_version.  Failover prefix verification compares ONE
        # digest instead of walking the committed list.
        self._digest = None
        self._model_version: str = "v0"

    @property
    def done(self) -> bool:
        return self._done

    @property
    def digest(self) -> Optional[str]:
        """Hex snapshot of the determinism digest over the tokens this
        handle has YIELDED (docs/observability.md, "Audit plane");
        None before the first token.  Equal to the serving engine's
        request digest for a stream that never failed over."""
        return None if self._digest is None else self._digest.hexdigest()

    def cancel(self) -> bool:
        """Request cancellation (forwarded to the bound engine).  A
        cancelled request never fails over — the resulting
        ``RequestCancelled`` is the client's own doing.  Returns False
        (no-op) once the stream already finished."""
        if self._done:
            return False
        self._cancelled = True
        if self._inner is not None:
            self._inner.cancel()
        return True

    # ------------------------------------------------------------------
    # Binding / failover

    def _fail(self, error: BaseException) -> None:
        if self._done:
            # Idempotent: a deadline that expires during placement is
            # failed by _remaining_deadline_s AND re-caught by the bind
            # loop — one terminal event, not two.
            return
        self.error = error
        self._done = True
        if self.trace_id is not None:
            _telemetry.event(
                "req.failed",
                rid=self.trace_id,
                engine="fleet",
                hop=self.hops,
                error=type(error).__name__,
                retryable=bool(getattr(error, "retryable", False)),
                n_tokens=len(self._committed),
            )
        if isinstance(error, (FailoverExhausted, FailoverDiverged,
                              NoReplicaAvailable)):
            # Fleet-terminal infrastructure failures are flight-recorder
            # moments: the ring holds the hops that led here.
            _telemetry.flight_dump(type(error).__name__, rid=self.trace_id)

    def _remaining_deadline_s(self) -> Optional[float]:
        if self._deadline is None:
            return None
        remaining = self._deadline - time.perf_counter()
        if remaining <= 0:
            err = DeadlineExceeded(
                "request deadline expired while re-routing "
                f"(after {self.hops} hop(s))"
            )
            self._fail(err)
            raise err
        return remaining

    def _bind(self, cause: Optional[BaseException] = None) -> None:
        """Pick a replica and submit there; synchronous typed-retryable
        rejections (shed, draining) try the next candidate.  Every
        re-submission — whether after a mid-stream failure (``cause``),
        a rejected hop, or a placement retry against a momentarily
        unroutable fleet — consumes the hop budget.  Raises typed
        (:class:`FailoverExhausted` / :class:`NoReplicaAvailable` /
        the non-retryable cause) when the request cannot be placed."""
        excluded = set() if self.replica_id is None else {self.replica_id}
        # A stream that already yielded tokens must finish on the SAME
        # weights version — never interleave two models in one stream.
        version = self.version if self._committed else None
        retry = self._router.retry
        t_fail = time.perf_counter() if cause is not None else None
        if self.trace_id is None and _telemetry.events_enabled():
            self.trace_id = f"fleet-r{next(_TRACE_SEQ)}"
        while True:
            if cause is not None:
                self.hops += 1
                if self.hops > self._max_hops:
                    _T_HOPS_EXHAUSTED.add()
                    err = FailoverExhausted(
                        f"hop budget ({self._max_hops}) exhausted; "
                        f"last failure: {cause!r}"
                    )
                    err.__cause__ = cause
                    self._fail(err)
                    raise err
                time.sleep(retry.delay(self.hops - 1))
                # A deadline can expire during the backoff sleeps of a
                # long placement wait — fail it as its own typed error,
                # not a generic NoReplicaAvailable at budget exhaustion.
                self._remaining_deadline_s()
            rep = self._router._pick(
                exclude=excluded, version=version,
                prompt_len=len(self._prompt),
            )
            if rep is None and excluded:
                # Every candidate was excluded by a failed attempt in
                # THIS binding.  Exclusion only means "not again without
                # backoff" — the backoff just slept, the replica may
                # have recovered (a shed queue drains, an overload
                # clears), and the hop budget still bounds the loop: so
                # stop shunning the pool and try it again rather than
                # failing a single-replica fleet on its first hiccup.
                excluded = set()
                rep = self._router._pick(
                    exclude=excluded, version=version,
                    prompt_len=len(self._prompt),
                )
            if rep is None:
                if self.hops < self._max_hops:
                    # A fleet with NO routable replica is routinely a
                    # momentary window, not a verdict: every replica
                    # draining mid-hot-swap, a killed engine reaped an
                    # instant before its respawn registers, a tiny fleet
                    # whose only peer is busy churning.  Chaos at small
                    # N hits these windows constantly.  Placement
                    # retries with backoff under the same hop budget —
                    # the loop head sleeps, re-checks the deadline, and
                    # re-picks — and only a fleet that STAYS unroutable
                    # for the whole budget fails typed below.
                    if cause is None:
                        cause = NoReplicaAvailable(
                            "no routable replica (momentary?); retrying "
                            f"placement (hop {self.hops + 1}/"
                            f"{self._max_hops})"
                        )
                    if t_fail is None:
                        # The binding's first obstacle was an unroutable
                        # fleet: the added-latency clock starts here.
                        t_fail = time.perf_counter()
                    continue
                err = NoReplicaAvailable(
                    "no replica can take the request"
                    + (f" (version-pinned to {version!r})" if version else "")
                    + f" after {self.hops} hop(s)"
                )
                if cause is not None and not isinstance(
                    cause, NoReplicaAvailable
                ):
                    err.__cause__ = cause
                self._fail(err)
                raise err
            try:
                self._inner = rep.engine.submit(
                    self._prompt,
                    max_new_tokens=self._max_new_tokens,
                    key=self._key,
                    deadline_s=self._remaining_deadline_s(),
                    tenant=self.tenant,
                    priority=self.priority,
                    model=self.model,
                    n=self.n,
                    trace_id=self.trace_id,
                    hop=self.hops,
                )
            except RequestError as err:
                if not retry.is_retryable(err):
                    self._fail(err)
                    raise
                excluded.add(rep.rid)
                cause = err
                if t_fail is None:
                    # The binding's FIRST failure was a synchronous
                    # rejection (not a mid-stream failure): the added-
                    # latency clock starts here.
                    t_fail = time.perf_counter()
                continue
            self.replica_id = rep.rid
            self.version = rep.version
            # The version folded into every digest token: the pool
            # entry's model_version for a pool model (the request
            # carries it), the engine's own otherwise.
            req = getattr(self._inner, "_req", None)
            self._model_version = (
                getattr(req, "model_version", None)
                or getattr(rep.engine, "model_version", "v0")
            )
            if self._digest is None:
                # Seed from the engine-normalized key so the fleet's
                # digest and the engine's request digests hash the same
                # bytes for the same submit(key=...) — including the
                # fold_in(base, 0) sibling-0 key when n > 1.
                self._digest = _audit.DeterminismDigest(
                    self._prompt,
                    req.key if req is not None
                    else _audit.canonical_key(self._key),
                )
            if cause is not None:
                _T_FAILOVERS.add()
                added = time.perf_counter() - t_fail
                _H_FAILOVER_ADDED.observe(added)
                if self.trace_id is not None:
                    _telemetry.event(
                        "req.failover_hop",
                        rid=self.trace_id,
                        engine=getattr(rep.engine, "engine_id", None),
                        hop=self.hops,
                        cause=type(cause).__name__,
                        added_s=round(added, 6),
                        n_tokens=len(self._committed),
                    )
            return

    # ------------------------------------------------------------------
    # Streaming

    def tokens(self) -> Iterator[int]:
        """Yield tokens as they are produced, driving the bound engine —
        and re-binding to a peer when it fails retryably.  The replay on
        the peer is token-identical (same key, same ``fold_in``
        schedule), so the already-yielded prefix is verified and
        skipped; the iterator continues mid-stream.  Raises the
        request's typed error when it fails terminally."""
        while True:
            if self._done:
                if self.error is not None:
                    raise self.error
                return
            inner = self._inner
            inner_err = getattr(inner, "error", None)
            if (
                inner_err is not None
                and not self._cancelled
                and self._router.retry.is_retryable(inner_err)
            ):
                # The bound engine already failed this request before we
                # consumed its stream (killed mid-load, closed, drained)
                # — tokens it BUFFERED but never yielded to the consumer
                # are discarded, not drained: consuming them would
                # version-pin the stream to a replica set that may
                # already be gone (the small-N kill-then-hot-swap chaos
                # failure), while the replay is token-identical from the
                # pinned key anyway.  Tokens already yielded in earlier
                # pulls stay committed and are prefix-verified below.
                self._bind(cause=inner_err)
                continue
            n_skip = len(self._committed)
            # Digest-based prefix verification (audit plane): the
            # replayed prefix re-hashes into a fresh digest and ONE
            # compare at the skip point decides — the digest, not the
            # token list, is the verification contract, and because
            # model_version folds into every token a same-router-tag
            # peer serving differently-tagged weights is rejected even
            # when the token ids match.  The per-token compare against
            # _committed (which result() retains anyway) is an early
            # exit: a token mismatch cancels the replay at the first
            # wrong token — with its exact index — instead of decoding
            # the rest of a long prefix on a broken stream.
            verify = None
            if n_skip:
                req = getattr(inner, "_req", None)
                verify = _audit.DeterminismDigest(
                    self._prompt,
                    req.key if req is not None
                    else _audit.canonical_key(self._key),
                )
            i = 0
            try:
                for tok in inner.tokens():
                    i += 1
                    if i <= n_skip:
                        if tok != self._committed[i - 1]:
                            inner.cancel()
                            err = FailoverDiverged(
                                f"failover replay diverged at token {i}: "
                                f"replayed {tok}, committed "
                                f"{self._committed[i - 1]} (replica "
                                f"{self.replica_id}, version {self.version})"
                            )
                            self._fail(err)
                            raise err
                        verify.update((tok,), self._model_version)
                        if (
                            i == n_skip
                            and verify.hexdigest() != self._digest.hexdigest()
                        ):
                            inner.cancel()
                            err = FailoverDiverged(
                                "failover replay prefix matches token-wise "
                                "but its determinism digest does not — a "
                                "version-mixed stream: digest "
                                f"{verify.hexdigest()} != committed "
                                f"{self._digest.hexdigest()} (replica "
                                f"{self.replica_id}, version {self.version}, "
                                f"model_version {self._model_version})"
                            )
                            self._fail(err)
                            raise err
                        continue
                    self._digest.update((tok,), self._model_version)
                    self._committed.append(tok)
                    yield tok
                if i < n_skip:
                    # The replay finished SHORTER than the prefix already
                    # yielded (early EOS under different weights): as
                    # much a parity break as a mismatched token — a
                    # "clean" completion here would silently truncate.
                    err = FailoverDiverged(
                        f"failover replay ended after {i} token(s), "
                        f"shorter than the {n_skip} already yielded "
                        f"(replica {self.replica_id}, version "
                        f"{self.version})"
                    )
                    self._fail(err)
                    raise err
                self._done = True
                return
            except RequestError as err:
                if err is self.error:
                    raise  # our own terminal error (diverged / deadline)
                if self._cancelled:
                    # The client's cancel may race a drain/close on the
                    # bound engine: whichever typed error the engine
                    # reported, the stream ended because the CLIENT
                    # cancelled — surface that, and never fail over.
                    if not isinstance(err, RequestCancelled):
                        cancelled = RequestCancelled(
                            "request cancelled by the client (engine "
                            f"reported {type(err).__name__})"
                        )
                        cancelled.__cause__ = err
                        err = cancelled
                    self._fail(err)
                    raise err
                if not self._router.retry.is_retryable(err):
                    self._fail(err)
                    raise
                self._bind(cause=err)  # raises typed when impossible

    def result(self) -> List[int]:
        """Block (by streaming) until done; returns all tokens — across
        however many replicas it took."""
        for _ in self.tokens():
            pass
        return list(self._committed)


class FleetRouter:
    """Front N engine replicas with one streaming submit/tokens API.

    Parameters
    ----------
    engines : initial replicas, all registered under ``version``.
    version : weights-version tag of the initial replicas (hot swaps
        introduce new tags; mid-stream failover is version-pinned).
    max_hops : per-request re-submission budget (failovers + rejected
        placement attempts); exhaustion fails typed, never silently.
    retry : :class:`~torchdistx_tpu.resilience.retry.RetryPolicy` whose
        ``is_retryable`` classifies failures (honoring the
        ``RequestError.retryable`` contract) and whose ``delay``
        schedule paces the hops.  Default: 5 ms base, 250 ms cap.
    long_prompt_tokens : prompt length (tokens) at which routing
        prefers a ``role="prefill"`` replica; shorter prompts prefer
        decode/mixed-role replicas.  Role preference is advisory — a
        role-less fleet routes exactly as before, and a role never
        makes a request unroutable (the non-preferred pool is the
        fallback).  Default 2048.
    ops_port : opt the whole fleet into the live ops plane
        (:mod:`torchdistx_tpu.telemetry.ops`): the router get-or-creates
        the plane on the port and ``retain()``-s it so it outlives
        replica churn — every replica (current and future) is watched
        (``/healthz`` entry + stall watchdog + per-tick attribution),
        reaped/removed replicas unwatch, and :meth:`close` releases the
        retain, tearing the listener down once the last engine is gone.
        ``0`` binds an ephemeral port (read it back from
        ``router.ops_plane.port``).  Default: ``TDX_OPS_PORT`` when
        set, else off.
    ops_config : :class:`torchdistx_tpu.telemetry.ops.OpsConfig`,
        applied when this router CREATES the plane; joiners share as-is.

    Single-threaded like the engines it fronts: handles drive their
    bound engine; :meth:`step` advances every live replica (and reaps
    stopped ones) for drain/idle progress.
    """

    def __init__(
        self,
        engines=(),
        *,
        version: str = "v0",
        max_hops: int = 3,
        retry: Optional[RetryPolicy] = None,
        long_prompt_tokens: int = 2048,
        ops_port: Optional[int] = None,
        ops_config: Optional[_ops.OpsConfig] = None,
    ):
        if max_hops < 0:
            raise ValueError("max_hops must be >= 0")
        if long_prompt_tokens < 1:
            raise ValueError("long_prompt_tokens must be >= 1")
        self.max_hops = max_hops
        self.long_prompt_tokens = int(long_prompt_tokens)
        self.retry = retry or RetryPolicy(
            max_attempts=max_hops + 1, base_delay_s=0.005, max_delay_s=0.25
        )
        self._replicas: Dict[int, Replica] = {}
        self._next_rid = 0
        self._next_key = 0
        # Supervision hooks (add_reap_listener): notified per replica
        # reaped by poll() — an autoscaler's control tick runs poll()
        # so STOPPED replicas leave the fleet (and their gauge families
        # leave the registry) without user code ever polling by hand.
        self._reap_listeners: List = []
        self.ops_plane: Optional[_ops.OpsPlane] = None
        if ops_port is None:
            ops_port = _ops.env_ops_port()
        if ops_port is not None:
            # Retained: the plane survives windows where every replica
            # is momentarily gone (kill + respawn, hot swap) — a scrape
            # mid-churn sees 503, not connection-refused.
            self.ops_plane = _ops.get_plane(
                int(ops_port), ops_config
            ).retain()
        for eng in engines:
            self.add_replica(eng, version=version)

    # ------------------------------------------------------------------
    # Fleet membership

    def add_replica(self, engine, *, version: str = "v0") -> int:
        """Register an engine (a fresh spawn, a respawn, or a hot-swap
        standby); returns its replica id."""
        rid = self._next_rid
        self._next_rid += 1
        self._replicas[rid] = Replica(rid, engine, version)
        if self.ops_plane is not None and not self.ops_plane.closed:
            self.ops_plane.watch(engine)
        self._update_ready_gauge()
        return rid

    def remove_replica(self, rid: int, *, close: bool = True) -> None:
        """Drop a replica from the fleet; by default also ``close()`` its
        engine (idempotent — a drained/crashed engine is already
        STOPPED, and close() fails any straggling work retryably so the
        affected handles re-route)."""
        rep = self._replicas.pop(rid, None)
        if rep is not None and close:
            rep.engine.close()
        if rep is not None and self.ops_plane is not None:
            # close()/STOPPED already unwatched via _finish_drain; this
            # covers the close=False reap of an engine that died without
            # running its own teardown.  Idempotent.
            self.ops_plane.unwatch(rep.engine)
        self._update_ready_gauge()

    def close_admission(self, rid: int) -> None:
        """Stop routing NEW work to a replica (hot swap: admission
        shifts to the standby before the old engine drains).  In-flight
        and queued work on the replica is untouched."""
        self._replicas[rid].admitting = False
        self._update_ready_gauge()

    def replicas(self) -> List[Replica]:
        """Snapshot of the fleet membership (routing order)."""
        return [self._replicas[rid] for rid in sorted(self._replicas)]

    def add_reap_listener(self, fn) -> None:
        """Register ``fn(rid, engine)``, called by :meth:`poll` for each
        replica it reaps — the router's supervision hook.  An attached
        :class:`~torchdistx_tpu.fleet.autoscale.Autoscaler` calls
        ``poll()`` every control tick, so with one running, STOPPED
        replicas are reaped (and their per-engine gauge families pruned)
        with no manual ``poll()`` from user code."""
        if fn not in self._reap_listeners:
            self._reap_listeners.append(fn)

    def remove_reap_listener(self, fn) -> None:
        try:
            self._reap_listeners.remove(fn)
        except ValueError:
            pass

    def poll(self) -> List[int]:
        """Reap replicas whose engine reached STOPPED (crashed, closed,
        or drained out).  Their queued/live work already failed with
        retryable typed errors, so the affected handles re-route on
        their next pull.  Returns the reaped replica ids."""
        dead = [
            rid
            for rid, rep in self._replicas.items()
            if rep.engine.health() is Health.STOPPED
        ]
        reaped = [(rid, self._replicas[rid].engine) for rid in dead]
        for rid in dead:
            self.remove_replica(rid, close=False)
        for rid, eng in reaped:
            for fn in list(self._reap_listeners):
                try:
                    fn(rid, eng)
                except Exception:  # noqa: BLE001 — supervision never kills routing
                    pass
        return dead

    def close(self) -> None:
        """Retire the whole fleet NOW: every replica engine is closed
        (outstanding work fails retryable-typed) and dropped; the ops
        plane's retain is released, so a router-created plane with no
        other engines shuts its listener down."""
        for rid in list(self._replicas):
            self.remove_replica(rid, close=True)
        if self.ops_plane is not None:
            self.ops_plane.release()
            self.ops_plane = None

    # ------------------------------------------------------------------
    # Routing

    def _pick(
        self,
        exclude=frozenset(),
        version: Optional[str] = None,
        prompt_len: Optional[int] = None,
    ) -> Optional[Replica]:
        """Least-estimated-TTFT among routable replicas.  READY (and
        STARTING) replicas are preferred; OVERLOADED ones serve only as
        a last resort; DRAINING/STOPPED never route.  With
        ``prompt_len``, role steering applies within the health-
        preferred pool (see :meth:`_role_pool`)."""
        candidates = [
            rep
            for rep in self._replicas.values()
            if rep.admitting
            and rep.rid not in exclude
            and (version is None or rep.version == version)
            and rep.engine.health() in _ROUTABLE
        ]
        self._update_ready_gauge()
        if not candidates:
            return None
        preferred = [
            rep for rep in candidates if rep.engine.health() in _PREFERRED
        ]
        pool = self._role_pool(preferred or candidates, prompt_len)
        return min(
            pool, key=lambda r: (r.engine.est_ttft_s(), r.load(), r.rid)
        )

    def _role_pool(
        self, pool: List[Replica], prompt_len: Optional[int]
    ) -> List[Replica]:
        """Prefill/decode disaggregation steering (docs/fleet.md): long
        prompts (``>= long_prompt_tokens``) prefer prefill-role
        replicas — their pages ship to a decode-role peer mid-stream
        via :meth:`rebalance` — while short/chatty work stays OFF
        prefill-role replicas so a 16k-token prefill never sits in
        front of its decode chunks.  Advisory only: a role-less pool
        passes through untouched, and when no replica of the preferred
        role is routable the whole pool is the fallback."""
        if prompt_len is None:
            return pool
        roles = {getattr(r.engine, "role", "mixed") for r in pool}
        if roles <= {"mixed"}:
            return pool
        if prompt_len >= self.long_prompt_tokens:
            pref = [
                r for r in pool
                if getattr(r.engine, "role", "mixed") == "prefill"
            ]
        else:
            pref = [
                r for r in pool
                if getattr(r.engine, "role", "mixed") != "prefill"
            ]
        return pref or pool

    def _update_ready_gauge(self) -> None:
        _G_REPLICAS_READY.set(
            sum(
                rep.admitting and rep.engine.health() in _PREFERRED
                for rep in self._replicas.values()
            )
        )

    # ------------------------------------------------------------------
    # Stream migration (docs/fleet.md, "Disaggregation & stream
    # migration"): move live decoding streams between replicas at the
    # KV-page level — zero recompute, digest-verified on arrival.

    def _migration_dests(self, src_rid: int, version: str) -> List[Replica]:
        """Candidate import targets for a stream leaving ``src_rid``:
        same weights version (a migrated stream must never interleave
        two models — the same pin as mid-stream failover), routable,
        still admitting, ordered decode-role first, then mixed, then
        (last resort) prefill, with least-loaded tiebreak."""
        candidates = [
            rep
            for rep in self._replicas.values()
            if rep.rid != src_rid
            and rep.admitting
            and rep.version == version
            and rep.engine.health() in _ROUTABLE
        ]
        return sorted(
            candidates,
            key=lambda r: (
                _ROLE_DEST_ORDER.get(getattr(r.engine, "role", "mixed"), 1),
                0 if r.engine.health() in _PREFERRED else 1,
                r.engine.est_ttft_s(),
                r.load(),
                r.rid,
            ),
        )

    def migrate_stream(self, rid: int, slot: int) -> bool:
        """Warm-migrate ONE live stream off replica ``rid``'s engine
        slot to the best same-version peer.  Returns True when the
        stream continues on the peer (the consumer's handle keeps
        streaming, token-identically, with zero recomputed tokens).

        Returns False and leaves the stream UNTOUCHED on the source
        when there is no compatible destination, the stream's deadline
        already expired (the source engine's own reap surfaces
        ``DeadlineExceeded`` — exactly once), or the export itself
        declines (injected fault, pool lost): a failed export must
        never strand a running stream.  When the export succeeded but
        every candidate refuses the import (geometry/version mismatch,
        overload, injected import fault), the source slot is already
        gone — the engine-side handle is failed with a retryable
        ``RequestPreempted`` so the :class:`FleetHandle` falls back to
        the cold key-pinned replay on its next pull, counted on
        ``fleet.migration_fallbacks``.  A ``DeterminismDiverged`` on
        arrival is terminal (the engine already failed the handle
        typed): a corrupt stream is never replayed.

        The fleet handle's ``replica_id`` is a routing hint, not a
        liveness contract — it goes stale across a migration and is
        refreshed by the next (re-)bind, which excludes it anyway."""
        rep = self._replicas.get(rid)
        if rep is None:
            return False
        eng = rep.engine
        req = eng._slot_req[slot] if slot < len(eng._slot_req) else None
        if req is None:
            return False
        if req.deadline is not None and time.perf_counter() >= req.deadline:
            return False
        dests = self._migration_dests(rid, rep.version)
        if not dests:
            return False
        t0 = time.perf_counter()
        try:
            snapshot = eng.migrate_out(slot)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception:  # noqa: BLE001 — export declined; stream untouched
            return False
        last_err: Optional[BaseException] = None
        for dest in dests:
            try:
                dest.engine.migrate_in(snapshot)
            except (KeyboardInterrupt, SystemExit):
                raise
            except DeterminismDiverged:
                # migrate_in already failed the handle typed and
                # flight-dumped; there is nothing to fall back to.
                return False
            except Exception as err:  # noqa: BLE001 — try the next candidate
                last_err = err
                continue
            _T_MIGRATIONS.add()
            _H_MIGRATION.observe(time.perf_counter() - t0)
            return True
        # Export succeeded but no candidate would take the import: the
        # page snapshot is dropped and the stream falls back to the
        # cold replay path — the FleetHandle catches the retryable
        # preemption on its next pull and replays from the pinned key.
        _T_MIGRATION_FALLBACKS.add()
        if req.trace_id is not None:
            _telemetry.event(
                "req.migration_fallback",
                rid=req.trace_id,
                engine=getattr(eng, "engine_id", None),
                error=type(last_err).__name__ if last_err else None,
                n_tokens=int(snapshot.get("n_tokens", 0)),
            )
        req.handle._fail(
            RequestPreempted(
                "stream migration failed mid-import ("
                + (
                    f"{type(last_err).__name__}: {last_err}"
                    if last_err is not None
                    else "no importable destination"
                )
                + "); falling back to a key-pinned replay",
                resumable=False,
            )
        )
        return False

    def migrate_out_streams(self, rid: int) -> Dict[str, int]:
        """Drain-by-migration: warm-migrate every migratable stream off
        replica ``rid`` (graceful drains — hot swap and autoscaler
        scale-in — call this BEFORE ``begin_drain()``, so in-flight
        streams finish on peers with zero recomputed prefill tokens
        instead of holding the drain open).  Streams with no compatible
        destination are left running for the normal drain to finish —
        skipping is strictly better than failing them.  Returns
        ``{"migrated", "fallbacks", "left"}`` counts."""
        out = {"migrated": 0, "fallbacks": 0, "left": 0}
        rep = self._replicas.get(rid)
        if rep is None:
            return out
        slots = getattr(rep.engine, "migratable_slots", None)
        if slots is None:
            # An engine without the migration API (a stub, an older
            # build) drains the normal way — nothing to move warm.
            return out
        before = _T_MIGRATION_FALLBACKS.value
        for slot in list(slots()):
            if self.migrate_stream(rid, slot):
                out["migrated"] += 1
        out["fallbacks"] = _T_MIGRATION_FALLBACKS.value - before
        out["left"] = rep.engine._n_running()
        return out

    def rebalance(self) -> int:
        """The prefill→decode handoff: ship decode-phase streams OFF
        prefill-role replicas onto decode/mixed-role same-version peers
        mid-stream.  Run by every :meth:`step`; a no-op in a role-less
        fleet.  Returns the number of streams moved.

        Capacity-gated: the handoff is an *optimization*, and an export
        whose import is then refused can only fall back to a cold
        replay — so a stream is shipped only while some candidate has a
        free slot to land it.  A saturated decode tier just means the
        prefill replica keeps decoding the stream itself."""
        moved = 0
        for rep in self.replicas():
            if getattr(rep.engine, "role", "mixed") != "prefill":
                continue
            if rep.engine.health() not in _ROUTABLE:
                continue
            for slot in list(rep.engine.migratable_slots()):
                if not any(
                    d.engine._n_running() < d.engine.num_slots
                    for d in self._migration_dests(rep.rid, rep.version)
                ):
                    break
                if self.migrate_stream(rep.rid, slot):
                    moved += 1
        return moved

    # ------------------------------------------------------------------
    # Cold-restart recovery (docs/resilience.md, "Durability")

    def recover(self, journal, *, version: Optional[str] = None) -> dict:
        """Fleet-level cold-restart resume: offer a dead process's
        request journal to the routable replicas (least-loaded first,
        optionally ``version``-pinned — a resumed stream must continue
        under the weights version it committed its tokens with) and
        resume every unfinished stream on the first replica that can
        take the claim.

        Exactly-once by construction: the winning replica holds the
        journal's ownership lock, so a second ``recover()`` call — or a
        peer router racing this one — gets the loser's typed
        :class:`~torchdistx_tpu.serving.lifecycle.JournalOwned` instead
        of a duplicate of every stream.  A replica whose geometry
        cannot continue the streams token-identically (config
        mismatch) is skipped for the next candidate; if no replica
        qualifies, a typed retryable ``RecoveryFailed`` surfaces the
        last refusal.

        Returns ``(replica_id, {journal uid: RequestHandle})``."""
        candidates = [
            rep
            for rep in self.replicas()
            if rep.admitting
            and (version is None or rep.version == version)
            and rep.engine.health() in _ROUTABLE
        ]
        candidates.sort(key=lambda r: (r.load(), r.rid))
        last_refusal: Optional[BaseException] = None
        for rep in candidates:
            try:
                handles = rep.engine.resume_from_journal(journal)
            except JournalOwned:
                # The double-resume guard: someone live already owns
                # these streams — surface it, do not shop it around.
                raise
            except ValueError as err:
                # Geometry mismatch (or an engine already bound to a
                # different journal): this replica cannot continue the
                # streams token-identically; the next one may.
                last_refusal = err
                continue
            return rep.rid, handles
        raise RecoveryFailed(
            "no routable replica could resume the journal"
            + (f" (last refusal: {last_refusal})" if last_refusal else "")
        )

    # ------------------------------------------------------------------
    # The fleet API

    def submit(
        self,
        prompt,
        *,
        max_new_tokens: int,
        key: Any = None,
        deadline_s: Optional[float] = None,
        max_hops: Optional[int] = None,
        tenant: str = "default",
        priority: int = 0,
        model: Optional[str] = None,
        n: int = 1,
    ) -> FleetHandle:
        """Route a request to the best replica; returns its streaming
        :class:`FleetHandle`.

        ``key`` is pinned HERE (defaulting to a fleet-wide counter, not
        any engine's request id) so every failover replay of the request
        samples identically on any replica.  ``deadline_s`` is a fleet-
        level wall-clock budget: each hop re-submits with the remaining
        time.  ``tenant`` / ``priority`` are the request's QoS context
        (see :mod:`torchdistx_tpu.serving.qos`), pinned on the handle
        and forwarded with every re-submission — a stream preempted on
        one replica and failed over to another keeps its class and its
        tenant's fair-queueing share.  ``model`` / ``n`` are the
        model-plane context (docs/serving.md, "Model plane"): the pool
        model to serve from and the parallel-sampling fan-out, pinned
        on the handle and forwarded on every re-submission exactly like
        tenant/priority — the handle streams the fork parent (sibling
        0), whose ``fold_in(base, 0)`` key replays identically on any
        peer.  Raises
        :class:`NoReplicaAvailable` (typed, retryable) when no replica
        can take it, and plain ``ValueError`` for requests that could
        never run anywhere (engine validation)."""
        if key is None:
            key = self._next_key
            self._next_key += 1
        handle = FleetHandle(
            self,
            prompt,
            max_new_tokens,
            key,
            deadline_s,
            self.max_hops if max_hops is None else max_hops,
            tenant=tenant,
            priority=priority,
            model=model,
            n=n,
        )
        if _telemetry.events_enabled():
            # The fleet-level submission opens the request's timeline —
            # even one that expires or fails before any engine accepts
            # it reconstructs complete (engine-side re-submissions emit
            # their own hop-scoped req.submitted as they land).
            handle.trace_id = f"fleet-r{next(_TRACE_SEQ)}"
            _telemetry.event(
                "req.submitted",
                rid=handle.trace_id,
                engine="fleet",
                hop=0,
                n_prompt=len(handle._prompt),
                max_new=int(max_new_tokens),
                tenant=handle.tenant,
                priority=handle.priority,
                model=handle.model,
                n=handle.n,
                deadline_s=deadline_s,
            )
        _T_SUBMITTED.add()
        try:
            handle._bind()
        except DeadlineExceeded as err:
            # The deadline expired before the request could even be
            # placed (the engine analog: expiring in queue).  The
            # handle carries the typed error; the pull raises it —
            # submit() itself only raises for requests that could
            # never run (ValueError) or a fleet that cannot take them.
            if err is not handle.error:
                raise
        return handle

    def step(self) -> None:
        """Advance every live replica one tick and reap stopped ones.
        Handles drive their own engine while streaming; step() exists
        for drain progress and idle upkeep (a draining replica with no
        consumer pulling it still has to finish its in-flight work)."""
        for rep in self.replicas():
            if rep.engine.health() is not Health.STOPPED:
                rep.engine.step()
        self.rebalance()
        self.poll()

    def stats(self) -> dict:
        """Fleet-level introspection: per-replica health/load plus the
        failover counters."""
        return {
            "replicas": [
                {
                    "rid": rep.rid,
                    "version": rep.version,
                    "admitting": rep.admitting,
                    "health": rep.engine.health().value,
                    "role": getattr(rep.engine, "role", "mixed"),
                    "est_ttft_s": round(rep.engine.est_ttft_s(), 4),
                    "load": rep.load(),
                }
                for rep in self.replicas()
            ],
            "submitted": _T_SUBMITTED.value,
            "failovers": _T_FAILOVERS.value,
            "hops_exhausted": _T_HOPS_EXHAUSTED.value,
            "migrations": _T_MIGRATIONS.value,
            "migration_fallbacks": _T_MIGRATION_FALLBACKS.value,
        }
