"""Signal-driven elastic autoscaler: the observe→act loop for the fleet.

PRs 9–13 made the serving stack self-diagnosing — per-tenant SLO
burn-rate (:class:`~torchdistx_tpu.telemetry.ops.SLOMonitor`), per-engine
occupancy/goodput/TTFT attribution, stall/recompile-storm/divergence
latches — and the fleet layer made capacity elastic (deferred-init
shard-then-materialize spins a warm standby up in ~0.13 s for gpt2-xl,
BENCH_r05).  This module connects them: :class:`Autoscaler` is a control
loop that *consumes* those signals and *drives* the existing actuators —
scale-out via an engine factory (typically
:func:`~torchdistx_tpu.fleet.hot_swap.materialize_standby` under the
hood) → :meth:`FleetRouter.add_replica`, scale-in via
``Engine.begin_drain()`` → reap through :meth:`FleetRouter.poll` — so
overload recovers and idle capacity retires without a human reading
``/metrics``.

Policy (every knob on :class:`AutoscaleConfig`; ticks are control-loop
iterations, not engine ticks):

* **Scale-out** on any of, subject to the scale-out cooldown and
  ``max_replicas``:

  - an **SLO burn** — the monitor's multi-window rule already demands
    the burn sustain in both its fast and slow windows, so a burn edge
    fires a scale-out immediately (no extra sustain);
  - **occupancy** ≥ ``occupancy_high`` (mean over capacity replicas)
    sustained ``fast_ticks`` consecutive ticks — likewise TTFT ≥
    ``ttft_high_s`` when set;
  - the **queue-slope predictor**: total queue depth (read from the
    per-engine ``serve.queue_depth{engine=}`` family) growing ≥
    ``slope_high`` requests/tick over the last ``slope_window`` ticks
    pre-scales *ahead* of a ramp, before occupancy saturates.

* **Scale-in** only when the fleet is *quiet* — no tenant burning, mean
  occupancy ≤ ``occupancy_low`` AND queue depth ≤
  ``queue_low_per_replica`` × replicas — sustained ``slow_ticks``
  consecutive ticks, subject to the scale-in cooldown and
  ``min_replicas``.  The gap between the high and low water marks is the
  **hysteresis band**: a signal oscillating inside it resets both
  sustain counters and produces no decision at all, so the fleet never
  flaps.  The victim **drains by migration** first
  (:meth:`~torchdistx_tpu.fleet.router.FleetRouter.migrate_out_streams`):
  its in-flight streams warm-migrate to same-version peers with zero
  recomputed prefill tokens, and only what could not move rides the
  normal drain out (docs/fleet.md, "Disaggregation & stream
  migration").

* **Role-aware placement**: in a fleet running prefill/decode
  disaggregation (engines with ``role=`` set), every spawn picks the
  scarcer role — a replacement keeps its predecessor's role — passed to
  the factory as ``make_engine(role=...)`` when it accepts the keyword
  (a role-less factory is called as before).

* **Replace, don't count**: a replica whose engine latched the
  divergence flag (:ref:`audit plane <docs/observability.md>`) is
  **never capacity** — it is drained and a fresh replica spawned in its
  place (``reason=replace_diverging``), independent of the load signals.
  The same deficit path respawns capacity lost to crashes below
  ``min_replicas``.

* **Recovery is an edge, not an absence**: burn state latches via the
  monitor's :meth:`~torchdistx_tpu.telemetry.ops.SLOMonitor
  .add_burn_listener` API (composing with — never replacing — the
  default flight-dump ``on_burn``), and only a genuine ``burning=False``
  transition counts as a recovery.  A tenant the monitor pruned for
  idleness silently disappears instead; the autoscaler does not mistake
  "no traffic" for "SLO healthy again", and a burn that clears during a
  cooldown cannot double-fire a stale scale-out once the cooldown ends
  (the live monitor state is re-checked at decision time).

Telemetry (docs/observability.md, "Control plane"): ``fleet.scale_outs``
/ ``fleet.scale_ins`` counters, the per-reason decision counter family
``fleet.autoscale_decision{reason=}`` (bounded: reasons are a fixed
enum), the ``fleet.replicas_target`` gauge, and one ``fleet.autoscale``
trace event per decision — ``scripts/autoscale_report.py`` reconstructs
the decision timeline from the exported trace.  All of it is pruned by
:meth:`Autoscaler.close` per the cardinality contract.

The loop is deterministic and thread-free by default: call
:meth:`Autoscaler.tick` from your driver (tests and the chaos soak do).
:meth:`Autoscaler.start` runs the same tick on a daemon thread for
deployments without a convenient driver loop.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .. import telemetry as _telemetry
from ..serving.lifecycle import Health

__all__ = ["AutoscaleConfig", "Autoscaler"]

_T_SCALE_OUTS = _telemetry.counter("fleet.scale_outs")
_T_SCALE_INS = _telemetry.counter("fleet.scale_ins")
_G_TARGET = _telemetry.gauge("fleet.replicas_target")

# The full decision-reason enum (the {reason=} label set is bounded by
# construction — free-form strings would break the cardinality contract).
REASONS = (
    "burn",
    "occupancy",
    "ttft",
    "queue_slope",
    "below_min",
    "replace_diverging",
    "quiet",
)


@dataclasses.dataclass
class AutoscaleConfig:
    """Knobs of one :class:`Autoscaler` (see the module docstring for
    the policy they parameterize).  Tick-denominated windows count
    *control-loop* ticks."""

    min_replicas: int = 1
    max_replicas: int = 4
    # -- scale-out (high water) --------------------------------------------
    occupancy_high: float = 0.85
    ttft_high_s: Optional[float] = None
    fast_ticks: int = 2  # consecutive ticks a high signal must sustain
    # -- queue-slope predictor ---------------------------------------------
    slope_window: int = 4  # ticks of total-queue-depth history
    slope_high: float = 2.0  # growth (requests/tick) that pre-scales
    # -- scale-in (low water: the hysteresis band's floor) -----------------
    occupancy_low: float = 0.30
    queue_low_per_replica: float = 0.5
    slow_ticks: int = 8  # consecutive quiet ticks before scale-in
    # -- cooldowns (ticks since the LAST scaling action) -------------------
    scale_out_cooldown: int = 3
    scale_in_cooldown: int = 6

    def validate(self) -> "AutoscaleConfig":
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                "need 1 <= min_replicas <= max_replicas, got "
                f"[{self.min_replicas}, {self.max_replicas}]"
            )
        if not 0.0 <= self.occupancy_low < self.occupancy_high <= 1.0:
            raise ValueError(
                "need 0 <= occupancy_low < occupancy_high <= 1 (the "
                "hysteresis band), got "
                f"[{self.occupancy_low}, {self.occupancy_high}]"
            )
        for field in (
            "fast_ticks",
            "slow_ticks",
            "slope_window",
            "scale_out_cooldown",
            "scale_in_cooldown",
        ):
            if getattr(self, field) < 1:
                raise ValueError(f"{field} must be >= 1")
        return self


class Autoscaler:
    """The control loop: one :meth:`tick` observes, decides, acts.

    Parameters
    ----------
    router : :class:`~torchdistx_tpu.fleet.router.FleetRouter`
        The fleet whose membership this loop owns.
    make_engine : ``() -> Engine``
        Replica factory for scale-out and replacement — typically wraps
        :func:`~torchdistx_tpu.fleet.hot_swap.materialize_standby` +
        ``Engine(...)``.  Called inline from :meth:`tick`.
    config : :class:`AutoscaleConfig`
    monitor : :class:`~torchdistx_tpu.telemetry.ops.SLOMonitor`, optional
        Burn-signal source.  Defaults to the router's ops plane monitor
        (``router.ops_plane.monitor``) when the plane exists; without
        either, the loop runs on occupancy/queue signals alone.
    version : weights version tag passed to ``add_replica``.
    """

    def __init__(
        self,
        router,
        make_engine: Callable[[], Any],
        *,
        config: Optional[AutoscaleConfig] = None,
        monitor=None,
        version: str = "v0",
    ):
        self.router = router
        self.make_engine = make_engine
        self.config = (config or AutoscaleConfig()).validate()
        self.version = version
        if monitor is None:
            plane = getattr(router, "ops_plane", None)
            monitor = getattr(plane, "monitor", None)
        self.monitor = monitor
        # Decision/introspection state (instance-local so tests and
        # benches read deltas without rummaging in global counters):
        self.scale_outs = 0
        self.scale_ins = 0
        self.replaces = 0
        self.recoveries = 0  # genuine burning→False edges seen
        self.decisions: deque = deque(maxlen=256)  # (tick, reason, n, target)
        self.burn_events: deque = deque(maxlen=256)  # (t, tenant, burning)
        # Control-loop state:
        self._tick_no = 0
        self._hi_ticks = 0
        self._lo_ticks = 0
        self._last_out: Optional[int] = None  # tick of last out/replace
        self._last_in: Optional[int] = None
        self._q_hist: deque = deque(maxlen=self.config.slope_window)
        # Burn latch, written by the monitor's listener thread:
        self._lock = threading.Lock()
        self._burning: Dict[str, bool] = {}
        self._burn_edge = False
        self._attached = False
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self.attach()

    # ------------------------------------------------------------------
    # Lifecycle

    def attach(self) -> "Autoscaler":
        """Subscribe the burn listener (idempotent; composes with the
        monitor's default flight-dump callback, see
        :meth:`SLOMonitor.add_burn_listener`)."""
        if self.monitor is not None and not self._attached:
            self.monitor.add_burn_listener(self._on_burn)
            self._attached = True
        return self

    def close(self) -> None:
        """Detach from the monitor, stop the background thread if any,
        and prune this loop's registry families (cardinality contract:
        a retired control plane leaves nothing behind in /metrics)."""
        self.stop()
        if self.monitor is not None and self._attached:
            self.monitor.remove_burn_listener(self._on_burn)
            self._attached = False
        _G_TARGET.set(None)
        for reason in REASONS:
            _telemetry.remove("fleet.autoscale_decision", reason=reason)

    # ------------------------------------------------------------------
    # Burn listener (monitor's emitting thread)

    def _on_burn(self, tenant: str, burning: bool, info) -> None:
        with self._lock:
            self.burn_events.append((time.time(), tenant, burning))
            if burning:
                self._burning[tenant] = True
                self._burn_edge = True
            elif self._burning.pop(tenant, None):
                # A REAL recovery transition.  Idle-pruned tenants never
                # reach here (the monitor suppresses that edge), so
                # "tenant went quiet" is never miscounted as "SLO
                # recovered".
                self.recoveries += 1

    # ------------------------------------------------------------------
    # Signals

    def _signals(self, capacity: List[Any]) -> Dict[str, Any]:
        """One observation of the fleet: occupancy / TTFT from the
        per-engine attribution gauges when the ops plane publishes them
        (falling back to the engines' own hooks), queue depth from the
        ``serve.queue_depth{engine=}`` family (satellite of this PR —
        the unlabeled gauge is clobbered by N replicas)."""
        gauges = _telemetry.gauges()
        occs: List[float] = []
        ttfts: List[float] = []
        queue = 0.0
        for rep in capacity:
            eng = rep.engine
            eid = getattr(eng, "engine_id", None)
            occ = gauges.get(f"serve.occupancy{{engine={eid}}}")
            if occ is None:
                slots = max(1, getattr(eng, "num_slots", 1))
                occ = eng._n_running() / slots
            occs.append(float(occ))
            q = gauges.get(f"serve.queue_depth{{engine={eid}}}")
            if q is None:
                q = len(eng.scheduler)
            queue += float(q)
            t = gauges.get(f"serve.est_ttft_s{{engine={eid}}}")
            if t is None:
                t = eng.est_ttft_s()
            ttfts.append(float(t))
        self._q_hist.append(queue)
        slope = 0.0
        if len(self._q_hist) == self._q_hist.maxlen:
            slope = (self._q_hist[-1] - self._q_hist[0]) / max(
                1, len(self._q_hist) - 1
            )
        return {
            "occupancy": sum(occs) / len(occs) if occs else 0.0,
            "ttft_s": max(ttfts) if ttfts else 0.0,
            "queue": queue,
            "queue_slope": slope,
        }

    # ------------------------------------------------------------------
    # The control tick

    def tick(self) -> str:
        """One observe→decide→act iteration; returns the decision reason
        (one of :data:`REASONS`, or ``"hold"``)."""
        cfg = self.config
        self._tick_no += 1
        # 1. Supervision: reap STOPPED replicas (crashed, closed, or
        # drained out by an earlier scale-in) — their gauge families
        # were pruned by the engines' own teardown; the router notifies
        # its reap listeners.  No user-code poll() required.
        self.router.poll()
        # 2. Partition the fleet.  Latched-diverging replicas are NEVER
        # capacity (hard rule): they serve wrong-token streams, so
        # counting them would both under-scale and route load into the
        # incident.
        reps = self.router.replicas()
        capacity: List[Any] = []
        diverging: List[Any] = []
        draining: List[Any] = []
        for rep in reps:
            h = rep.engine.health()
            if h is Health.DRAINING:
                draining.append(rep)
            elif getattr(rep.engine, "_diverging", False):
                diverging.append(rep)
            else:
                capacity.append(rep)
        # 3. Step draining replicas so drains progress even when no
        # consumer is pulling their handles (same rationale as
        # router.step()); they re-enter poll()'s reap at STOPPED.
        for rep in draining:
            try:
                rep.engine.step()
            except Exception:  # noqa: BLE001 — a dying drain is poll()'s problem
                pass
        # 4. Replace rule: drain every newly-diverging replica and spawn
        # its replacement immediately — replacement is incident
        # remediation, not load-driven growth, so it bypasses the
        # sustain windows (but still lands inside max_replicas via the
        # fleet-size guard below).
        decision = "hold"
        for rep in diverging:
            self.router.close_admission(rep.rid)
            rep.engine.begin_drain()
            self.replaces += 1
            if len(capacity) + 1 <= cfg.max_replicas:
                # The replacement inherits the drained replica's role so
                # a disaggregated fleet keeps its prefill/decode shape.
                self._spawn(role=getattr(rep.engine, "role", None))
                capacity.append(self.router.replicas()[-1])
            self._last_out = self._tick_no
            decision = self._decide("replace_diverging", len(capacity))
        n = len(capacity)
        sig = self._signals(capacity)
        with self._lock:
            burn_edge = self._burn_edge
            self._burn_edge = False
        # Live burn state re-checked at decision time: a burn that
        # cleared (or was idle-pruned) during a cooldown must not fire a
        # stale scale-out from the edge latch alone.
        burning_now = bool(self.monitor and any(self.monitor.burning().values()))
        # 5. Sustain counters for the high/low signal bands.  Anything
        # inside the hysteresis band resets both: no decision, no flap.
        high = None
        if burn_edge or burning_now:
            high = "burn"
        elif sig["occupancy"] >= cfg.occupancy_high:
            high = "occupancy"
        elif (
            cfg.ttft_high_s is not None and sig["ttft_s"] >= cfg.ttft_high_s
        ):
            high = "ttft"
        self._hi_ticks = self._hi_ticks + 1 if high else 0
        predict = (
            len(self._q_hist) == self._q_hist.maxlen
            and sig["queue_slope"] >= cfg.slope_high
        )
        quiet = (
            not burning_now
            and not burn_edge
            and sig["occupancy"] <= cfg.occupancy_low
            and sig["queue"] <= cfg.queue_low_per_replica * max(1, n)
        )
        self._lo_ticks = self._lo_ticks + 1 if quiet else 0
        # 6. Decide.  Deficit repair first (capacity below the floor is
        # an outage, not a load signal — no cooldown applies), then
        # scale-out under cooldown, then scale-in under its own.
        want_out = (
            high == "burn"  # the monitor already enforced dual-window sustain
            or (high is not None and self._hi_ticks >= cfg.fast_ticks)
            or predict
        )
        if n < cfg.min_replicas:
            while n < cfg.min_replicas:
                self._spawn(role=self._desired_role())
                n += 1
            self._last_out = self._tick_no
            self._hi_ticks = self._lo_ticks = 0
            decision = self._decide("below_min", n)
        elif (
            want_out
            and n < cfg.max_replicas
            and self._cooled(self._last_out, cfg.scale_out_cooldown)
        ):
            reason = high if high is not None else "queue_slope"
            self._spawn(role=self._desired_role())
            self.scale_outs += 1
            _T_SCALE_OUTS.add()
            self._last_out = self._tick_no
            self._hi_ticks = 0
            self._lo_ticks = 0
            n += 1
            decision = self._decide(reason, n)
        elif (
            quiet
            and self._lo_ticks >= cfg.slow_ticks
            and n > cfg.min_replicas
            and self._cooled(self._last_in, cfg.scale_in_cooldown)
            and self._cooled(self._last_out, cfg.scale_in_cooldown)
        ):
            victim = max(capacity, key=lambda r: (-r.load(), r.rid))
            self.router.close_admission(victim.rid)
            # Drain by migration: ship the victim's in-flight streams to
            # surviving same-version peers (zero recomputed tokens);
            # whatever could not move finishes under the normal drain.
            self.router.migrate_out_streams(victim.rid)
            victim.engine.begin_drain()
            self.scale_ins += 1
            _T_SCALE_INS.add()
            self._last_in = self._tick_no
            self._lo_ticks = 0
            n -= 1
            decision = self._decide("quiet", n)
        _G_TARGET.set(max(cfg.min_replicas, min(cfg.max_replicas, n)))
        # One trace event per tick (free when nothing records): the
        # decision timeline scripts/autoscale_report.py reads back.
        _telemetry.event(
            "fleet.autoscale",
            decision=decision,
            replicas=n,
            draining=len(draining) + len(diverging),
            occupancy=round(sig["occupancy"], 4),
            queue=sig["queue"],
            queue_slope=round(sig["queue_slope"], 3),
            burning=burning_now,
            tick=self._tick_no,
        )
        return decision

    def _cooled(self, last: Optional[int], cooldown: int) -> bool:
        return last is None or self._tick_no - last >= cooldown

    def _desired_role(self) -> Optional[str]:
        """Role for the next spawn in a disaggregated fleet: the
        scarcer of prefill/decode among non-draining replicas (ties go
        to decode — decode capacity bounds steady-state throughput).
        None (factory default) in a role-less fleet."""
        roles = [
            getattr(rep.engine, "role", "mixed")
            for rep in self.router.replicas()
            if rep.engine.health() is not Health.DRAINING
        ]
        if not any(r != "mixed" for r in roles):
            return None
        n_prefill = sum(r == "prefill" for r in roles)
        n_decode = sum(r == "decode" for r in roles)
        return "prefill" if n_prefill < n_decode else "decode"

    def _spawn(self, role: Optional[str] = None) -> int:
        if role is not None:
            try:
                eng = self.make_engine(role=role)
            except TypeError:
                # Factory predates roles (or hard-pins its own): spawn
                # role-less rather than refusing to scale.
                eng = self.make_engine()
        else:
            eng = self.make_engine()
        return self.router.add_replica(eng, version=self.version)

    def _decide(self, reason: str, n: int) -> str:
        _telemetry.counter("fleet.autoscale_decision", reason=reason).add()
        self.decisions.append((self._tick_no, reason, n))
        return reason

    # ------------------------------------------------------------------
    # Optional background loop

    def start(self, interval_s: float = 1.0) -> "Autoscaler":
        """Run :meth:`tick` on a daemon thread every ``interval_s``.
        Deployments with their own driver loop should call ``tick()``
        directly instead (deterministic, single-threaded)."""
        if self._thread is not None:
            return self
        self._stop_evt.clear()

        def _loop() -> None:
            while not self._stop_evt.wait(interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — scaling never kills serving
                    pass

        self._thread = threading.Thread(
            target=_loop, name="tdx-autoscale", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop_evt.set()
        self._thread.join(timeout=5.0)
        self._thread = None
