"""Zero-downtime weight hot-swap: deferred-init standby, drain, retire.

The paper's load-bearing feature — deferred-init shard-then-materialize
— used for what it was built for on the serving side: a model upgrade
with **zero dropped requests**.  Version v keeps serving while version
v+1 is *recorded* with zero allocation (:func:`deferred_init` — the
full architecture is inspectable before a byte is committed) and then
*materialized* straight into device arrays
(:func:`materialize_module_jax`, sharded if a mesh plan says so) for a
standby engine.  The swap choreography:

1. **Build** the standby: :func:`materialize_standby` (or any factory)
   produces v+1 parameters and an Engine over them.
2. **Admit** the standby into the router under the new version tag —
   from this moment new work may land on v+1.
3. **Shift admission**: the router's gate closes on every v replica
   (:meth:`~.router.FleetRouter.close_admission`) — new work now routes
   only to v+1.  This happens between chunks; no stream is interrupted.
4. **Migrate** what can move: in-flight v streams warm-migrate to any
   REMAINING same-version peer (:meth:`~.router.FleetRouter
   .migrate_out_streams` — KV pages ship at the page level, zero
   recompute; docs/fleet.md, "Disaggregation & stream migration").
   Migration is version-pinned, so when the swap retires the LAST v
   replica there is no compatible destination and every stream is
   simply left in place — skipped, not failed — for step 5.
5. **Drain** v gracefully (:meth:`~torchdistx_tpu.serving.engine.Engine
   .begin_drain` — PR 5's SIGTERM path, minus the signal): queued work
   flushes with retryable typed errors (the router re-routes it to v+1
   on its next pull — those requests have yielded nothing, so the
   version change is invisible), while in-flight streams that could
   not migrate **finish on their original engine** under the drain
   deadline.  Tokens from two versions never interleave within one
   stream.
6. **Retire**: each drained v engine is removed and ``close()``-d
   (idempotent on a STOPPED engine), its pages all returned.

A v stream that outlives the drain deadline fails with a *retryable*
``RequestPreempted`` — but having already yielded tokens it is
version-pinned, and with every v replica gone the router fails it
**typed** (:class:`~.router.NoReplicaAvailable`) rather than splicing a
v+1 continuation onto a v prefix.

Telemetry: the whole swap runs under a ``fleet.swap`` span and bumps
``fleet.swaps`` on success (docs/observability.md).
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from .. import telemetry as _telemetry
from ..serving.lifecycle import Health
from .router import FleetRouter

__all__ = ["hot_swap", "materialize_standby"]

_T_SWAPS = _telemetry.counter("fleet.swaps")


def materialize_standby(
    module_fn: Callable,
    *args,
    convert: Optional[Callable] = None,
    materialize_kwargs: Optional[dict] = None,
    **kwargs,
):
    """Build the next version's parameters while the current one serves.

    ``module_fn(*args, **kwargs)`` is constructed under
    :func:`~torchdistx_tpu.deferred_init.deferred_init` — every
    parameter fake, every initializer recorded, zero bytes allocated —
    then replayed as real ``jax.Array`` leaves by
    :func:`~torchdistx_tpu.materialize.materialize_module_jax`
    (``materialize_kwargs`` passes a mesh/plan through for sharded
    standbys).  ``convert`` maps the flat ``{qualified_name: array}``
    dict into a family pytree (e.g.
    :func:`~torchdistx_tpu.models.convert.llama_params_from_hf`).

    Torch imports happen here, lazily: a fleet that never hot-swaps
    never touches the deferred-init stack.
    """
    from .. import deferred_init as _di
    from ..materialize import materialize_module_jax

    module = _di.deferred_init(module_fn, *args, **kwargs)
    arrays = materialize_module_jax(module, **(materialize_kwargs or {}))
    return convert(arrays) if convert is not None else arrays


def hot_swap(
    router: FleetRouter,
    make_standby: Callable[[], object],
    *,
    version: str,
    retire: Optional[Iterable[int]] = None,
    max_steps: int = 200_000,
) -> int:
    """Upgrade the fleet to ``version`` with zero dropped requests.

    ``make_standby`` builds the v+1 engine (typically over parameters
    from :func:`materialize_standby`); ``retire`` names the replica ids
    to drain out (default: every replica whose version differs from
    ``version``).  Blocks (stepping the retiring engines) until they
    drain; ``max_steps`` bounds the wait — a stuck drain raises rather
    than spinning forever.  Returns the new replica's id.
    """
    sp = _telemetry.start_span("fleet.swap", version=version)
    try:
        standby = make_standby()
        if retire is None:
            old = [r for r in router.replicas() if r.version != version]
        else:
            retire = set(retire)
            old = [r for r in router.replicas() if r.rid in retire]
        new_rid = router.add_replica(standby, version=version)
        # Admission shifts to v+1 BEFORE the drain starts: from here no
        # new work lands on v, and the drain's queue flush re-routes
        # v's waiting requests (which have yielded nothing) to v+1.
        for rep in old:
            router.close_admission(rep.rid)
        # Warm-migrate in-flight v streams to surviving same-version
        # peers (a partial retire) before draining.  Version-pinned: a
        # full upgrade has no v peer left, migrate_out_streams skips
        # every stream, and the drain below finishes them in place.
        n_migrated = 0
        for rep in old:
            n_migrated += router.migrate_out_streams(rep.rid)["migrated"]
        for rep in old:
            rep.engine.begin_drain()
        steps = 0
        while any(
            rep.engine.health() is not Health.STOPPED for rep in old
        ):
            for rep in old:
                if rep.engine.health() is not Health.STOPPED:
                    rep.engine.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"hot swap to {version!r}: retiring engines did not "
                    f"drain within {max_steps} steps"
                )
        for rep in old:
            router.remove_replica(rep.rid)  # close() idempotent on STOPPED
        _T_SWAPS.add()
        sp.end(
            n_retired=len(old), new_replica=new_rid, steps=steps,
            n_migrated=n_migrated,
        )
        return new_rid
    except BaseException:
        sp.cancel()
        raise
