"""Block allocator for the paged KV cache (vLLM-style, host-side).

The device-side cache is a pool of ``num_blocks`` fixed-size pages per
layer (see :mod:`.cache`); this module owns the *map*: which pages are
free, which belong to which request.  All bookkeeping is host-side Python
— allocation happens once per request admit/finish, never per token, so
there is nothing to compile.

Page 0 is the **trash page**: it is never handed out, and every masked
write (prompt padding, retired-but-still-batched slots, position
overshoot) is steered into it.  Scribbling on trash is safe by
construction — the attention mask zeroes any read of a page outside a
slot's own table (:func:`torchdistx_tpu.ops.attention.paged_attention`).

Pages are **refcounted** (vLLM-style prefix sharing): ``alloc`` hands a
page out with one reference, ``share()`` adds references — the prefix
index and every request mapping a cached prefix hold one each — and
``free()`` removes one, returning the page to the free list only when
the last reference drops.  A page with more than one reference is
*shared*: writers must copy-on-write before touching it (the engine's
job; the allocator only exposes :meth:`refcount`).

Invariants (enforced, not assumed):

* ``alloc`` never hands out a page that still has references
  (double-assignment raises);
* ``free()``/``share()`` of a page with no references raises
  (double-free / stray free / stray share);
* exhaustion is a ``None`` return, not an exception — the scheduler turns
  it into backpressure (the request waits in the FIFO);
* ``utilization()``/``num_in_use`` count **physical** pages: a page
  shared by five requests is one page of HBM, not five.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import telemetry as _telemetry

__all__ = ["BlockAllocator", "TRASH_BLOCK", "blocks_needed"]

TRASH_BLOCK = 0

_G_UTIL = _telemetry.gauge("serve.block_util")
_G_SWAPPED = _telemetry.gauge("serve.swapped_pages")


def blocks_needed(n_tokens: int, block_size: int) -> int:
    """Pages needed to hold ``n_tokens`` cache slots."""
    return -(-n_tokens // block_size)


class BlockAllocator:
    """Free-list allocator over pages ``1 .. num_blocks-1`` (0 is trash)."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (page 0 is the trash page)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # LIFO free list: recently-freed (still-warm) pages are reused
        # first.  Deterministic: same admit/finish order → same tables.
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}  # page -> live reference count
        # Logical pages whose KV currently lives in a HOST buffer (the
        # QoS swap-to-host preemption path).  The physical pages were
        # freed — utilization()/num_in_use stay honest about HBM — and
        # this count is what keeps the *logical* picture honest: the
        # serve.swapped_pages gauge reports host-resident pages that
        # will want physical pages back at swap-in.
        self._n_swapped = 0

    @property
    def capacity(self) -> int:
        """Allocatable pages (the trash page doesn't count)."""
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_in_use(self) -> int:
        """PHYSICAL pages with at least one reference (shared pages count
        once — this is HBM occupancy, not the sum of refcounts)."""
        return len(self._ref)

    @property
    def num_swapped(self) -> int:
        """Logical pages currently swapped out to host buffers."""
        return self._n_swapped

    def refcount(self, blk: int) -> int:
        """Live references on ``blk`` (0 = free).  A result > 1 means the
        page is shared and a writer must copy-on-write first."""
        return self._ref.get(blk, 0)

    def utilization(self) -> float:
        """Fraction of allocatable pages currently owned (physical)."""
        return len(self._ref) / self.capacity

    def fragmentation(self) -> float:
        """Scatter of the free map in ``[0, 1]``: 0 when every free page
        sits in one contiguous id run (or nothing/everything is free),
        approaching 1 as the free pages splinter into single-page holes
        between allocations.  Paged attention itself is indifferent to
        contiguity — this is the *observability* estimate the HBM
        ledger exports (``mem.pool_fragmentation``): a pool that stays
        shattered under churn is a pool whose holes the allocator keeps
        cutting, the early signature of admission patterns that thrash
        pages.  Computed as ``1 - largest_free_run / num_free`` over
        sorted page ids — O(num_free), called per tick only with the
        ops plane attached."""
        n = len(self._free)
        if n <= 1:
            return 0.0
        ids = sorted(self._free)
        best = run = 1
        for a, b in zip(ids, ids[1:]):
            run = run + 1 if b == a + 1 else 1
            if run > best:
                best = run
        return 1.0 - best / n

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take ``n`` pages, or ``None`` if fewer than ``n`` are free
        (backpressure — never a partial grant)."""
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for blk in out:
            if blk in self._ref or blk == TRASH_BLOCK:
                raise RuntimeError(f"block allocator double-assigned page {blk}")
            self._ref[blk] = 1
        _G_UTIL.set(round(self.utilization(), 4))
        return out

    def share(self, blocks: List[int]) -> None:
        """Add one reference to each page (prefix-cache mapping: the page
        now also backs the sharer's block table).  Sharing a page with no
        live references raises — a cached page must already be owned by
        the index or a request."""
        for blk in blocks:
            if blk not in self._ref:
                raise RuntimeError(
                    f"sharing page {blk} that is not in use (stray share)"
                )
        for blk in blocks:
            self._ref[blk] += 1

    def reset(self) -> None:
        """Forget every grant and rebuild the full free list.

        The crash-recovery supervisor's primitive: when a failed device
        call consumes the donated page pool, every page's KV is gone and
        the ownership map with it — the supervisor installs a fresh pool
        and re-reserves pages per replayed request from a clean map.
        Page order matches a fresh allocator, so a deterministic replay
        produces deterministic tables."""
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._ref = {}
        # Host swap buffers die with the pool they were cut from: the
        # engine's recovery path converts swapped slots to replays.
        self._n_swapped = 0
        _G_UTIL.set(0.0)
        _G_SWAPPED.set(0)

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per page; a page whose LAST reference drops
        returns to the free list.  Freeing a page with no references
        raises (double free / stray free) — BEFORE any reference moves,
        so a failed call never half-applies."""
        counts: Dict[int, int] = {}
        for blk in blocks:
            counts[blk] = counts.get(blk, 0) + 1
        for blk, n in counts.items():
            if self._ref.get(blk, 0) < n:
                raise RuntimeError(
                    f"freeing page {blk} that is not in use (double free?)"
                )
        for blk in blocks:
            self._ref[blk] -= 1
            if self._ref[blk] == 0:
                del self._ref[blk]
                self._free.append(blk)
        _G_UTIL.set(round(self.utilization(), 4))

    # ------------------------------------------------------------------
    # Swap-to-host accounting (the QoS preemption path; see engine.py)

    def swap_out(self, blocks: List[int]) -> None:
        """Release ``blocks`` whose KV was copied to a host buffer: one
        reference drops per page (shared pages survive on their other
        references, exactly like :meth:`free`) and the count of
        host-resident logical pages rises.  The caller owns the host
        buffer; :meth:`swap_in` or :meth:`drop_swapped` settles the
        account."""
        self.free(blocks)
        self._n_swapped += len(blocks)
        _G_SWAPPED.set(self._n_swapped)

    def swap_in(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` physical pages for a host buffer coming back;
        ``None`` (nothing changes) when fewer than ``n`` are free."""
        got = self.alloc(n)
        if got is not None:
            self._n_swapped -= n
            _G_SWAPPED.set(self._n_swapped)
        return got

    def drop_swapped(self, n: int) -> None:
        """Forget ``n`` host-resident pages without re-allocating them:
        the swapped request was preempted to drop-and-replay, failed,
        or cancelled, and its host buffer was discarded."""
        if n > self._n_swapped:
            raise RuntimeError(
                f"dropping {n} swapped pages but only {self._n_swapped} "
                "are accounted (double drop?)"
            )
        self._n_swapped -= n
        _G_SWAPPED.set(self._n_swapped)
