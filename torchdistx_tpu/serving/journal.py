"""Crash-consistent request journal (WAL) + cold-restart resume.

The serving stack already survives everything short of process death by
record-then-replay: ``fold_in(key, n_gen)`` sampling means a stream's
*identity* is just ``(prompt, normalized key, model version, committed
tokens)``, and every preempt/recovery/migration resume re-prefills that
identity token-identically under digest verification.  This module
makes the same identity survive ``kill -9``: an append-only **request
journal** records it durably as the engine runs, and
:meth:`Engine.resume_from_journal` / :meth:`FleetRouter.recover`
re-admit every unfinished stream in a fresh process through the
existing replay machinery.

Record framing (torn-tail tolerant)
-----------------------------------

Every record is ``<u32 length> <u32 crc32(payload)> <payload>`` with a
compact-JSON payload.  A crash mid-append leaves a torn tail: a short
header, a short payload, or a checksum mismatch.  The reader treats all
three as end-of-segment — the truncated final record is *skipped*,
never misparsed — so recovery after power loss sees exactly the
prefix of records that were fully written.

Record types:

* ``config`` — the engine's sampling/chunk geometry, appended once per
  claim: a resume into a differently-configured engine would continue
  the stream with different tokens, so the mismatch must refuse loudly.
* ``admit``  — one request's replay identity (prompt ids, normalized
  key, model tag/version, tenant/priority, budget, wall-clock
  deadline), the same payload ``req.submitted`` carries.  A handoff
  admit (migration import, compaction checkpoint) additionally carries
  the committed tokens + digest snapshot.
* ``commit`` — one chunk boundary's newly committed tokens plus the
  rolling-digest snapshot after them.
* ``retire`` — terminal outcome (finished/failed/cancelled/expired/
  migrated); a retired uid is never resumed and compacts away.

Durability (``fsync=``)
-----------------------

* ``always`` — fsync after every append (each admission and chunk
  boundary is durable before the next device dispatch).
* ``tick``   — the default **group commit**: appends buffer in the OS;
  the engine calls :meth:`sync` once per tick, so one fsync covers the
  whole tick's records and the hot path never blocks per-record.
* ``async``  — never fsync explicitly; the OS flushes on its schedule.

An io failure at the ``journal.fsync`` fault site (or a real one)
**degrades the journal to async** and bumps ``journal.fsync_degraded``
— durability quietly weakens rather than the tick blocking or a
request failing on a disk hiccup.

Ownership (the double-resume guard)
-----------------------------------

A journal is resumed by exactly one engine: :meth:`claim` takes an
``owner.lock`` file (``O_CREAT | O_EXCL``) recording the claimant and
its pid.  A second live claimant gets a typed
:class:`.lifecycle.JournalOwned` refusal; a lock whose pid is dead is
stale (the crash this module exists for) and is stolen atomically.
Migration transfers ownership per-stream instead: the source journals
``retire(outcome="migrated")`` and the destination journals a handoff
admit into *its own* journal — a stream lives in exactly one journal.

Fault sites: ``journal.append`` (io fails one append — counted, the
request keeps running unjournaled), ``journal.fsync`` (degrades to
async, see above), ``journal.recover`` (io fails one recovery scan —
the caller sees the error, nothing is half-resumed).
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from .. import telemetry as _telemetry
from ..resilience import faults
from .lifecycle import JournalOwned

__all__ = [
    "JournalEntry",
    "RequestJournal",
    "read_records",
    "read_segment",
]

_HEADER = struct.Struct("<II")
# A torn length field must not make the reader "wait" for gigabytes
# that were never written: anything above this is treated as a torn
# tail.  Generous — a 1M-token prompt is ~8 MB of JSON.
_MAX_RECORD = 64 << 20
_SEGMENT_FMT = "segment-%06d.wal"
_LOCK_NAME = "owner.lock"

_T_APPENDS = _telemetry.counter("journal.appends")
_T_BYTES = _telemetry.counter("journal.bytes")
_T_APPEND_ERRORS = _telemetry.counter("journal.append_errors")
_T_FSYNCS = _telemetry.counter("journal.fsyncs")
_T_FSYNC_DEGRADED = _telemetry.counter("journal.fsync_degraded")
_T_ROTATIONS = _telemetry.counter("journal.rotations")
_T_COMPACTED = _telemetry.counter("journal.compacted_entries")
_T_TORN = _telemetry.counter("journal.torn_tails")
_T_RECOVERED = _telemetry.counter("journal.recovered_streams")
_T_RESUMED = _telemetry.counter("journal.resumed")
_T_RESUME_EXPIRED = _telemetry.counter("journal.resume_expired")


def _frame(payload: bytes) -> bytes:
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def _fsync_dir(path: str) -> None:
    """Durably record a directory entry (segment create/rename).  Best
    effort — not every platform allows fsync on a directory fd."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def read_segment(path: str) -> Tuple[List[dict], bool]:
    """Parse one segment; returns ``(records, torn)``.  A truncated or
    checksum-failing final record ends the scan cleanly (``torn=True``)
    — the records before it are exactly the durable prefix."""
    records: List[dict] = []
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    n = len(data)
    while off < n:
        if n - off < _HEADER.size:
            return records, True
        length, crc = _HEADER.unpack_from(data, off)
        if length > _MAX_RECORD or n - off - _HEADER.size < length:
            return records, True
        payload = data[off + _HEADER.size:off + _HEADER.size + length]
        if zlib.crc32(payload) != crc:
            return records, True
        try:
            rec = json.loads(payload)
        except ValueError:
            return records, True
        if isinstance(rec, dict):
            records.append(rec)
        off += _HEADER.size + length
    return records, False


def _segments(dirpath: str) -> List[str]:
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    segs = sorted(
        n for n in names if n.startswith("segment-") and n.endswith(".wal")
    )
    return [os.path.join(dirpath, n) for n in segs]


def read_records(dirpath: str) -> Iterator[dict]:
    """Every intact record across the journal's segments, in write
    order — the read-only scan ``incident_replay.py --journal`` and
    recovery share.  Torn tails are skipped (and counted) per segment."""
    for path in _segments(dirpath):
        records, torn = read_segment(path)
        if torn:
            _T_TORN.add()
        for rec in records:
            yield rec


@dataclass
class JournalEntry:
    """One request's journaled replay identity, folded over its
    admit/commit/retire records."""

    uid: int
    prompt: List[int] = field(default_factory=list)
    key: List[int] = field(default_factory=list)
    max_new_tokens: int = 0
    model_tag: str = "default"
    model_version: str = "v0"
    tenant: str = "default"
    priority: int = 0
    deadline_wall: Optional[float] = None
    trace_id: Optional[str] = None
    tokens: List[int] = field(default_factory=list)
    digest: Optional[str] = None
    retired: bool = False
    outcome: Optional[str] = None

    @property
    def n_gen(self) -> int:
        return len(self.tokens)


def fold_records(records) -> Tuple[Dict[int, JournalEntry], Optional[dict]]:
    """Fold a record stream into per-uid entries plus the LAST config
    record (a re-claimed journal appends one per claim; the newest
    engine geometry governs).  Order-tolerant: a retirement that lands
    one record before its chunk's trailing commit (a mid-chunk EOS)
    still folds to the full committed stream."""
    entries: Dict[int, JournalEntry] = {}
    config: Optional[dict] = None
    for rec in records:
        t = rec.get("t")
        if t == "config":
            config = rec
            continue
        uid = rec.get("u")
        if not isinstance(uid, int):
            continue
        if t == "admit":
            e = entries.setdefault(uid, JournalEntry(uid))
            e.prompt = [int(x) for x in rec.get("prompt", ())]
            e.key = [int(x) for x in rec.get("key", ())]
            e.max_new_tokens = int(rec.get("max_new", 0))
            e.model_tag = rec.get("model", "default")
            e.model_version = rec.get("version", "v0")
            e.tenant = rec.get("tenant", "default")
            e.priority = int(rec.get("priority", 0))
            e.deadline_wall = rec.get("deadline")
            e.trace_id = rec.get("trace")
            toks = rec.get("tokens")
            if toks:
                e.tokens = [int(x) for x in toks]
                e.digest = rec.get("d")
        elif t == "commit":
            e = entries.get(uid)
            if e is None:
                continue
            e.tokens.extend(int(x) for x in rec.get("toks", ()))
            e.digest = rec.get("d", e.digest)
        elif t == "retire":
            e = entries.get(uid)
            if e is None:
                continue
            e.retired = True
            e.outcome = rec.get("outcome")
            # The final uncommitted tail rides on the retire record
            # (retirement lands mid-chunk, before the trailing commit
            # would have run) — fold it so the entry holds the full
            # stream the client saw.
            e.tokens.extend(int(x) for x in rec.get("toks", ()))
            if rec.get("d"):
                e.digest = rec["d"]
    return entries, config


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True  # EPERM: alive, someone else's
    return True


class RequestJournal:
    """The crash-consistent request WAL one engine owns at a time.

    Construct it on a directory and pass it to ``Engine(journal=...)``;
    the engine claims ownership, appends a ``config`` record, and
    journals every admission, chunk commit, and retirement.  After a
    crash, construct a fresh engine on the same directory and call
    :meth:`Engine.resume_from_journal`."""

    def __init__(
        self,
        dirpath: str,
        *,
        fsync: str = "tick",
        rotate_bytes: int = 4 << 20,
    ):
        if fsync not in ("always", "tick", "async"):
            raise ValueError(
                f"fsync {fsync!r}: expected 'always', 'tick', or 'async'"
            )
        self.dir = str(dirpath)
        self.fsync = fsync
        self.degraded = False  # an io failure demoted fsync to 'async'
        self.rotate_bytes = int(rotate_bytes)
        if self.rotate_bytes < 4096:
            raise ValueError("rotate_bytes must be >= 4096")
        os.makedirs(self.dir, exist_ok=True)
        self._f = None  # active segment (open after claim)
        self._seg_no = 0
        self._dirty = False
        self._closed = False
        self._owner: Optional[str] = None
        # Live (unretired) entries, folded as we append: rotation
        # compacts the journal down to exactly these.
        self._live: Dict[int, JournalEntry] = {}
        self._config_rec: Optional[dict] = None
        self._next_uid = 1
        self._append_no = 0  # journal.append fault-site step
        self._fsync_no = 0  # journal.fsync fault-site step
        self._recover_no = 0  # journal.recover fault-site step
        self.n_segments_compacted = 0

    # -- ownership -----------------------------------------------------

    @property
    def _lock_path(self) -> str:
        return os.path.join(self.dir, _LOCK_NAME)

    def claim(self, owner: str) -> None:
        """Take exclusive ownership, or raise typed
        :class:`JournalOwned` if a LIVE claimant holds it.  A stale
        lock (dead pid — the crash this journal recovers from) is
        stolen atomically."""
        token = json.dumps({"owner": str(owner), "pid": os.getpid()})
        while True:
            try:
                fd = os.open(
                    self._lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY
                )
            except FileExistsError:
                holder = self._read_lock()
                if holder is not None and _pid_alive(holder.get("pid", -1)):
                    raise JournalOwned(
                        f"journal {self.dir!r} is owned by "
                        f"{holder.get('owner')!r} (pid {holder.get('pid')}, "
                        "alive); a stream is resumed by exactly one engine"
                    ) from None
                # Stale lock: steal by atomic replace so two stealers
                # cannot both think they won a torn write.
                tmp = self._lock_path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(token)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self._lock_path)
                break
            else:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    f.write(token)
                    f.flush()
                    os.fsync(f.fileno())
                break
        self._owner = str(owner)
        self._open_active_segment()

    def _read_lock(self) -> Optional[dict]:
        try:
            with open(self._lock_path, "r", encoding="utf-8") as f:
                data = json.loads(f.read())
            return data if isinstance(data, dict) else None
        except (OSError, ValueError):
            return None

    def release(self) -> None:
        """Drop ownership (close path).  Only the holder unlinks."""
        if self._owner is None:
            return
        holder = self._read_lock()
        if (
            holder is not None
            and holder.get("owner") == self._owner
            and holder.get("pid") == os.getpid()
        ):
            try:
                os.unlink(self._lock_path)
            except OSError:
                pass
        self._owner = None

    # -- the write path ------------------------------------------------

    def _seg_path(self, no: int) -> str:
        return os.path.join(self.dir, _SEGMENT_FMT % no)

    def _open_active_segment(self) -> None:
        segs = _segments(self.dir)
        if segs:
            last = os.path.basename(segs[-1])
            self._seg_no = int(last[len("segment-"):-len(".wal")])
            self._f = open(segs[-1], "ab")
        else:
            self._seg_no = 1
            # New segments are born durable: written under a tmp name,
            # fsynced, atomically renamed, directory entry fsynced —
            # a crash can leave a stray .tmp, never a torn segment.
            path = self._seg_path(self._seg_no)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            _fsync_dir(self.dir)
            self._f = open(path, "ab")

    def append(self, rec: dict) -> None:
        """Append one record (caller holds the claim).  Raises
        ``OSError`` on an io failure (injected or real) — the engine's
        wrappers count and carry on; durability is best-effort once the
        disk itself fails."""
        if self._closed or self._f is None:
            return
        self._append_no += 1
        faults.fire("journal.append", self._append_no)
        payload = json.dumps(rec, separators=(",", ":")).encode()
        framed = _frame(payload)
        self._f.write(framed)
        self._dirty = True
        _T_APPENDS.add()
        _T_BYTES.add(len(framed))
        self._fold_live(rec)
        if self.fsync == "always" and not self.degraded:
            self._do_fsync()
        if self._f.tell() >= self.rotate_bytes:
            self._rotate()

    def _fold_live(self, rec: dict) -> None:
        t = rec.get("t")
        uid = rec.get("u")
        if not isinstance(uid, int):
            return
        if t == "admit":
            entries, _ = fold_records((rec,))
            if uid in entries:
                self._live[uid] = entries[uid]
        elif t == "commit":
            live = self._live.get(uid)
            if live is not None:
                live.tokens.extend(int(x) for x in rec.get("toks", ()))
                live.digest = rec.get("d", live.digest)
        elif t == "retire":
            self._live.pop(uid, None)

    def committed_n(self, uid: int) -> int:
        """Committed-token count the WAL currently holds for ``uid`` —
        the retire path journals everything past this as the stream's
        final tail (retirement lands mid-chunk, before the chunk's
        trailing commit would have run)."""
        e = self._live.get(uid)
        return len(e.tokens) if e is not None else 0

    def _do_fsync(self) -> None:
        """One durability point.  An io failure — the ``journal.fsync``
        site or a real disk error — degrades the journal to async with
        a counter; it NEVER raises into the tick."""
        self._fsync_no += 1
        try:
            faults.fire("journal.fsync", self._fsync_no)
            self._f.flush()
            os.fsync(self._f.fileno())
        except OSError:
            self.degraded = True
            _T_FSYNC_DEGRADED.add()
            return
        self._dirty = False
        _T_FSYNCS.add()

    def sync(self) -> None:
        """The per-tick group commit (``fsync='tick'``): one fsync
        covers every record the tick appended.  No-op when clean,
        async, degraded, or closed."""
        if (
            self._closed
            or self._f is None
            or not self._dirty
            or self.degraded
            or self.fsync == "async"
        ):
            return
        self._do_fsync()

    def _rotate(self) -> None:
        """Seal the active segment and compact: the next segment opens
        with one checkpoint admit per LIVE entry (committed tokens +
        digest folded in), then every older segment unlinks — retired
        requests' records vanish.  The compacted segment is fully
        durable (tmp + fsync + rename) BEFORE anything is deleted."""
        old_segs = _segments(self.dir)
        self._f.flush()
        try:
            os.fsync(self._f.fileno())
        except OSError:
            pass
        self._f.close()
        self._seg_no += 1
        path = self._seg_path(self._seg_no)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            if self._config_rec is not None:
                f.write(_frame(json.dumps(
                    self._config_rec, separators=(",", ":")
                ).encode()))
            for uid in sorted(self._live):
                e = self._live[uid]
                rec = {
                    "t": "admit", "u": uid,
                    "prompt": e.prompt, "key": e.key,
                    "max_new": e.max_new_tokens,
                    "model": e.model_tag, "version": e.model_version,
                    "tenant": e.tenant, "priority": e.priority,
                    "deadline": e.deadline_wall, "trace": e.trace_id,
                }
                if e.tokens:
                    rec["tokens"] = e.tokens
                    rec["d"] = e.digest
                f.write(_frame(json.dumps(
                    rec, separators=(",", ":")
                ).encode()))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.dir)
        self._f = open(path, "ab")
        self._dirty = False
        for seg in old_segs:
            try:
                os.unlink(seg)
            except OSError:
                pass
        _T_ROTATIONS.add()
        _T_COMPACTED.add(len(old_segs))
        self.n_segments_compacted += len(old_segs)

    def write_config(self, **attrs) -> None:
        """Append the claiming engine's geometry (sampling config,
        chunk sizes): resume refuses a mismatched engine loudly rather
        than continuing streams with different tokens."""
        self._config_rec = {"t": "config", **attrs}
        try:
            self.append(self._config_rec)
        except OSError:
            _T_APPEND_ERRORS.add()

    def peek_config(self) -> Optional[dict]:
        """The LAST config record on disk (read-only — safe before a
        claim): the geometry the journaled streams were committed
        under, which a resuming engine must match."""
        cfg = None
        for rec in read_records(self.dir):
            if rec.get("t") == "config":
                cfg = rec
        return cfg

    def next_uid(self) -> int:
        uid = self._next_uid
        self._next_uid += 1
        return uid

    # -- recovery ------------------------------------------------------

    def recover(self) -> Tuple[Dict[int, JournalEntry], Optional[dict]]:
        """Scan every segment and return ``(unfinished, config)`` —
        the entries a cold restart must resume, and the geometry record
        the claiming engine wrote.  Also primes the live map and the
        uid mint so this journal continues where the dead process
        stopped.  ``journal.recover`` io faults raise out of here:
        nothing is half-resumed."""
        self._recover_no += 1
        faults.fire("journal.recover", self._recover_no)
        sp = _telemetry.start_span("journal.recover", dir=self.dir)
        entries, config = fold_records(read_records(self.dir))
        unfinished = {
            uid: e for uid, e in entries.items() if not e.retired
        }
        self._live = {
            uid: JournalEntry(
                uid, list(e.prompt), list(e.key), e.max_new_tokens,
                e.model_tag, e.model_version, e.tenant, e.priority,
                e.deadline_wall, e.trace_id, list(e.tokens), e.digest,
            )
            for uid, e in unfinished.items()
        }
        if entries:
            self._next_uid = max(entries) + 1
        if config is not None:
            self._config_rec = config
        _T_RECOVERED.add(len(unfinished))
        sp.end(n_entries=len(entries), n_unfinished=len(unfinished))
        return unfinished, config

    # -- lifecycle -----------------------------------------------------

    def close(self) -> None:
        """Flush, fsync (best effort), release the claim.  Idempotent;
        the segments stay on disk — a closed journal is a complete,
        fully-retired record of the run."""
        if self._closed:
            return
        self._closed = True
        if self._f is not None:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except OSError:
                pass
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
        self.release()

    def stats(self) -> dict:
        return {
            "dir": self.dir,
            "fsync": self.fsync,
            "degraded": self.degraded,
            "live": len(self._live),
            "segments": len(_segments(self.dir)),
            "segments_compacted": self.n_segments_compacted,
            "next_uid": self._next_uid,
        }
