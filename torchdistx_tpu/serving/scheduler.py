"""Request scheduler: FIFO admission, backpressure, streaming handles.

The scheduler owns the *waiting* side of the engine: a FIFO of submitted
requests, the prefill/decode interleave knob (``max_prefills_per_tick`` —
how many prompts may be prefilled per engine tick before the decode batch
runs; raising it favors TTFT, lowering it favors decode throughput), and
the backpressure rule: admission is head-of-line — if the head request's
page reservation does not fit the allocator's free list, nothing is
admitted this tick and the FIFO waits (no out-of-order admission, no
partial grants, no crash).  Both binding constraints are counted: a tick
stalled on pages AND a tick stalled on decode slots bump
``serve.backpressure`` (a slot-bound stall that telemetry cannot see is
indistinguishable from a healthy idle engine).

Lifecycle hooks (see :mod:`.lifecycle`): :meth:`FIFOScheduler.purge`
drops cancelled/deadline-expired requests from the waiting side at each
chunk boundary, :meth:`FIFOScheduler.requeue` puts requests back at the
FIFO *head* after a transient prefill failure (order preserved),
:meth:`FIFOScheduler.shed_oldest` implements the ``drop-oldest``
overload policy, and :meth:`FIFOScheduler.flush` empties the queue when
a drain begins.

:class:`RequestHandle` is the streaming API: ``handle.tokens()`` yields
tokens as the engine produces them, *driving* the engine while the caller
iterates — no background thread, so runs are deterministic and the engine
is single-threaded by construction (document, don't lock).  A request
that failed — cancelled, expired, shed, preempted by a drain, or beyond
its recovery budget — raises its typed :class:`.lifecycle.RequestError`
from ``tokens()``/``result()`` instead of truncating silently.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Iterator, List, Optional, Tuple

import numpy as np

from .. import telemetry as _telemetry
from .blocks import BlockAllocator, blocks_needed
from .lifecycle import RecoveryFailed

__all__ = ["FIFOScheduler", "Request", "RequestHandle"]

_T_BACKPRESSURE = _telemetry.counter("serve.backpressure")
_G_QUEUE = _telemetry.gauge("serve.queue_depth")


@dataclasses.dataclass
class Request:
    """One admitted unit of work (host-side bookkeeping only)."""

    rid: int
    prompt: np.ndarray  # (S,) int32
    max_new_tokens: int
    key: np.ndarray  # (2,) uint32 — the solo-generate-compatible PRNG key
    handle: "RequestHandle"
    deadline: Optional[float] = None  # absolute perf_counter() expiry
    submit_t: float = dataclasses.field(default_factory=time.perf_counter)
    blocks: Optional[List[int]] = None  # pages owned while running
    recoveries: int = 0  # replay budget consumed by the supervisor
    # Chunked-prefill state (owned by the engine while the request holds
    # a slot in the PREFILLING state):
    n_chunks: int = 1  # estimated prefill cost in chunks (TTFT estimate)
    table: Optional[np.ndarray] = None  # (M,) page table being filled
    prefill_pos: int = 0  # next prompt position to prefill
    n_cached: int = 0  # prompt tokens served from the prefix cache
    hashes: Optional[list] = None  # chained full-page hashes of the prompt
    hit_counted: bool = False  # prefix hit recorded (once per request)
    # QoS context (inert under the FIFO scheduler; see qos.py):
    tenant: str = "default"  # fair-queueing share owner
    priority: int = 0  # priority class — higher admits (and preempts) first
    # Model plane (see modelpool.py): which registered model serves this
    # request, and the version tag folded into its determinism digest —
    # per-model, so two models' digests of the same prompt can never
    # collide (audit isolation for free).
    model_tag: str = "default"
    model_version: str = "v0"
    # Parallel sampling (``submit(n=4)``): siblings share the parent's
    # prompt pages and diverge copy-on-write.  ``fork_of`` is the parent
    # rid (None for the parent itself / solo requests); ``fork_index``
    # is this request's position in the group — its sampling key is
    # ``fold_in(base_key, fork_index)``.
    fork_of: Optional[int] = None
    fork_index: int = 0
    # Trace context (see docs/observability.md, "Request tracing"): the
    # request-scoped id every req.* lifecycle event and serve.* span
    # carries.  A fleet submission pins one id across every failover hop
    # (hop counts re-submissions); a standalone engine mints
    # "{engine_id}-r{rid}" lazily, only once something is recording.
    trace_id: Optional[str] = None
    hop: int = 0
    # Phase-timing marks (engine-owned; feed the latency histograms):
    admit_t: Optional[float] = None  # first admission (queue-wait end)
    preempt_t: Optional[float] = None  # outage start (preempt/recovery)
    # Audit plane (docs/observability.md): the rolling determinism
    # digest over (prompt, key schedule, model version, committed
    # tokens) — created at submit, updated at every token commit,
    # verified at every resume.  ``audit_of`` marks a shadow-auditor
    # replay (the trace id of the request it re-executes): audit
    # replays are never themselves audited.
    digest: Optional[Any] = None
    audit_of: Optional[str] = None

    @property
    def cache_tokens(self) -> int:
        """KV slots this request reserves: every prompt + output position."""
        return len(self.prompt) + self.max_new_tokens

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    def replay_len(self) -> int:
        """Length of :meth:`replay_seq` without building it."""
        return len(self.prompt) + max(0, len(self.handle._tokens) - 1)

    def replay_seq(self) -> np.ndarray:
        """The sequence a (re-)prefill of this request must run.

        A fresh request prefills its prompt.  A request with committed
        tokens (a drop-and-replay preemption victim, or a supervisor
        replay) re-prefills ``prompt + tokens[:-1]``: every committed
        token but the last was already *fed* to the model, and the last
        is the slot's pending input token.  ``fold_in(key, n_gen)``
        sampling makes the continuation token-identical either way."""
        toks = self.handle._tokens
        if not toks:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(toks[:-1], np.int32)]
        ).astype(np.int32)


class RequestHandle:
    """Streaming view of one request's output."""

    def __init__(self, engine, rid: int):
        self._engine = engine
        self.rid = rid
        self._req = None  # back-ref for lifecycle events (engine sets it)
        self._tokens: List[int] = []
        self._done = False
        self._cancel_requested = False
        self.ttft_s: Optional[float] = None
        self.error: Optional[BaseException] = None
        # Parallel sampling (``submit(n=4)``): every handle of the group
        # carries the SAME list of all n sibling handles (index order);
        # None for solo requests.
        self.siblings: Optional[List["RequestHandle"]] = None

    @property
    def done(self) -> bool:
        return self._done

    @property
    def digest(self) -> Optional[str]:
        """Hex snapshot of the request's rolling determinism digest
        (docs/observability.md, "Audit plane"); None before submission
        wiring completes."""
        req = self._req
        if req is None or req.digest is None:
            return None
        return req.digest.hexdigest()

    def cancel(self) -> bool:
        """Request cancellation.  Takes effect at the next chunk
        boundary (waiting requests leave the queue, running requests
        release their pages); the handle then raises
        :class:`.lifecycle.RequestCancelled`.  Returns False (no-op) if
        the request already finished."""
        if self._done:
            return False
        self._cancel_requested = True
        return True

    def _push(self, token: int) -> None:
        self._tokens.append(token)

    def _event(self, name: str, **attrs) -> None:
        """Emit a lifecycle event for this request — the ONE funnel for
        terminal events, so every failure path (shed, drain flush,
        expiry, cancel, recovery exhaustion) closes the timeline without
        each call site remembering to.  Free for untraced requests
        (``trace_id`` stays None when nothing was recording at submit)."""
        req = self._req
        if req is None or req.trace_id is None:
            return
        _telemetry.event(
            name,
            rid=req.trace_id,
            engine=getattr(self._engine, "engine_id", None),
            hop=req.hop,
            **attrs,
        )

    def _finish(self) -> None:
        self._done = True
        req = self._req
        # Durability funnel: a journaled stream's retirement is recorded
        # the moment its handle goes terminal — same one-funnel rule as
        # the lifecycle events below (no-op without a journal).
        jr = getattr(self._engine, "_journal_retire", None)
        if jr is not None and req is not None:
            jr(req)
        if req is not None and req.trace_id is not None and (
            req.digest is not None
        ):
            # The digest snapshot is stamped ONLY on traced requests —
            # the disabled path formats no hex strings.
            self._event(
                "req.finished",
                n_tokens=len(self._tokens),
                digest=req.digest.hexdigest(),
            )
        else:
            self._event("req.finished", n_tokens=len(self._tokens))

    def _fail(self, error: BaseException) -> None:
        """Abort the request with a typed error (see :mod:`.lifecycle`):
        consumers see the exception instead of a silently truncated
        stream.  Idempotent — the FIRST terminal error wins: a stream
        whose deadline expires mid-migration is failed once by whichever
        side observes it first (source fallback or destination reap),
        never surfaced as two terminal events."""
        if self._done:
            return
        self.error = error
        self._done = True
        jr = getattr(self._engine, "_journal_retire", None)
        if jr is not None and self._req is not None:
            jr(self._req, error=error)
        self._event(
            "req.failed",
            error=type(error).__name__,
            retryable=bool(getattr(error, "retryable", False)),
            n_tokens=len(self._tokens),
        )
        if isinstance(error, RecoveryFailed):
            # Recovery exhaustion is exactly the post-mortem the flight
            # recorder exists for: dump the recent-records ring.
            _telemetry.flight_dump(
                "RecoveryFailed", rid=self._req.trace_id if self._req else None
            )

    def tokens(self) -> Iterator[int]:
        """Yield tokens as they are produced, stepping the engine while
        none are buffered.  Safe to interleave across handles — every
        ``step()`` advances all running requests.  Raises the request's
        typed error if it was aborted."""
        i = 0
        while True:
            while i < len(self._tokens):
                yield self._tokens[i]
                i += 1
            if self._done:
                if self.error is not None:
                    raise self.error
                return
            self._engine.step()

    def result(self) -> List[int]:
        """Block (by stepping the engine) until done; return all tokens —
        up to and including the first EOS, or ``max_new_tokens`` if EOS
        never fires (solo ``generate()``'s output truncated the same way).
        """
        for _ in self.tokens():
            pass
        return list(self._tokens)


class FIFOScheduler:
    """FIFO admission with head-of-line backpressure."""

    def __init__(self, max_prefills_per_tick: int = 1):
        if max_prefills_per_tick < 1:
            raise ValueError("max_prefills_per_tick must be >= 1")
        self.max_prefills_per_tick = max_prefills_per_tick
        self._waiting: deque = deque()
        self._engine_gauge = None  # serve.queue_depth{engine=}, see bind_engine

    def bind_engine(self, engine_id: str) -> None:
        """Mint the per-engine ``serve.queue_depth{engine=...}`` gauge.

        The unlabeled gauge is process-global: N replicas in one process
        clobber it (the PR-6 ``serve.health`` bug all over again), so a
        fleet — and the autoscaler's queue-slope predictor — reads the
        labeled family instead.  The owning engine calls this right
        after constructing its scheduler and prunes the family from the
        registry at STOPPED; a standalone scheduler stays unlabeled."""
        self._engine_gauge = _telemetry.gauge(
            "serve.queue_depth", engine=engine_id
        )
        self._engine_gauge.set(len(self._waiting))

    def _set_queue_gauge(self, n: int) -> None:
        _G_QUEUE.set(n)
        if self._engine_gauge is not None:
            self._engine_gauge.set(n)

    def __len__(self) -> int:
        return len(self._waiting)

    def pending_prefill_chunks(self) -> int:
        """Total prefill cost of the waiting queue, in chunks — the unit
        the TTFT estimate drains at (``max_prefills_per_tick`` chunks per
        tick).  A short prompt is one chunk; a 16k prompt behind a small
        ``prefill_chunk`` is many."""
        return sum(r.n_chunks for r in self._waiting)

    def push(self, req: Request) -> None:
        self._waiting.append(req)
        self._set_queue_gauge(len(self._waiting))

    def requeue(self, reqs: List[Request]) -> None:
        """Return ``reqs`` to the FIFO *head*, preserving their order —
        a transient prefill failure must not cost a request its place."""
        for req in reversed(reqs):
            self._waiting.appendleft(req)
        self._set_queue_gauge(len(self._waiting))

    def shed_oldest(self) -> Optional[Request]:
        """Pop the oldest waiting request (the ``drop-oldest`` overload
        policy's victim), or None if the queue is empty."""
        if not self._waiting:
            return None
        req = self._waiting.popleft()
        self._set_queue_gauge(len(self._waiting))
        return req

    def flush(self) -> List[Request]:
        """Empty the queue (drain start); returns the flushed requests."""
        out = list(self._waiting)
        self._waiting.clear()
        self._set_queue_gauge(0)
        return out

    def purge(self, now: float) -> Tuple[List[Request], List[Request]]:
        """Drop cancelled and deadline-expired requests from the waiting
        side.  Returns ``(expired, cancelled)`` for the engine to fail
        with their typed errors."""
        expired: List[Request] = []
        cancelled: List[Request] = []
        if not self._waiting:
            return expired, cancelled
        keep: deque = deque()
        for req in self._waiting:
            if req.handle._cancel_requested:
                cancelled.append(req)
            elif req.expired(now):
                expired.append(req)
            else:
                keep.append(req)
        if expired or cancelled:
            self._waiting = keep
            self._set_queue_gauge(len(keep))
        return expired, cancelled

    def pop_admissible(
        self,
        n_free_slots: int,
        allocator: BlockAllocator,
        block_size: int,
        reclaim: Optional[Callable[[int], int]] = None,
        need: Optional[Callable[[Request], int]] = None,
        ready: Optional[Callable[[Request], bool]] = None,
    ) -> List[Request]:
        """Pop up to ``max_prefills_per_tick`` requests that fit the free
        slots AND whose cumulative page reservations fit the free list.
        Stops at the first head that doesn't fit (FIFO order is the
        fairness guarantee; skipping ahead would starve long prompts).
        Every stalled tick with work waiting counts — whether pages or
        slots are the binding constraint.

        ``reclaim(n)``, when given, is asked to free up to ``n`` more
        pages before a head is declared unadmittable — the engine wires
        it to prefix-cache LRU eviction, so cached-but-unreferenced
        pages never cause an admission stall that an empty cache would
        not.  The reservation check is conservative (the head's FULL
        page quota, ignoring any prefix it may share): a cache hit can
        only admit *no later* than a cache-off engine would.

        ``need(req)``, when given, overrides the reservation estimate —
        the engine wires the model plane's fork accounting through it
        (a sibling whose parent's prompt pages are live reserves only
        its marginal pages).  ``ready(req)``, when given, gates the
        head: a False head stalls admission WITHOUT being popped (a
        cold model whose weights are still materializing — the engine
        materializes out-of-band and the head admits next tick).  The
        head-of-line rule is deliberate: skipping past a cold head
        would reorder the FIFO, and the stall is one materialize long,
        not a starvation risk."""
        out: List[Request] = []
        limit = min(self.max_prefills_per_tick, n_free_slots)
        if self._waiting and limit == 0:
            _T_BACKPRESSURE.add()  # slot-bound stall, visible like a page-bound one
            return out
        reserved = 0
        while self._waiting and len(out) < limit:
            head = self._waiting[0]
            if ready is not None and not ready(head):
                break  # cold model: the engine counts + materializes
            n_pages = (
                need(head) if need is not None
                else blocks_needed(head.cache_tokens, block_size)
            )
            avail = allocator.num_free - reserved
            if n_pages > avail and reclaim is not None:
                reclaim(n_pages - avail)
                avail = allocator.num_free - reserved
            if n_pages > avail:
                _T_BACKPRESSURE.add()
                break
            reserved += n_pages
            out.append(self._waiting.popleft())
        self._set_queue_gauge(len(self._waiting))
        return out
