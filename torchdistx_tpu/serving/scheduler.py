"""Request scheduler: FIFO admission, backpressure, streaming handles.

The scheduler owns the *waiting* side of the engine: a FIFO of submitted
requests, the prefill/decode interleave knob (``max_prefills_per_tick`` —
how many prompts may be prefilled per engine tick before the decode batch
runs; raising it favors TTFT, lowering it favors decode throughput), and
the backpressure rule: admission is head-of-line — if the head request's
page reservation does not fit the allocator's free list, nothing is
admitted this tick and the FIFO waits (no out-of-order admission, no
partial grants, no crash).

:class:`RequestHandle` is the streaming API: ``handle.tokens()`` yields
tokens as the engine produces them, *driving* the engine while the caller
iterates — no background thread, so runs are deterministic and the engine
is single-threaded by construction (document, don't lock).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Iterator, List, Optional

import numpy as np

from .. import telemetry as _telemetry
from .blocks import BlockAllocator, blocks_needed

__all__ = ["FIFOScheduler", "Request", "RequestHandle"]

_T_BACKPRESSURE = _telemetry.counter("serve.backpressure")
_G_QUEUE = _telemetry.gauge("serve.queue_depth")


@dataclasses.dataclass
class Request:
    """One admitted unit of work (host-side bookkeeping only)."""

    rid: int
    prompt: np.ndarray  # (S,) int32
    max_new_tokens: int
    key: np.ndarray  # (2,) uint32 — the solo-generate-compatible PRNG key
    handle: "RequestHandle"
    submit_t: float = dataclasses.field(default_factory=time.perf_counter)
    blocks: Optional[List[int]] = None  # pages owned while running

    @property
    def cache_tokens(self) -> int:
        """KV slots this request reserves: every prompt + output position."""
        return len(self.prompt) + self.max_new_tokens


class RequestHandle:
    """Streaming view of one request's output."""

    def __init__(self, engine, rid: int):
        self._engine = engine
        self.rid = rid
        self._tokens: List[int] = []
        self._done = False
        self.ttft_s: Optional[float] = None
        self.error: Optional[str] = None

    @property
    def done(self) -> bool:
        return self._done

    def _push(self, token: int) -> None:
        self._tokens.append(token)

    def _finish(self) -> None:
        self._done = True

    def _fail(self, msg: str) -> None:
        """Abort the request (e.g. its KV was lost to a failed device
        call): consumers see a ``RuntimeError`` instead of a silent
        truncated stream."""
        self.error = msg
        self._done = True

    def tokens(self) -> Iterator[int]:
        """Yield tokens as they are produced, stepping the engine while
        none are buffered.  Safe to interleave across handles — every
        ``step()`` advances all running requests.  Raises if the request
        was aborted."""
        i = 0
        while True:
            while i < len(self._tokens):
                yield self._tokens[i]
                i += 1
            if self._done:
                if self.error is not None:
                    raise RuntimeError(
                        f"request {self.rid} aborted: {self.error}"
                    )
                return
            self._engine.step()

    def result(self) -> List[int]:
        """Block (by stepping the engine) until done; return all tokens —
        up to and including the first EOS, or ``max_new_tokens`` if EOS
        never fires (solo ``generate()``'s output truncated the same way).
        """
        for _ in self.tokens():
            pass
        return list(self._tokens)


class FIFOScheduler:
    """FIFO admission with head-of-line backpressure."""

    def __init__(self, max_prefills_per_tick: int = 1):
        if max_prefills_per_tick < 1:
            raise ValueError("max_prefills_per_tick must be >= 1")
        self.max_prefills_per_tick = max_prefills_per_tick
        self._waiting: deque = deque()

    def __len__(self) -> int:
        return len(self._waiting)

    def push(self, req: Request) -> None:
        self._waiting.append(req)
        _G_QUEUE.set(len(self._waiting))

    def pop_admissible(
        self,
        n_free_slots: int,
        allocator: BlockAllocator,
        block_size: int,
    ) -> List[Request]:
        """Pop up to ``max_prefills_per_tick`` requests that fit the free
        slots AND whose cumulative page reservations fit the free list.
        Stops at the first head that doesn't fit (FIFO order is the
        fairness guarantee; skipping ahead would starve long prompts)."""
        out: List[Request] = []
        free_pages = allocator.num_free
        while (
            self._waiting
            and len(out) < min(self.max_prefills_per_tick, n_free_slots)
        ):
            need = blocks_needed(self._waiting[0].cache_tokens, block_size)
            if need > free_pages:
                _T_BACKPRESSURE.add()
                break
            free_pages -= need
            out.append(self._waiting.popleft())
        _G_QUEUE.set(len(self._waiting))
        return out
