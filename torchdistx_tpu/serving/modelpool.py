"""Model plane: many models on one engine, one page pool.

The paper's load-bearing feature — record a model's construction at
near-zero cost, materialize on demand — applied to serving capacity
instead of startup time.  An :class:`~.engine.Engine` is married to one
*pool geometry* (layers × block_size × kv-heads × head-dim — what a KV
page looks like), not to one set of weights: any model whose pages look
the same can decode into the same pool.  The :class:`ModelPool` holds N
such models over one engine:

* **register** — a model enters as a *skeleton*: its parameter pytree as
  shapes/dtypes only (:func:`jax.eval_shape` over the materialize
  factory, or the family's ``abstract_params``), near-zero HBM, fully
  inspectable geometry.  Registration validates pool-geometry
  compatibility up front — an incompatible model is rejected at
  register time, not at first traffic.
* **materialize on demand** — the first ``submit(model=...)`` for a
  cold model queues it; the engine materializes the weights *between*
  decode ticks (one model per tick, after the decode dispatch), so a
  cold model's materialize stall never blocks a hot model's decode.
  The ``serve.materialize`` fault site fires per attempt; a transient
  (``io``) failure leaves the skeleton untouched and retries next tick.
* **evict under pressure** — materializing over ``hbm_budget_bytes``
  (or ``max_resident``) first drops the least-recently-used *cold*
  models' weights.  "Cold" is checked against live engine state — a
  model with any slot (running, prefilling, or swapped out) is never
  evicted; queued-only demand is safe to drop because admission
  re-demands materialization.  The policy reads the HBM ledger's real
  per-owner rows (:meth:`~torchdistx_tpu.telemetry.perf.Ledger.owners`:
  ``weights`` vs ``kv_pool`` vs ``prefix_cache_held``), not estimates.
  Eviction drops weights only; KV pages, streams, and the prefix index
  are untouched.

Determinism is per model: every registered model carries its own
``model_version``, folded into every request digest
(:class:`~torchdistx_tpu.telemetry.audit.DeterminismDigest`), so the
same prompt under two models yields distinct digests and the shadow
auditor can never cross-check.  The prefix index is model-namespaced
the same way (:func:`~.prefix.page_hashes` seeds its chain with the
model tag) — two models never share a KV page even for identical
prompts.

Telemetry: ``serve.models_resident{engine=}``,
``serve.model_state{engine=,model=}`` (0 skeleton / 1 materialized),
``serve.materializations`` / ``serve.model_evictions`` (global and
``{engine=,model=}``-labeled), ``serve.materialize_s{engine=}`` (stall
histogram), ``mem.hbm_bytes{component=weights}`` rows per model owner,
and ``model.registered`` / ``model.materialized`` / ``model.evicted``
lifecycle events under a ``serve.materialize`` span.  All per-engine
families are pruned when the engine stops.  Full design:
docs/serving.md, "Model plane".
"""

from __future__ import annotations

import math
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from .. import telemetry as _telemetry
from ..resilience import faults
from ..telemetry import perf as _perf

__all__ = ["DEFAULT_MODEL", "ModelPool"]

# The engine's own construction-time model: always materialized, never
# evictable, namespace b"" (single-model prefix hashes are unchanged).
DEFAULT_MODEL = "default"

_T_MATERIALIZATIONS = _telemetry.counter("serve.materializations")
_T_EVICTIONS = _telemetry.counter("serve.model_evictions")
_T_MODEL_STALLS = _telemetry.counter("serve.model_stalls")


class _ModelEntry:
    """One registered model: skeleton always, weights sometimes."""

    __slots__ = (
        "tag",
        "model",
        "cfg",
        "model_version",
        "materialize",
        "skeleton",
        "nbytes_estimate",
        "params",
        "params_nbytes",
        "last_used",
        "materializations",
        "evictions",
    )

    def __init__(self, tag, model, cfg, model_version, materialize,
                 skeleton, nbytes_estimate):
        self.tag = tag
        self.model = model
        self.cfg = cfg
        self.model_version = model_version
        self.materialize = materialize
        self.skeleton = skeleton
        self.nbytes_estimate = nbytes_estimate
        self.params = None  # prepped weights while materialized
        self.params_nbytes = 0
        self.last_used = 0  # LRU clock value of the latest demand
        self.materializations = 0
        self.evictions = 0

    @property
    def ready(self) -> bool:
        return self.params is not None

    @property
    def namespace(self) -> bytes:
        """Prefix-chain seed: pages are content-addressed per model."""
        return self.tag.encode("utf-8")


def _skeleton_nbytes(skeleton) -> int:
    """Exact weight bytes from shapes/dtypes alone — the inspectable
    half of deferred init: cost known before a byte is committed."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(skeleton):
        total += int(math.prod(leaf.shape)) * leaf.dtype.itemsize
    return total


def _abstract_pool_geometry(model, cfg, block_size: int) -> tuple:
    """What :func:`~.cache.pool_geometry` would say about a pool built
    for ``model``/``cfg`` — from :func:`jax.eval_shape` only, no
    allocation.  Must match the engine's live pool for the model to be
    servable from it."""
    import jax

    proto = jax.eval_shape(lambda: model.init_cache(cfg, 1, 1))

    def page(leaf):
        n_layers, _, _, heads, head_dim = leaf.shape
        return jax.ShapeDtypeStruct(
            (n_layers, 1, block_size, heads, head_dim), leaf.dtype
        )

    abstract = jax.tree.map(page, proto)
    leaves, treedef = jax.tree.flatten(abstract)
    return (
        str(treedef),
        tuple(
            (x.shape[0],) + tuple(x.shape[2:]) + (str(x.dtype),)
            for x in leaves
        ),
    )


class ModelPool:
    """Deferred-init skeleton registry + weight residency manager for
    one engine.

    Construct, register models, then hand to
    ``Engine(..., model_pool=pool)``; the engine binds the pool
    (validating every registered skeleton against its live pool
    geometry) and routes ``submit(model=tag)`` traffic through it.
    ``register`` also works after binding — models can join a serving
    engine at runtime, skeleton-first.

    ``hbm_budget_bytes`` caps the ledger total (weights + kv_pool +
    prefix_cache_held + everything else registered) the pool will
    materialize into: crossing it evicts LRU cold models first.  The
    budget is a pressure threshold, not a hard wall — if every other
    model is pinned by live streams the demanded model still
    materializes (serving beats strict accounting; the ledger records
    the truth either way).  ``max_resident`` is the count-based
    equivalent (N materialized pool models max); either, both, or
    neither may be set.
    """

    def __init__(
        self,
        *,
        hbm_budget_bytes: Optional[int] = None,
        max_resident: Optional[int] = None,
    ):
        if hbm_budget_bytes is not None and hbm_budget_bytes <= 0:
            raise ValueError("hbm_budget_bytes must be positive")
        if max_resident is not None and max_resident < 1:
            raise ValueError("max_resident must be >= 1")
        self.hbm_budget_bytes = hbm_budget_bytes
        self.max_resident = max_resident
        self._entries: "OrderedDict[str, _ModelEntry]" = OrderedDict()
        self._engine = None
        self._clock = 0
        self._materialize_no = 0  # serve.materialize fault-site attempts
        self.materialize_retries = 0
        # Per-engine labeled families (minted at bind, pruned at close):
        self._g_resident = None
        self._h_materialize = None
        self._g_state: Dict[str, Any] = {}
        self._c_requests: Dict[str, Any] = {}
        self._c_tokens: Dict[str, Any] = {}

    # ------------------------------------------------------------------
    # Registry

    def register(
        self,
        tag: str,
        *,
        model,
        cfg,
        materialize: Callable[[], Any],
        model_version: Optional[str] = None,
        skeleton=None,
    ) -> None:
        """Admit a model as a skeleton — near-zero HBM until demanded.

        ``materialize()`` must return the family parameter pytree for
        ``model`` (e.g. ``lambda: llama.init_params(key, cfg)``, a
        checkpoint load, or a deferred-init torch replay via
        :func:`~torchdistx_tpu.fleet.hot_swap.materialize_standby`).
        ``skeleton`` overrides the shape probe for factories
        :func:`jax.eval_shape` cannot trace (torch tape replays); by
        default the family's ``abstract_params(cfg)`` is used when
        present, else the factory is shape-traced.  ``model_version``
        defaults to the tag — it seeds every request digest, so two
        registered models can never produce colliding digests.
        """
        import jax

        if not tag or tag == DEFAULT_MODEL:
            raise ValueError(
                f"model tag must be non-empty and not {DEFAULT_MODEL!r} "
                "(the engine's own model)"
            )
        if tag in self._entries:
            raise ValueError(f"model {tag!r} already registered")
        if skeleton is None:
            abstract = getattr(model, "abstract_params", None)
            skeleton = (
                abstract(cfg) if abstract is not None
                else jax.eval_shape(materialize)
            )
        entry = _ModelEntry(
            tag=tag,
            model=model,
            cfg=cfg,
            model_version=model_version if model_version is not None else tag,
            materialize=materialize,
            skeleton=skeleton,
            nbytes_estimate=_skeleton_nbytes(skeleton),
        )
        if self._engine is not None:
            self._check_geometry(entry)
        self._entries[tag] = entry
        if self._engine is not None:
            self._mint_model_metrics(entry)
        _telemetry.event(
            "model.registered",
            model=tag,
            version=entry.model_version,
            nbytes=entry.nbytes_estimate,
            n_leaves=len(jax.tree.leaves(skeleton)),
            engine=getattr(self._engine, "engine_id", None),
        )

    def tags(self) -> List[str]:
        return list(self._entries)

    def __contains__(self, tag: str) -> bool:
        return tag in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def ready(self, tag: str) -> bool:
        """True when ``tag``'s weights are resident (admissible now)."""
        return self._entries[tag].ready

    def geometry(self, tag: str) -> Dict[str, Any]:
        """The skeleton's inspectable geometry — what deferred init
        promises: full architecture knowledge at near-zero cost, before
        (or instead of) paying for the weights."""
        import jax

        entry = self._entries[tag]
        leaves = jax.tree.leaves(entry.skeleton)
        return {
            "tag": tag,
            "version": entry.model_version,
            "materialized": entry.ready,
            "n_leaves": len(leaves),
            "n_params": sum(int(math.prod(x.shape)) for x in leaves),
            "nbytes": entry.nbytes_estimate,
        }

    # ------------------------------------------------------------------
    # Engine binding

    def _bind(self, engine) -> None:
        """Called by ``Engine.__init__``: validate every skeleton
        against the live pool geometry and mint the per-engine labeled
        telemetry families."""
        if self._engine is not None:
            raise ValueError(
                "ModelPool is already bound to an engine — one pool "
                "serves one engine (its weights ledger rows and labeled "
                "metric families are per-engine)"
            )
        self._engine = engine
        for entry in self._entries.values():
            self._check_geometry(entry)
        eid = engine.engine_id
        self._g_resident = _telemetry.gauge(
            "serve.models_resident", engine=eid
        )
        self._g_resident.set(0)
        self._h_materialize = _telemetry.histogram(
            "serve.materialize_s", engine=eid
        )
        for entry in self._entries.values():
            self._mint_model_metrics(entry)

    def _check_geometry(self, entry: _ModelEntry) -> None:
        from .cache import pool_geometry

        eng = self._engine
        want = pool_geometry(eng._cache)
        got = _abstract_pool_geometry(
            entry.model, entry.cfg, eng.block_size
        )
        if got != want:
            raise ValueError(
                f"model {entry.tag!r} cannot share engine "
                f"{eng.engine_id}'s page pool: KV page geometry {got} "
                f"!= pool geometry {want} (layers/heads/head-dim/dtype "
                "must match; block_size already does by construction)"
            )

    def _mint_model_metrics(self, entry: _ModelEntry) -> None:
        eid = self._engine.engine_id
        tag = entry.tag
        self._g_state[tag] = _telemetry.gauge(
            "serve.model_state", engine=eid, model=tag
        )
        self._g_state[tag].set(1 if entry.ready else 0)
        self._c_requests[tag] = _telemetry.counter(
            "serve.model_requests", engine=eid, model=tag
        )
        self._c_tokens[tag] = _telemetry.counter(
            "serve.model_tokens", engine=eid, model=tag
        )

    # ------------------------------------------------------------------
    # Residency

    def _touch(self, tag: str) -> _ModelEntry:
        """Record demand (the LRU clock) and return the entry."""
        entry = self._entries[tag]
        self._clock += 1
        entry.last_used = self._clock
        return entry

    def _note_request(self, tag: str) -> None:
        c = self._c_requests.get(tag)
        if c is not None:
            c.add()

    def _note_tokens(self, tag: str, n: int) -> None:
        c = self._c_tokens.get(tag)
        if c is not None and n:
            c.add(n)

    def _note_stall(self, tag: str) -> None:
        """An admission tick held back by ``tag`` being cold."""
        _T_MODEL_STALLS.add()

    def resident(self) -> List[str]:
        return [t for t, e in self._entries.items() if e.ready]

    def _owner_key(self, tag: str) -> str:
        return f"model:{self._engine.engine_id}:{tag}"

    def ensure(self, tag: str):
        """Materialize ``tag`` if cold (evicting under pressure first);
        return its entry.  The engine calls this from its
        materialize phase — after the tick's decode dispatch, one model
        per tick — but it is also the public warm-up hook: call it
        before opening traffic to take the stall off the first request.
        """
        import jax

        if self._engine is None:
            raise ValueError("ModelPool.ensure before binding an engine")
        entry = self._touch(tag)
        if entry.ready:
            return entry
        self._evict_for(entry)
        self._materialize_no += 1
        sp = _telemetry.start_span(
            "serve.materialize", model=tag, engine=self._engine.engine_id
        )
        t0 = time.perf_counter()
        try:
            # The fault site fires INSIDE the span with nothing
            # allocated and nothing registered: a kill here (the
            # chaos drill's crash kind) leaves only the skeleton, so
            # recovery re-enters exactly like a first demand.
            kind = faults.fire("serve.materialize", self._materialize_no)
            if kind is not None:  # nan/corrupt cooperation: attempt poisoned
                raise faults.InjectedFault(
                    f"injected {kind} fault at serve.materialize:"
                    f"{self._materialize_no}"
                )
            params = entry.materialize()
            prep = getattr(entry.model, "prep_decode", None)
            if prep is not None:
                params = prep(params, entry.cfg)
            params = jax.block_until_ready(params)
        except BaseException:
            sp.cancel()
            raise
        stall_s = time.perf_counter() - t0
        entry.params = params
        entry.params_nbytes = _perf.pytree_nbytes(params)
        entry.materializations += 1
        _T_MATERIALIZATIONS.add()
        _perf.ledger.register(
            "weights", entry.params_nbytes, owner=self._owner_key(tag)
        )
        if self._h_materialize is not None:
            self._h_materialize.observe(stall_s)
        g = self._g_state.get(tag)
        if g is not None:
            g.set(1)
        if self._g_resident is not None:
            self._g_resident.set(len(self.resident()))
        _telemetry.event(
            "model.materialized",
            model=tag,
            version=entry.model_version,
            nbytes=entry.params_nbytes,
            stall_s=round(stall_s, 6),
            engine=self._engine.engine_id,
        )
        sp.end(nbytes=entry.params_nbytes, stall_s=round(stall_s, 6))
        return entry

    def evict(self, tag: str) -> bool:
        """Drop ``tag``'s weights back to the skeleton.  Refuses (False)
        while any live stream — running, prefilling, or swapped-out
        slot — is on the model; queued requests re-demand
        materialization at admission, so they never pin weights."""
        entry = self._entries[tag]
        if not entry.ready:
            return False
        if self._engine is not None and self._engine._model_in_use(tag):
            return False
        nbytes = entry.params_nbytes
        entry.params = None
        entry.params_nbytes = 0
        entry.evictions += 1
        _T_EVICTIONS.add()
        _perf.ledger.unregister("weights", owner=self._owner_key(tag))
        g = self._g_state.get(tag)
        if g is not None:
            g.set(0)
        if self._g_resident is not None:
            self._g_resident.set(len(self.resident()))
        _telemetry.event(
            "model.evicted",
            model=tag,
            version=entry.model_version,
            nbytes=nbytes,
            engine=getattr(self._engine, "engine_id", None),
        )
        return True

    def _evict_for(self, incoming: _ModelEntry) -> int:
        """Make room for ``incoming`` under the residency knobs: evict
        LRU cold models until under budget (or nothing cold remains).
        Returns models evicted."""
        evicted = 0
        while True:
            over = False
            if self.max_resident is not None:
                over = len(self.resident()) >= self.max_resident
            if not over and self.hbm_budget_bytes is not None:
                # Real ledger rows, per owner: this pool's weights plus
                # everything else attributed on the device (the
                # engine's kv_pool, its own weights, prefix pages,
                # swap buffers) — pressure is against what is actually
                # held, not against a private estimate.
                held = sum(_perf.ledger.owners().values())
                over = held + incoming.nbytes_estimate > self.hbm_budget_bytes
            if not over:
                return evicted
            victim = None
            for entry in self._entries.values():
                if not entry.ready or entry is incoming:
                    continue
                if self._engine is not None and self._engine._model_in_use(
                    entry.tag
                ):
                    continue
                if victim is None or entry.last_used < victim.last_used:
                    victim = entry
            if victim is None:
                # Everything resident is pinned by live streams: serve
                # the demand anyway (the budget is pressure, not a
                # wall) — the ledger keeps the overage honest.
                return evicted
            self.evict(victim.tag)
            evicted += 1

    # ------------------------------------------------------------------
    # Teardown / introspection

    def _close(self) -> None:
        """Engine stop: drop every weight, unregister every ledger row,
        prune every per-engine labeled family."""
        for entry in self._entries.values():
            if entry.ready:
                entry.params = None
                entry.params_nbytes = 0
                _perf.ledger.unregister(
                    "weights", owner=self._owner_key(entry.tag)
                )
        if self._engine is not None:
            eid = self._engine.engine_id
            _telemetry.remove("serve.models_resident", engine=eid)
            for tag in self._entries:
                _telemetry.remove("serve.model_state", engine=eid, model=tag)
                _telemetry.remove(
                    "serve.model_requests", engine=eid, model=tag
                )
                _telemetry.remove("serve.model_tokens", engine=eid, model=tag)
        self._g_resident = None
        self._h_materialize = None
        self._g_state.clear()
        self._c_requests.clear()
        self._c_tokens.clear()

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "n_registered": len(self._entries),
            "n_resident": len(self.resident()),
            "materialize_retries": self.materialize_retries,
            "models": {},
        }
        if self._h_materialize is not None and self._h_materialize.count:
            out["materialize_p95_s"] = round(
                self._h_materialize.percentile(95), 6
            )
        for tag, entry in self._entries.items():
            out["models"][tag] = {
                "materialized": entry.ready,
                "version": entry.model_version,
                "nbytes": (
                    entry.params_nbytes if entry.ready
                    else entry.nbytes_estimate
                ),
                "materializations": entry.materializations,
                "evictions": entry.evictions,
            }
        return out
