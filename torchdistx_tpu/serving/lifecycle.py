"""Request-lifecycle robustness: typed errors, engine health, overload.

The serving engine's failure surface, made first-class (the serving
analog of what :mod:`torchdistx_tpu.resilience` did for training).
Production continuous-batching systems (vLLM, Orca) treat admission
control and failure recovery as part of the scheduler contract, not as
exception noise — a caller must be able to tell, from the *type* of a
failure, whether to retry the request elsewhere (`retryable=True`:
overload shed, drain preemption), fix the request (validation errors
raise plain ``ValueError`` at ``submit``), or give up (deadline,
cancellation, exhausted recovery budget).

Three pieces live here:

* the **typed error taxonomy** — every way a submitted request can fail
  is a :class:`RequestError` subclass carrying ``retryable``; handles
  raise these from ``tokens()``/``result()`` instead of bare
  ``RuntimeError`` strings;
* the **health state machine** — :class:`Health`:
  ``STARTING → READY → DRAINING → STOPPED``, plus ``OVERLOADED`` as a
  READY-adjacent pressure state.  ``Engine.health()`` exposes it and the
  ``serve.health`` gauge tracks every transition;
* the **overload detector** — :class:`OverloadDetector`: queue depth
  against a bounded queue plus estimated time-to-first-token from an
  EWMA of tick duration.  The engine consults it at ``submit`` to drive
  the shedding policy (``reject-new`` | ``drop-oldest``).
"""

from __future__ import annotations

import enum
from typing import Optional

__all__ = [
    "DeadlineExceeded",
    "DeterminismDiverged",
    "EngineDraining",
    "EngineOverloaded",
    "Health",
    "JournalOwned",
    "MigrationIncompatible",
    "OverloadDetector",
    "RecoveryFailed",
    "RequestCancelled",
    "RequestError",
    "RequestPreempted",
]


class Health(enum.Enum):
    """Engine lifecycle states.

    ``STARTING`` — constructed, no tick executed yet (programs cold).
    ``READY`` — serving; admission open.
    ``OVERLOADED`` — serving, but the overload detector trips: new
    submissions are shed per the engine's policy until pressure drops.
    ``DRAINING`` — preemption observed: admission closed, in-flight work
    finishing under the drain deadline.
    ``STOPPED`` — drain complete; the engine no longer accepts work.
    """

    STARTING = "starting"
    READY = "ready"
    OVERLOADED = "overloaded"
    DRAINING = "draining"
    STOPPED = "stopped"


class RequestError(RuntimeError):
    """Base of every typed request/engine failure.

    ``retryable`` is the client contract: True means the request itself
    was fine and a retry (here after backoff, or against another
    replica) is the right move; False means retrying the identical
    request cannot help.
    """

    retryable: bool = False


class DeadlineExceeded(RequestError):
    """The request's ``deadline_s`` expired before completion.

    Raised from the handle at the chunk boundary where the expiry was
    observed; the request's pages were released there."""


class RequestCancelled(RequestError):
    """The client called :meth:`RequestHandle.cancel`."""


class EngineOverloaded(RequestError):
    """Shed by the overload policy (bounded queue / TTFT estimate)."""

    retryable = True


class EngineDraining(RequestError):
    """Submission refused: the engine is DRAINING or STOPPED."""

    retryable = True


class RequestPreempted(RequestError):
    """Failed by a drain or an engine close: either flushed from the
    queue before starting, or cut off in flight.  The stream is
    *explicitly* truncated — retry against another replica.

    ``resumable`` tells the client what a retry costs: ``True`` means
    the request had yielded **no tokens yet** (flushed from the queue,
    or preempted before its first token) — a plain re-submission
    resumes it losslessly.  ``False`` means it was cut mid-stream: a
    lossless resume needs a key-pinned, token-verified replay (what
    :class:`~torchdistx_tpu.fleet.FleetHandle` does automatically);
    naive re-submission would restart the stream from token 0.

    QoS preemptions (swap-to-host / drop-and-replay) never raise this —
    the engine resumes those itself, invisibly in the token stream."""

    retryable = True
    resumable: bool = False

    def __init__(self, *args, resumable: bool = False):
        super().__init__(*args)
        self.resumable = resumable


class RecoveryFailed(RequestError):
    """The crash-recovery supervisor exhausted the request's replay
    budget (``max_recoveries``) without completing it."""

    retryable = True


class MigrationIncompatible(RequestError):
    """A live-stream KV page migration could not land on the destination
    engine: pool geometry mismatch (layer count, page size, head shape,
    dtype), a different weights version, or a snapshot wider than the
    destination's block table.  The import is rejected BEFORE any page
    scatter — an incompatible snapshot must never silently corrupt the
    destination pool.  Retryable: the stream itself is fine, and a cold
    key-pinned replay (the pre-migration failover path) reproduces it
    token-identically on any replica."""

    retryable = True


class JournalOwned(RequestError):
    """A request journal's ownership claim was refused: another LIVE
    engine holds it (``owner.lock`` with an alive pid).  The
    double-resume guard — a journal offered to two engines is resumed
    by exactly one; the loser gets this typed refusal instead of a
    second copy of every stream.  Retryable in the fleet sense: offer
    the journal elsewhere, or wait for the holder to release it.  A
    *stale* lock (dead pid — the crash the journal exists for) never
    raises this; it is stolen atomically."""

    retryable = True


class DeterminismDiverged(RequestError):
    """A resume's committed-token buffer no longer matches the request's
    determinism digest (docs/observability.md, "Audit plane"): the
    stream was corrupted between commit and resume, and feeding it back
    to the model would silently poison the continuation.  NOT retryable
    — the engine latches ``serve.diverging`` and a human (or the
    incident-replay tooling) owns the next move; a blind retry cannot
    restore a broken determinism invariant."""


class OverloadDetector:
    """Admission-time overload signal: queue bound + TTFT estimate.

    ``max_queue`` bounds waiting requests outright.  ``max_ttft_s``
    bounds the *estimated* time a new arrival would wait for its
    prefill.  The unit of prefill work is the **chunk**, not the
    request: chunked prefill splits a long prompt's suffix into
    fixed-size chunks and the engine dispatches at most
    ``max_prefills_per_tick`` chunks per tick — so a queued 16k-token
    prompt costs ``ceil(suffix_chunks / max_prefills_per_tick)`` ticks,
    not 1, and the estimate is
    ``ceil((queued_chunks + 1) / max_prefills_per_tick) * ewma_tick_s``
    (the ``+1`` is the arriving request's own first chunk).  Callers
    that don't chunk (one prompt = one prefill) pass queue depth as the
    chunk count — the pre-chunking formula is the degenerate case.  The
    tick EWMA is seeded by the first observed tick and smoothed with
    factor ``alpha``; compile-heavy warm-up ticks inflate it briefly and
    decay out (the detector errs toward shedding while cold, which is
    the safe direction).  Both knobs ``None`` → never overloaded, the
    engine's default.
    """

    def __init__(
        self,
        max_queue: Optional[int] = None,
        max_ttft_s: Optional[float] = None,
        alpha: float = 0.2,
    ):
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None for unbounded)")
        if max_ttft_s is not None and max_ttft_s <= 0:
            raise ValueError("max_ttft_s must be > 0 (or None to disable)")
        self.max_queue = max_queue
        self.max_ttft_s = max_ttft_s
        self.alpha = alpha
        self._tick_ewma_s: Optional[float] = None

    def observe_tick(self, dur_s: float) -> None:
        """Feed one engine-tick duration into the EWMA."""
        if self._tick_ewma_s is None:
            self._tick_ewma_s = dur_s
        else:
            self._tick_ewma_s += self.alpha * (dur_s - self._tick_ewma_s)

    def est_ttft_s(
        self, queued_chunks: int, max_prefills_per_tick: int
    ) -> float:
        """Estimated wait-for-prefill of a request arriving now.

        ``queued_chunks`` is the total prefill work ahead of the arrival
        in CHUNKS (``Request.n_chunks`` summed over the queue plus any
        in-flight prefill's remainder) — an unchunked caller passes
        queue depth, one chunk per request."""
        if self._tick_ewma_s is None:
            return 0.0
        ticks = -(-(queued_chunks + 1) // max(1, max_prefills_per_tick))
        return ticks * self._tick_ewma_s

    def overloaded(
        self,
        queue_depth: int,
        max_prefills_per_tick: int,
        queued_chunks: Optional[int] = None,
    ) -> bool:
        """``max_queue`` bounds REQUESTS (depth); ``max_ttft_s`` bounds
        estimated prefill wait, which drains in CHUNKS — pass
        ``queued_chunks`` when they differ (chunked prefill), else depth
        doubles as the chunk count."""
        if self.max_queue is not None and queue_depth >= self.max_queue:
            return True
        if self.max_ttft_s is not None:
            chunks = queue_depth if queued_chunks is None else queued_chunks
            if self.est_ttft_s(chunks, max_prefills_per_tick) > self.max_ttft_s:
                return True
        return False

    @property
    def enabled(self) -> bool:
        return self.max_queue is not None or self.max_ttft_s is not None
