"""QoS scheduler: SLO-aware multi-tenant admission — priority classes,
weighted fair queueing, deadline-aware ordering.

The FIFO scheduler (:mod:`.scheduler`) admits in arrival order: one
burst tenant or one long batch job starves everyone behind it, and
``deadline_s`` only *expires* requests, it never *orders* them.
:class:`QoSScheduler` is a drop-in replacement behind the same
interface (``push`` / ``pop_admissible`` / ``purge`` / ``requeue`` /
``flush`` / ``shed_oldest`` / ``pending_prefill_chunks``), selected via
``Engine(scheduler="qos")``, ordering admission by three nested rules:

1. **Priority classes are strict.**  A waiting request of a higher
   ``Request.priority`` admits before any lower one — and under page or
   slot pressure the engine *preempts* running lower-priority streams
   to make room (swap-to-host or drop-and-replay; see
   :class:`~.engine.Engine`).
2. **Within a class, tenants share by weighted fair queueing** over
   prefill-chunk cost (Demers et al., SIGCOMM '89), virtual-time
   based: each (class, tenant) pair carries a virtual time advanced by
   ``chunks / weight`` per admitted request, and among tenants with
   work in the class the smallest virtual time goes next.  Virtual
   time is scoped per class — service in one class never moves
   another class's clock, so a quiet class's pops cannot hand a
   newly-busy tenant a head start over a busy class's incumbents.  Chunks are
   the engine's native cost unit (chunked prefill) and **cache-aware**:
   ``Request.n_chunks`` weighs only the suffix a prefix-cache hit will
   actually prefill (``PrefixIndex.probe`` at submit), so a cached
   request charges its tenant what it will really cost.  The ordering
   path is a pure function of the push/pop sequence — no ``time.time``
   anywhere — so tests are deterministic.
3. **Within a (class, tenant) queue: earliest deadline first.**
   Requests carrying a ``deadline_s`` order by their absolute expiry
   (stamped once at submit; comparing stamps needs no clock),
   deadline-less requests after them, ties by submission order.

Starvation bounds are provable from rule 2: over any interval where a
tenant stays backlogged, it receives at least ``w / W`` of the class's
admitted chunk budget (``W`` = total weight of backlogged tenants), so
a weight-1 tenant under sustained weight-8 competition admits within
~``8 × cost`` chunks of competing work — pinned in
``tests/test_serving_qos.py``.  An idle tenant's virtual time is
clamped up to its class's clock when it becomes busy again: sleeping
banks no credit (the classic virtual-time rule).

Two shedding hooks ride along: :meth:`shed_oldest` keeps the FIFO
``drop-oldest`` policy working unchanged, and :meth:`shed_lowest`
implements ``shed_policy="by-priority"`` — the victim is the **lowest
class, youngest first**, and an arrival that is itself the lowest
class is the one shed (the engine rejects it).

Transactional requeues (:meth:`requeue` — a transient prefill failure
returning its admission batch) re-enter at the *head of the line*,
ahead of the QoS order, and are **not** re-charged: the failure must
not cost the request its place or its tenant a second fare.  A
*preemption* requeue goes through :meth:`push` instead — the victim
re-enters QoS order behind the higher class that displaced it, and its
resume cost (the re-prefill of prompt + generated-so-far) is charged
like any other work.

Telemetry: the shared ``serve.queue_depth`` gauge plus a per-tenant
labeled ``serve.queue_depth{tenant=...}`` gauge family (rendered with
a proper ``tenant`` label on a ``/metrics`` scrape).

State is bounded: tenant counters, empty per-tenant heaps, and gauge
iteration all prune when a tenant's waiting count hits zero — the
pruned tenant's gauge leaves the process-wide registry too
(:func:`torchdistx_tpu.telemetry.remove`), so the registry and every
``/metrics`` scrape track ACTIVE tenants, not tenants ever seen — and a
class whose last waiting request leaves drops its virtual clock and
every tenant virtual time — the classic busy-period reset (virtual
time restarts at zero when the system idles; with no one waiting,
relative debts are moot).  A long-lived engine serving free-form
per-user tenant ids therefore pays O(active tenants) per operation,
not O(tenants ever seen).
"""

from __future__ import annotations

import heapq
import math
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from .. import telemetry as _telemetry
from .blocks import BlockAllocator, blocks_needed
from .scheduler import Request

__all__ = ["QoSScheduler"]

_T_BACKPRESSURE = _telemetry.counter("serve.backpressure")
_G_QUEUE = _telemetry.gauge("serve.queue_depth")


class QoSScheduler:
    """Priority + weighted-fair-queueing + EDF admission (see module
    docstring).  Drop-in for :class:`~.scheduler.FIFOScheduler`.

    Parameters
    ----------
    max_prefills_per_tick : the prefill/decode interleave knob, in
        chunks per tick (identical to the FIFO scheduler's).
    tenant_weights : ``{tenant: weight}`` — relative shares of prefill
        chunk capacity within a priority class.  Unlisted tenants get
        ``default_weight``.  Weights must be > 0.
    default_weight : weight of tenants absent from ``tenant_weights``.
    """

    def __init__(
        self,
        max_prefills_per_tick: int = 1,
        tenant_weights: Optional[Dict[str, float]] = None,
        default_weight: float = 1.0,
    ):
        if max_prefills_per_tick < 1:
            raise ValueError("max_prefills_per_tick must be >= 1")
        self.max_prefills_per_tick = max_prefills_per_tick
        self._weights: Dict[str, float] = {}
        for tenant, w in (tenant_weights or {}).items():
            w = float(w)
            if w <= 0:
                raise ValueError(
                    f"tenant_weights[{tenant!r}] = {w}: weights must be > 0"
                )
            self._weights[str(tenant)] = w
        self.default_weight = float(default_weight)
        if self.default_weight <= 0:
            raise ValueError("default_weight must be > 0")
        # priority -> tenant -> heap of (deadline_key, seq, Request).
        self._queues: Dict[int, Dict[str, List[Tuple[float, int, Request]]]] = {}
        # Transactional head-of-line returns (failed prefill batches):
        # drained FIFO before any QoS selection, never re-charged.
        self._requeued: deque = deque()
        # Virtual time is scoped PER PRIORITY CLASS: fair queueing runs
        # among the tenants of one class, so a class's clock must only
        # advance on that class's service.  One global clock would let
        # a pop in a quiet low class regress the clock and hand a
        # newly-busy tenant of a busy class a huge head start over its
        # backlogged incumbents — breaking the w/W starvation bound.
        self._vt: Dict[Tuple[int, str], float] = {}  # (prio, tenant) -> finish
        self._vclock: Dict[int, float] = {}  # prio -> clock at last service
        self._n = 0
        self._tenant_n: Dict[str, int] = {}
        self._tenant_gauges: Dict[str, object] = {}
        self._engine_gauge = None  # serve.queue_depth{engine=}, see bind_engine

    # ------------------------------------------------------------------
    # Introspection

    def __len__(self) -> int:
        return self._n

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, self.default_weight)

    def _iter(self):
        """Every waiting request (no particular order)."""
        for req in self._requeued:
            yield req
        for tmap in self._queues.values():
            for heap in tmap.values():
                for _, _, req in heap:
                    yield req

    def pending_prefill_chunks(self) -> int:
        """Total prefill cost of the waiting queues, in chunks (the
        same contract as the FIFO scheduler's)."""
        return sum(r.n_chunks for r in self._iter())

    # ------------------------------------------------------------------
    # Gauges

    def bind_engine(self, engine_id: str) -> None:
        """Mint the per-engine ``serve.queue_depth{engine=...}`` gauge
        (same contract as :meth:`FIFOScheduler.bind_engine
        <torchdistx_tpu.serving.scheduler.FIFOScheduler.bind_engine>`):
        the unlabeled gauge is process-global and N replicas clobber it,
        so a fleet and the autoscaler's slope predictor read the labeled
        family; the owning engine prunes it at STOPPED."""
        self._engine_gauge = _telemetry.gauge(
            "serve.queue_depth", engine=engine_id
        )
        self._engine_gauge.set(self._n)

    def _set_gauges(self) -> None:
        _G_QUEUE.set(self._n)
        if self._engine_gauge is not None:
            self._engine_gauge.set(self._n)
        # Departed tenants (count pruned to zero) leave BOTH the
        # iteration set and the process-wide registry
        # (telemetry.remove): a long-lived engine serving free-form
        # per-user tenant ids must not grow the registry — and with it
        # every exported counters snapshot and /metrics scrape — by one
        # gauge per tenant ever seen.  The gauge family is labeled
        # (serve.queue_depth{tenant=...}), so a Prometheus scrape sees
        # the tenant as a proper label and idle tenants' series simply
        # end.
        for tenant in [
            t for t in self._tenant_gauges if t not in self._tenant_n
        ]:
            del self._tenant_gauges[tenant]
            _telemetry.remove("serve.queue_depth", tenant=tenant)
        for tenant, n in self._tenant_n.items():
            g = self._tenant_gauges.get(tenant)
            if g is None:
                g = _telemetry.gauge("serve.queue_depth", tenant=tenant)
                self._tenant_gauges[tenant] = g
            g.set(n)

    def _count(self, req: Request, delta: int) -> None:
        self._n += delta
        n = self._tenant_n.get(req.tenant, 0) + delta
        if n:
            self._tenant_n[req.tenant] = n
        else:
            self._tenant_n.pop(req.tenant, None)

    def _gc_class(self, prio: int) -> None:
        """Prune a class's empty tenant heaps; when its last waiting
        request left (requeued deque included), drop the class map and
        reset its virtual time wholesale — the classic busy-period
        rule.  Keeps scheduler state proportional to waiting work."""
        tmap = self._queues.get(prio)
        if tmap is not None:
            for tenant in [t for t, h in tmap.items() if not h]:
                del tmap[tenant]
            if not tmap:
                del self._queues[prio]
                tmap = None
        if tmap is None and not any(
            r.priority == prio for r in self._requeued
        ):
            self._vclock.pop(prio, None)
            for vk in [vk for vk in self._vt if vk[0] == prio]:
                del self._vt[vk]

    # ------------------------------------------------------------------
    # Push / selection / pop

    @staticmethod
    def _key(req: Request) -> Tuple[float, int, Request]:
        """EDF-within-(class, tenant) heap key: absolute deadline stamp
        (deadline-less requests last), ties by submission order."""
        dl = req.deadline if req.deadline is not None else math.inf
        return (dl, req.rid, req)

    def push(self, req: Request) -> None:
        heap = self._queues.setdefault(req.priority, {}).setdefault(
            req.tenant, []
        )
        if not heap:
            # Idle (class, tenant) queue going busy: clamp its virtual
            # time up to the class clock — sleeping banks no credit.
            vk = (req.priority, req.tenant)
            self._vt[vk] = max(
                self._vt.get(vk, 0.0),
                self._vclock.get(req.priority, 0.0),
            )
        heapq.heappush(heap, self._key(req))
        self._count(req, +1)
        self._set_gauges()

    def _select(self) -> Optional[Tuple[int, str]]:
        """The (priority, tenant) queue the next pop comes from, or
        None.  Highest class first; within it, smallest tenant virtual
        time (ties by tenant name — deterministic)."""
        best: Optional[Tuple[int, str]] = None
        for prio in sorted(self._queues, reverse=True):
            tenants = [t for t, h in self._queues[prio].items() if h]
            if tenants:
                best = (
                    prio,
                    min(
                        tenants,
                        key=lambda t: (self._vt.get((prio, t), 0.0), t),
                    ),
                )
                break
        return best

    def peek(self) -> Optional[Request]:
        """The request the next :meth:`pop_admissible` would admit
        first — no removal, no virtual-time charge.  The engine's
        preemption trigger reads the head's priority and page quota
        from here."""
        if self._requeued:
            return self._requeued[0]
        sel = self._select()
        if sel is None:
            return None
        prio, tenant = sel
        return self._queues[prio][tenant][0][2]

    def _pop_next(self) -> Request:
        if self._requeued:
            req = self._requeued.popleft()  # already charged — no re-fare
        else:
            prio, tenant = self._select()
            _, _, req = heapq.heappop(self._queues[prio][tenant])
            vk = (prio, tenant)
            self._vclock[prio] = self._vt.get(vk, 0.0)
            self._vt[vk] = self._vclock[prio] + max(
                1, req.n_chunks
            ) / self.weight(tenant)
        self._count(req, -1)
        self._gc_class(req.priority)
        return req

    # ------------------------------------------------------------------
    # The scheduler contract (FIFOScheduler-compatible)

    def pop_admissible(
        self,
        n_free_slots: int,
        allocator: BlockAllocator,
        block_size: int,
        reclaim: Optional[Callable[[int], int]] = None,
        need: Optional[Callable[[Request], int]] = None,
        ready: Optional[Callable[[Request], bool]] = None,
    ) -> List[Request]:
        """Pop up to ``max_prefills_per_tick`` requests in QoS order
        whose cumulative page reservations fit the free list.  Stops at
        the first head that doesn't fit — no skipping ahead to smaller
        requests (that would starve long prompts within a class, the
        same rule the FIFO scheduler enforces); the engine's preemption
        path is the legitimate way to make room for a blocked head.
        Backpressure accounting matches the FIFO scheduler's: any
        stalled tick with work waiting counts, slot- or page-bound.

        ``need``/``ready`` match the FIFO scheduler's contract (see
        :meth:`.scheduler.FIFOScheduler.pop_admissible`): ``need(req)``
        overrides the page reservation (fork siblings charge their true
        marginal pages — the WFQ fare already charged their marginal
        prefill, one chunk), ``ready(req)`` holds a cold-model head in
        place without popping it while the engine materializes its
        weights out-of-band."""
        out: List[Request] = []
        limit = min(self.max_prefills_per_tick, n_free_slots)
        if self._n and limit == 0:
            _T_BACKPRESSURE.add()
            return out
        reserved = 0
        while self._n and len(out) < limit:
            head = self.peek()
            if ready is not None and not ready(head):
                break  # cold model: the engine counts + materializes
            n_pages = (
                need(head) if need is not None
                else blocks_needed(head.cache_tokens, block_size)
            )
            avail = allocator.num_free - reserved
            if n_pages > avail and reclaim is not None:
                reclaim(n_pages - avail)
                avail = allocator.num_free - reserved
            if n_pages > avail:
                _T_BACKPRESSURE.add()
                break
            reserved += n_pages
            out.append(self._pop_next())
        self._set_gauges()
        return out

    def requeue(self, reqs: List[Request]) -> None:
        """Return ``reqs`` to the head of the line in order, ahead of
        the QoS order and without a second virtual-time charge — the
        transactional path for a transiently-failed admission batch
        (preemption victims re-enter via :meth:`push` instead)."""
        for req in reversed(reqs):
            self._requeued.appendleft(req)
            self._count(req, +1)
        self._set_gauges()

    def _remove(self, victim: Request) -> None:
        """Drop one specific waiting request (shed paths)."""
        try:
            self._requeued.remove(victim)
        except ValueError:
            heap = self._queues[victim.priority][victim.tenant]
            heap.remove(self._key(victim))
            heapq.heapify(heap)
        self._count(victim, -1)
        self._gc_class(victim.priority)
        self._set_gauges()

    def shed_oldest(self) -> Optional[Request]:
        """The globally oldest waiting request (``drop-oldest``
        compatibility), or None."""
        oldest = min(self._iter(), key=lambda r: r.rid, default=None)
        if oldest is not None:
            self._remove(oldest)
        return oldest

    def shed_lowest(
        self, below_priority: Optional[int] = None
    ) -> Optional[Request]:
        """The ``shed_policy="by-priority"`` victim: lowest class,
        youngest first.  With ``below_priority`` given, only a victim
        of a strictly lower class qualifies — None means the arrival is
        itself the lowest class and should be the one shed."""
        victim: Optional[Request] = None
        for req in self._iter():
            if victim is None or (req.priority, -req.rid) < (
                victim.priority,
                -victim.rid,
            ):
                victim = req
        if victim is None:
            return None
        if below_priority is not None and victim.priority >= below_priority:
            return None
        self._remove(victim)
        return victim

    def flush(self) -> List[Request]:
        """Empty every queue (drain start); returns the flushed
        requests in submission order."""
        out = sorted(self._iter(), key=lambda r: r.rid)
        self._queues.clear()
        self._requeued.clear()
        self._n = 0
        self._tenant_n = {}
        self._vt.clear()
        self._vclock.clear()
        self._set_gauges()
        return out

    def purge(self, now: float) -> Tuple[List[Request], List[Request]]:
        """Drop cancelled and deadline-expired requests from the
        waiting side; returns ``(expired, cancelled)`` exactly like the
        FIFO scheduler."""
        expired: List[Request] = []
        cancelled: List[Request] = []
        if not self._n:
            return expired, cancelled
        for req in list(self._iter()):
            if req.handle._cancel_requested:
                cancelled.append(req)
            elif req.expired(now):
                expired.append(req)
        for req in expired + cancelled:
            self._remove(req)
        return expired, cancelled
