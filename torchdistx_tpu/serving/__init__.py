"""Serving: continuous batching + paged KV cache + streaming scheduler.

The inference-scaling subsystem (ROADMAP: "serves heavy traffic").  A
solo :func:`~torchdistx_tpu.models.generate.generate` call is one batch
that must finish together, with ``prompt + max_new_tokens`` cache
allocated per row up front.  This package replaces that for serving:

* :mod:`.blocks` — host-side page allocator (fixed-size KV pages,
  refcounted for prefix sharing, admit/finish granularity, backpressure
  on exhaustion);
* :mod:`.cache`  — the device page pools, the jitted prompt scatter,
  and the copy-on-write page copy;
* :mod:`.prefix` — the refcounted prefix index: full prompt pages
  content-addressed by chained hash, shared across requests, LRU-evicted
  under allocator pressure (``Engine(prefix_cache=True)``);
* :mod:`.engine` — the continuous-batching :class:`~.engine.Engine`
  (one compiled decode chunk over fixed slots, per-bucket compiled
  prefill, slot recycling at chunk boundaries);
* :mod:`.scheduler` — FIFO admission, the prefill/decode interleave
  knob, and the streaming :class:`~.scheduler.RequestHandle`;
* :mod:`.qos`    — the SLO-aware multi-tenant scheduler
  (``Engine(scheduler="qos")``): strict priority classes, per-tenant
  weighted fair queueing over prefill-chunk cost, earliest-deadline-
  first ordering, and the shed-by-priority overload policy; the engine
  pairs it with preemption of running lower-class streams
  (swap-to-host / drop-and-replay, both token-identical on resume);
* :mod:`.modelpool` — the model plane (``Engine(model_pool=...)``):
  many models on one engine's page pool — deferred-init skeleton
  registry (near-zero HBM until demand), materialize-on-first-request,
  ledger-driven LRU weight eviction under HBM pressure; pairs with
  ``submit(model=..., n=...)`` copy-on-write parallel sampling;
* :mod:`.lifecycle` — the request-lifecycle robustness layer: typed
  errors (deadline, cancel, shed, preempt, recovery), the
  :class:`~.lifecycle.Health` state machine
  (STARTING→READY→DRAINING→STOPPED, plus OVERLOADED), and the
  :class:`~.lifecycle.OverloadDetector` behind the shedding policy;
* :mod:`.journal` — the durability plane (``Engine(journal=...)``):
  a crash-consistent append-only request journal (torn-tail-tolerant
  WAL, per-tick group commit, segment rotation + compaction, exclusive
  ownership lock) and :meth:`~.engine.Engine.resume_from_journal` —
  a ``kill -9``'d engine's in-flight streams finish token-identically
  in the restarted process (docs/resilience.md, "Durability").

Quick start::

    from torchdistx_tpu.serving import Engine
    from torchdistx_tpu.models import llama

    eng = Engine(params, model=llama, cfg=cfg, num_slots=8,
                 block_size=16, eos_id=2)
    h = eng.submit(prompt_ids, max_new_tokens=128, key=0, deadline_s=30)
    for tok in h.tokens():      # streams; drives the engine
        print(tok)

Engine output is token-identical to solo ``generate`` with the same key
(see :mod:`.engine`) — and stays token-identical across device-call
failures: a crash-recovery supervisor rebuilds the paged pool and
replays live requests from their committed tokens.  SIGTERM (via
:mod:`torchdistx_tpu.resilience.preemption`) drains the engine
gracefully: admission stops, in-flight work finishes within the drain
deadline, the remainder fails with a retryable typed error.  Telemetry:
``serve.*`` spans/counters/gauges (docs/observability.md); fault sites
``serve.admit`` / ``serve.prefill`` / ``serve.step`` / ``serve.recover``
(docs/resilience.md).  Full design: docs/serving.md.

One engine is still a single point of failure: :mod:`torchdistx_tpu
.fleet` fronts N of them with health-aware routing, typed-error
failover, and zero-downtime weight hot swap (docs/fleet.md).
"""

from .blocks import BlockAllocator, blocks_needed  # noqa: F401
from .cache import (  # noqa: F401
    copy_pages,
    fresh_pool,
    init_paged_cache,
    pool_geometry,
    swap_in_pages,
    swap_out_pages,
    write_prompt,
)
from .engine import Engine  # noqa: F401
from .journal import JournalEntry, RequestJournal  # noqa: F401
from .modelpool import DEFAULT_MODEL, ModelPool  # noqa: F401
from .qos import QoSScheduler  # noqa: F401
from .lifecycle import (  # noqa: F401
    DeadlineExceeded,
    DeterminismDiverged,
    EngineDraining,
    EngineOverloaded,
    Health,
    JournalOwned,
    MigrationIncompatible,
    OverloadDetector,
    RecoveryFailed,
    RequestCancelled,
    RequestError,
    RequestPreempted,
)
from .prefix import PrefixIndex, page_hashes  # noqa: F401
from .scheduler import FIFOScheduler, Request, RequestHandle  # noqa: F401

__all__ = [
    "BlockAllocator",
    "DEFAULT_MODEL",
    "DeadlineExceeded",
    "DeterminismDiverged",
    "Engine",
    "EngineDraining",
    "EngineOverloaded",
    "FIFOScheduler",
    "Health",
    "JournalEntry",
    "JournalOwned",
    "MigrationIncompatible",
    "ModelPool",
    "OverloadDetector",
    "PrefixIndex",
    "QoSScheduler",
    "RecoveryFailed",
    "Request",
    "RequestCancelled",
    "RequestError",
    "RequestHandle",
    "RequestJournal",
    "RequestPreempted",
    "blocks_needed",
    "copy_pages",
    "fresh_pool",
    "init_paged_cache",
    "page_hashes",
    "pool_geometry",
    "swap_in_pages",
    "swap_out_pages",
    "write_prompt",
]
