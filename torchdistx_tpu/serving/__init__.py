"""Serving: continuous batching + paged KV cache + streaming scheduler.

The inference-scaling subsystem (ROADMAP: "serves heavy traffic").  A
solo :func:`~torchdistx_tpu.models.generate.generate` call is one batch
that must finish together, with ``prompt + max_new_tokens`` cache
allocated per row up front.  This package replaces that for serving:

* :mod:`.blocks` — host-side page allocator (fixed-size KV pages,
  admit/finish granularity, backpressure on exhaustion);
* :mod:`.cache`  — the device page pools + the jitted prompt scatter;
* :mod:`.engine` — the continuous-batching :class:`~.engine.Engine`
  (one compiled decode chunk over fixed slots, per-bucket compiled
  prefill, slot recycling at chunk boundaries);
* :mod:`.scheduler` — FIFO admission, the prefill/decode interleave
  knob, and the streaming :class:`~.scheduler.RequestHandle`.

Quick start::

    from torchdistx_tpu.serving import Engine
    from torchdistx_tpu.models import llama

    eng = Engine(params, model=llama, cfg=cfg, num_slots=8,
                 block_size=16, eos_id=2)
    h = eng.submit(prompt_ids, max_new_tokens=128, key=0)
    for tok in h.tokens():      # streams; drives the engine
        print(tok)

Engine output is token-identical to solo ``generate`` with the same key
(see :mod:`.engine`).  Telemetry: ``serve.*`` spans/counters/gauges
(docs/observability.md); fault sites ``serve.admit`` / ``serve.step``
(docs/resilience.md).  Full design: docs/serving.md.
"""

from .blocks import BlockAllocator, blocks_needed  # noqa: F401
from .cache import init_paged_cache, write_prompt  # noqa: F401
from .engine import Engine  # noqa: F401
from .scheduler import FIFOScheduler, Request, RequestHandle  # noqa: F401

__all__ = [
    "BlockAllocator",
    "Engine",
    "FIFOScheduler",
    "Request",
    "RequestHandle",
    "blocks_needed",
    "init_paged_cache",
    "write_prompt",
]
