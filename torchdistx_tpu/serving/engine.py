"""Continuous-batching engine: fixed decode slots over a paged KV cache.

Orca-style iteration-level scheduling (Yu et al., OSDI '22) on top of a
vLLM-style paged cache (Kwon et al., SOSP '23), specialized for the TPU
idiom of this stack: **two compiled programs total** serve any traffic
mix —

* a jitted **prefill** per prompt-length bucket: the family's unchanged
  ``forward_cached`` over the padded prompt, first-token sampling, and
  the page scatter (:func:`.cache.write_prompt`), all one program;
* ONE jitted **decode chunk**: ``decode_chunk`` steps of the family's
  ``forward_paged`` over all ``num_slots`` slots, ``lax.scan``-fused so
  the host syncs once per chunk, not once per token.

Slots admit and retire independently — the moment a sequence hits EOS or
its token budget (observed at the next chunk boundary), its pages free
and the next FIFO request prefills into them.  No request ever waits for
a batch-mate.

**Token parity with solo** :func:`~torchdistx_tpu.models.generate.generate`
is a correctness invariant, not an aspiration: the paged attention path
masks exactly like the contiguous one, per-slot sampling keys are
``fold_in(request_key, n_generated)`` (the same schedule ``generate``
uses), and ``_sample`` is literally the same function — so an engine
under out-of-order admission and mid-stream recycling emits the same
tokens a solo call would.  ``tests/test_serving.py`` pins this, greedy
and sampled.

Sampling config (temperature/top_k/eos) is **engine-level static** — it
is baked into the two compiled programs, exactly as it is baked into a
``generate`` call.  Per-request knobs are prompt, budget, and key.

Resilience: ``serve.admit`` and ``serve.step`` are ``TDX_FAULT`` sites.
An ``io`` fault leaves state untouched and the tick retries; a ``nan``
fault marks the decode chunk poisoned and the engine *skips* it (decode
is a pure function of committed state, so the re-run next tick emits the
identical tokens — the serving analog of the training loop's
skip-step non-finite guard).  ``fatal`` propagates: fatal means fatal.
"""

from __future__ import annotations

import time
from collections import deque
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry as _telemetry
from ..models.generate import _sample
from ..resilience import faults
from .blocks import BlockAllocator, blocks_needed
from .cache import init_paged_cache, write_prompt
from .scheduler import FIFOScheduler, Request, RequestHandle

__all__ = ["Engine"]

_T_REQUESTS = _telemetry.counter("serve.requests")
_T_FINISHED = _telemetry.counter("serve.finished")
_T_TOKENS = _telemetry.counter("serve.tokens_out")
_T_ADMIT_RETRIES = _telemetry.counter("serve.admit_retries")
_T_STEP_RETRIES = _telemetry.counter("serve.step_retries")
_T_SKIPPED = _telemetry.counter("serve.skipped_steps")
_G_RUNNING = _telemetry.gauge("serve.running_slots")
_G_DECODE_TPS = _telemetry.gauge("serve.decode_tok_s")
_G_TTFT = _telemetry.gauge("serve.ttft_s")


@partial(
    jax.jit,
    static_argnames=(
        "model", "cfg", "temperature", "top_k", "block_size",
    ),
    donate_argnums=(1,),
)
def _prefill(
    params, paged, prompt, length, key, table,
    *, model, cfg, temperature, top_k, block_size,
):
    """Compiled prefill: contiguous forward over the padded prompt,
    first-token sample (``fold_in(key, 0)`` — ``generate``'s schedule),
    and the page scatter.  One compile per prompt bucket."""
    p_pad = prompt.shape[1]
    scratch = model.init_cache(cfg, 1, p_pad)
    logits, scratch = model.forward_cached(params, prompt, cfg, scratch, 0)
    last = jax.lax.dynamic_index_in_dim(
        logits, length - 1, axis=1, keepdims=False
    )
    first = _sample(
        last, jax.random.fold_in(key, 0), temperature, top_k
    ).astype(jnp.int32)[0]
    paged = write_prompt(paged, scratch, table, length, block_size=block_size)
    return first, paged


@partial(
    jax.jit,
    static_argnames=(
        "model", "cfg", "temperature", "top_k", "eos_id", "n_steps",
    ),
    donate_argnums=(1,),
)
def _decode_chunk(
    params, paged, tokens, positions, n_gen, done, keys, block_tables,
    *, model, cfg, temperature, top_k, eos_id, n_steps,
):
    """Compiled decode chunk: ``n_steps`` scan-fused ``forward_paged``
    steps over every slot.  Post-EOS slots keep emitting EOS (solo
    ``generate`` semantics); retired slots scribble on the trash page.
    Returns ``(new paged cache, tokens (n_steps, S))``."""

    def one(carry, _):
        tok, cache, pos, n, dn = carry
        logits, cache = model.forward_paged(
            params, tok[:, None], cfg, cache, block_tables, pos
        )
        step_keys = jax.vmap(jax.random.fold_in)(keys, n)
        nxt = jax.vmap(
            lambda lg, k: _sample(lg[None], k, temperature, top_k)[0]
        )(logits[:, -1], step_keys).astype(jnp.int32)
        if eos_id is not None:
            nxt = jnp.where(dn, eos_id, nxt)
            dn = dn | (nxt == eos_id)
        return (nxt, cache, pos + 1, n + 1, dn), nxt

    (tok, paged, pos, n, dn), out = jax.lax.scan(
        one, (tokens, paged, positions, n_gen, done), None, length=n_steps
    )
    return paged, out


class Engine:
    """Continuous-batching serving engine over one model family.

    Single-host, single-threaded: drive it from ``handle.tokens()`` /
    ``handle.result()`` / :meth:`drain`, or call :meth:`step` yourself.

    Parameters
    ----------
    params : the family's parameter pytree (raw or ``prep_decode``-prepped;
        prepped once at construction when the family supports it).
    model / cfg : the family module + config (the ``generate`` protocol).
    num_slots : decode batch width — concurrent running requests.
    block_size : KV page size in tokens.
    num_blocks : page-pool size; default reserves dense capacity
        (``num_slots`` × the max request) so nothing backpressures unless
        you size it down — sizing it down is the point of paging.
    max_model_len : longest admissible ``prompt + max_new_tokens``; also
        the block-table width, i.e. the decode attention span.  Keep it at
        your real traffic's max, NOT ``cfg.max_seq_len``.
    temperature / top_k / eos_id : engine-static sampling config.
    decode_chunk : decode steps fused per host sync.  Recycling happens at
        chunk boundaries, so large chunks trade slot-turnaround (and thus
        a little throughput under churn) for far fewer host round-trips.
    max_prefills_per_tick : the prefill/decode interleave knob
        (see :class:`.scheduler.FIFOScheduler`).
    """

    def __init__(
        self,
        params,
        *,
        model,
        cfg,
        num_slots: int = 8,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        max_model_len: Optional[int] = None,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        eos_id: Optional[int] = None,
        decode_chunk: int = 8,
        max_prefills_per_tick: int = 1,
        min_prefill_bucket: int = 16,
    ):
        self.model = model
        self.cfg = cfg
        self.num_slots = num_slots
        self.block_size = block_size
        self.max_model_len = int(max_model_len or cfg.max_seq_len)
        if self.max_model_len > cfg.max_seq_len:
            raise ValueError(
                f"max_model_len ({self.max_model_len}) exceeds "
                f"cfg.max_seq_len ({cfg.max_seq_len})"
            )
        self.temperature = float(temperature)
        self.top_k = top_k
        self.eos_id = eos_id
        self.decode_chunk = int(decode_chunk)
        if self.decode_chunk < 1:
            raise ValueError("decode_chunk must be >= 1")
        self.min_prefill_bucket = int(min_prefill_bucket)
        if self.min_prefill_bucket < 1:
            # _bucket doubles up from this value; <= 0 would never
            # terminate.
            raise ValueError("min_prefill_bucket must be >= 1")

        self._table_width = blocks_needed(self.max_model_len, block_size)
        if num_blocks is None:
            num_blocks = 1 + num_slots * self._table_width
        self.allocator = BlockAllocator(num_blocks, block_size)
        self.scheduler = FIFOScheduler(max_prefills_per_tick)

        prep = getattr(model, "prep_decode", None)
        self._params = prep(params, cfg) if prep is not None else params
        self._cache = init_paged_cache(model, cfg, num_blocks, block_size)

        s = num_slots
        self._slot_req: list[Optional[Request]] = [None] * s
        self._tokens = np.zeros((s,), np.int32)  # each slot's current token
        self._positions = np.zeros((s,), np.int32)  # its next cache slot
        self._n_gen = np.zeros((s,), np.int32)  # tokens sampled so far
        self._done = np.ones((s,), bool)  # idle slots read as done
        self._keys = np.zeros((s, 2), np.uint32)
        self._tables = np.zeros((s, self._table_width), np.int32)
        self._emitted = np.zeros((s,), np.int64)  # tokens pushed to handles

        self._next_rid = 0
        self._admit_no = 0  # admission attempts (serve.admit fault site)
        self._decode_no = 0  # decode chunks attempted (serve.step site)
        self._decode_s = 0.0
        self._decode_tokens = 0
        # Bounded: stats() reports percentiles over the most recent
        # window, and a long-lived engine must not grow per-request state.
        self._ttft = deque(maxlen=4096)

    # ------------------------------------------------------------------
    # Submission / draining

    def submit(
        self,
        prompt,
        *,
        max_new_tokens: int,
        key: Any = None,
    ) -> RequestHandle:
        """Queue a request; returns its streaming handle.

        ``key``: an int seed or a PRNG key array — the SAME key a solo
        ``generate(params, prompt[None], key, ...)`` call would take, for
        token parity.  Default: a key derived from the request id.
        """
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(prompt) + max_new_tokens
        if total > self.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens})"
                f" = {total} exceeds max_model_len ({self.max_model_len})"
            )
        if blocks_needed(total, self.block_size) > self.allocator.capacity:
            raise ValueError(
                "request needs more pages than the engine owns "
                f"({blocks_needed(total, self.block_size)} > "
                f"{self.allocator.capacity}); raise num_blocks"
            )
        if key is None:
            key = self._next_rid
        if isinstance(key, int):
            key = jax.random.PRNGKey(key)
        key = np.asarray(key).astype(np.uint32).reshape(2)

        rid = self._next_rid
        self._next_rid += 1
        handle = RequestHandle(self, rid)
        self.scheduler.push(
            Request(rid, prompt, int(max_new_tokens), key, handle)
        )
        _T_REQUESTS.add()
        return handle

    def drain(self) -> None:
        """Step until every submitted request has finished."""
        while len(self.scheduler) or self._n_running():
            self.step()

    def _n_running(self) -> int:
        return sum(r is not None for r in self._slot_req)

    # ------------------------------------------------------------------
    # The engine tick

    def step(self) -> None:
        """One tick: admit + prefill (up to the interleave knob), then one
        decode chunk over the running slots."""
        self._admit_phase()
        self._decode_phase()
        _G_RUNNING.set(self._n_running())

    def _admit_phase(self) -> None:
        free_slots = [
            i for i, r in enumerate(self._slot_req) if r is None
        ]
        if not free_slots or not len(self.scheduler):
            return
        self._admit_no += 1
        try:
            kind = faults.fire("serve.admit", self._admit_no)
        except OSError:
            # Transient admit failure: nothing was popped or allocated —
            # the very next tick retries the same FIFO head.
            _T_ADMIT_RETRIES.add()
            return
        if kind is not None:
            # Cooperation kinds (nan) at this site mean "this admission
            # tick is poisoned": skip it — a consumed spec that silently
            # did nothing would defeat the registry's whole point.
            _T_ADMIT_RETRIES.add()
            return
        batch = self.scheduler.pop_admissible(
            len(free_slots), self.allocator, self.block_size
        )
        for req in batch:
            slot = free_slots.pop(0)
            self._prefill_into(slot, req)

    def _prefill_into(self, slot: int, req: Request) -> None:
        s = len(req.prompt)
        blocks = self.allocator.alloc(
            blocks_needed(req.cache_tokens, self.block_size)
        )
        if blocks is None:  # pop_admissible reserved cumulatively
            raise RuntimeError("scheduler admitted past the free list")
        req.blocks = blocks
        bucket = self._bucket(s)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :s] = req.prompt
        table = np.zeros((self._table_width,), np.int32)
        table[: len(blocks)] = blocks
        try:
            with _telemetry.span(
                "serve.prefill", slot=slot, prompt_len=s, bucket=bucket
            ):
                first, self._cache = _prefill(
                    self._params, self._cache, padded, s, req.key, table,
                    model=self.model, cfg=self.cfg,
                    temperature=self.temperature, top_k=self.top_k,
                    block_size=self.block_size,
                )
                first = int(first)
        except BaseException:
            # A failed prefill (compile error, device OOM) must not leak
            # the reservation — pages go back before the error surfaces,
            # or a few such failures drive the engine into permanent
            # backpressure.  And because the call held the DONATED cache,
            # a failure during execution may have consumed the pool:
            # recover it (failing any in-flight requests whose KV died
            # with it) so the engine stays servable.
            self.allocator.free(blocks)
            req.blocks = None
            self._recover_lost_cache()
            raise
        req.handle.ttft_s = time.perf_counter() - req.submit_t
        self._ttft.append(req.handle.ttft_s)
        _G_TTFT.set(round(req.handle.ttft_s, 4))

        self._slot_req[slot] = req
        self._tokens[slot] = first
        self._positions[slot] = s
        self._n_gen[slot] = 1
        self._done[slot] = False
        self._keys[slot] = req.key
        self._tables[slot] = table
        self._emitted[slot] = 0
        # _push_token retires immediately on a first-token EOS or a
        # budget of one — the slot never enters the decode batch.
        self._push_token(slot, first)

    def _bucket(self, prompt_len: int) -> int:
        """Prompt pad length: next power of two (one prefill compile per
        bucket), capped at ``max_model_len``."""
        b = self.min_prefill_bucket
        while b < prompt_len:
            b *= 2
        return min(b, self.max_model_len)

    def _decode_phase(self) -> None:
        if not self._n_running():
            return
        self._decode_no += 1
        try:
            kind = faults.fire("serve.step", self._decode_no)
        except OSError:
            # Transient: state untouched, next tick re-runs the chunk —
            # decode is pure, so the retry is token-identical.
            _T_STEP_RETRIES.add()
            return
        if kind == "nan":
            # Poisoned step: skip BEFORE dispatch (committed state is the
            # prior state bit-identically — the serving analog of the
            # train loop's skip-step guard), count it, keep going.
            _T_SKIPPED.add()
            return
        sp = _telemetry.start_span(
            "serve.step",
            n_active=self._n_running(),
            chunk=self.decode_chunk,
        )
        t0 = time.perf_counter()
        try:
            self._cache, out = _decode_chunk(
                self._params, self._cache,
                self._tokens, self._positions, self._n_gen, self._done,
                self._keys, self._tables,
                model=self.model, cfg=self.cfg,
                temperature=self.temperature, top_k=self.top_k,
                eos_id=self.eos_id, n_steps=self.decode_chunk,
            )
        except BaseException:
            # The chunk held the donated cache; see _recover_lost_cache.
            sp.cancel()
            self._recover_lost_cache()
            raise
        out = np.asarray(out)  # (chunk, S) — the one host sync per chunk
        dt = time.perf_counter() - t0
        self._decode_s += dt

        committed = 0
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            for tok in out[:, slot]:
                self._push_token(slot, int(tok))
                committed += 1
                if self._slot_req[slot] is None:  # retired mid-chunk
                    break
            else:
                # Still running: roll the slot's device-visible state
                # forward by the whole chunk (post-EOS/budget overshoot
                # inside the chunk stays inside the slot's own pages).
                self._tokens[slot] = out[-1, slot]
                self._positions[slot] += self.decode_chunk
                self._n_gen[slot] += self.decode_chunk
        self._decode_tokens += committed
        if self._decode_s > 0:
            _G_DECODE_TPS.set(round(self._decode_tokens / self._decode_s, 1))
        sp.end(tokens=committed)

    def _push_token(self, slot: int, token: int) -> None:
        """Commit one token to the slot's handle; retire on EOS/budget."""
        req = self._slot_req[slot]
        req.handle._push(token)
        self._emitted[slot] += 1
        _T_TOKENS.add()
        if self._emitted[slot] >= req.max_new_tokens or (
            self.eos_id is not None and token == self.eos_id
        ):
            self._retire(slot)

    def _retire(self, slot: int) -> None:
        req = self._slot_req[slot]
        self.allocator.free(req.blocks)
        req.blocks = None
        req.handle._finish()
        _T_FINISHED.add()
        self._clear_slot(slot)

    def _clear_slot(self, slot: int) -> None:
        self._slot_req[slot] = None
        self._tokens[slot] = 0
        self._positions[slot] = 0
        self._n_gen[slot] = 0
        self._done[slot] = True
        self._tables[slot] = 0  # idle slots scribble on the trash page

    def _recover_lost_cache(self) -> None:
        """Restore servability after a compiled call that held the
        DONATED page pool raised.

        If the failure happened before execution (trace/compile error),
        the donation was never consumed and this is a no-op.  If the
        buffers are gone, every running request's KV died with them:
        those requests are failed loudly (their handles raise — a silent
        truncated stream would look like a short completion), their
        pages freed, and a fresh zeroed pool installed so NEW requests
        keep being served.
        """
        if not any(
            isinstance(x, jax.Array) and x.is_deleted()
            for x in jax.tree.leaves(self._cache)
        ):
            return
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            self.allocator.free(req.blocks)
            req.blocks = None
            req.handle._fail(
                "KV page pool lost to a failed device call"
            )
            self._clear_slot(slot)
        self._cache = init_paged_cache(
            self.model, self.cfg, self.allocator.num_blocks, self.block_size
        )

    # ------------------------------------------------------------------
    # Introspection

    def stats(self) -> dict:
        """Host-side serving stats (TTFT percentiles, sustained decode)."""
        out = {
            "requests": self._next_rid,
            "running": self._n_running(),
            "waiting": len(self.scheduler),
            "decode_tokens": self._decode_tokens,
            "decode_s": round(self._decode_s, 4),
            "block_utilization": round(self.allocator.utilization(), 4),
        }
        if self._decode_s > 0:
            out["decode_tokens_per_s"] = round(
                self._decode_tokens / self._decode_s, 1
            )
        if self._ttft:
            t = np.asarray(self._ttft)
            out["ttft_p50_s"] = round(float(np.percentile(t, 50)), 4)
            out["ttft_p95_s"] = round(float(np.percentile(t, 95)), 4)
        return out
