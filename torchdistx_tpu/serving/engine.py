"""Continuous-batching engine: fixed decode slots over a paged KV cache.

Orca-style iteration-level scheduling (Yu et al., OSDI '22) on top of a
vLLM-style paged cache (Kwon et al., SOSP '23), specialized for the TPU
idiom of this stack: **two compiled programs total** serve any traffic
mix —

* a jitted **prefill** per prompt-length bucket: the family's unchanged
  ``forward_cached`` over the padded prompt, first-token sampling, and
  the page scatter (:func:`.cache.write_prompt`), all one program;
* ONE jitted **decode chunk**: ``decode_chunk`` steps of the family's
  ``forward_paged`` over all ``num_slots`` slots, ``lax.scan``-fused so
  the host syncs once per chunk, not once per token.

Slots admit and retire independently — the moment a sequence hits EOS or
its token budget (observed at the next chunk boundary), its pages free
and the next FIFO request prefills into them.  No request ever waits for
a batch-mate.

**Token parity with solo** :func:`~torchdistx_tpu.models.generate.generate`
is a correctness invariant, not an aspiration: the paged attention path
masks exactly like the contiguous one, per-slot sampling keys are
``fold_in(request_key, n_generated)`` (the same schedule ``generate``
uses), and ``_sample`` is literally the same function — so an engine
under out-of-order admission and mid-stream recycling emits the same
tokens a solo call would.  ``tests/test_serving.py`` pins this, greedy
and sampled.

Sampling config (temperature/top_k/eos) is **engine-level static** — it
is baked into the two compiled programs, exactly as it is baked into a
``generate`` call.  Per-request knobs are prompt, budget, key, and
``deadline_s``.

Request lifecycle (see :mod:`.lifecycle` and ``docs/serving.md``):
per-request **deadlines** and client **cancellation** act at chunk
boundaries (pages released, handles raise typed errors); a bounded
queue with a configurable **shedding policy** (``reject-new`` |
``drop-oldest``) driven by an :class:`.lifecycle.OverloadDetector`
guards admission; and SIGTERM (via
:mod:`torchdistx_tpu.resilience.preemption`) moves the engine through
the :class:`.lifecycle.Health` state machine — admission stops,
in-flight work finishes under ``drain_deadline_s``, the remainder fails
with a *retryable* typed error, never a silent truncation.

Crash recovery: the **supervisor** wraps prefill/decode dispatch.  The
compiled calls hold the page pool DONATED, so a failed device call may
consume every live request's KV — instead of failing them loudly, the
supervisor rebuilds the pool (:func:`.cache.fresh_pool`), resets the
allocator, and *replays* each live request by re-prefilling
``prompt + tokens-generated-so-far``.  Because sampling keys are
``fold_in(key, n_gen)``, the continuation is token-identical — greedy
and sampled — under a per-request ``max_recoveries`` budget before a
typed :class:`.lifecycle.RecoveryFailed`.

Fault sites (``TDX_FAULT``): ``serve.admit`` and ``serve.prefill`` —
``io``/``nan`` requeue at the FIFO head and the next tick retries;
``serve.step`` — ``io`` leaves state untouched (tick retries), ``nan``
marks the chunk poisoned and the engine skips it pre-dispatch (decode is
a pure function of committed state, so the re-run is token-identical —
the serving analog of the training loop's skip-step non-finite guard);
``serve.recover`` — fails one supervisor replay attempt, consuming
recovery budget.  ``fatal`` propagates everywhere: fatal means fatal.
"""

from __future__ import annotations

import time
from collections import deque
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry as _telemetry
from ..models.generate import _sample
from ..resilience import faults
from ..resilience import preemption as _preemption
from .blocks import BlockAllocator, blocks_needed
from .cache import fresh_pool, init_paged_cache, write_prompt
from .lifecycle import (
    DeadlineExceeded,
    EngineDraining,
    EngineOverloaded,
    Health,
    OverloadDetector,
    RecoveryFailed,
    RequestCancelled,
    RequestPreempted,
)
from .scheduler import FIFOScheduler, Request, RequestHandle

__all__ = ["Engine"]

_T_REQUESTS = _telemetry.counter("serve.requests")
_T_FINISHED = _telemetry.counter("serve.finished")
_T_TOKENS = _telemetry.counter("serve.tokens_out")
_T_ADMIT_RETRIES = _telemetry.counter("serve.admit_retries")
_T_PREFILL_RETRIES = _telemetry.counter("serve.prefill_retries")
_T_STEP_RETRIES = _telemetry.counter("serve.step_retries")
_T_SKIPPED = _telemetry.counter("serve.skipped_steps")
_T_SHED = _telemetry.counter("serve.shed")
_T_EXPIRED = _telemetry.counter("serve.expired")
_T_CANCELLED = _telemetry.counter("serve.cancelled")
_T_RECOVERIES = _telemetry.counter("serve.recoveries")
_T_RECOVERY_FAILURES = _telemetry.counter("serve.recovery_failures")
_T_PREEMPTED = _telemetry.counter("serve.preempted")
_G_RUNNING = _telemetry.gauge("serve.running_slots")
_G_DECODE_TPS = _telemetry.gauge("serve.decode_tok_s")
_G_TTFT = _telemetry.gauge("serve.ttft_s")
_G_EST_TTFT = _telemetry.gauge("serve.est_ttft_s")
_G_HEALTH = _telemetry.gauge("serve.health")


@partial(
    jax.jit,
    static_argnames=(
        "model", "cfg", "temperature", "top_k", "block_size",
    ),
    donate_argnums=(1,),
)
def _prefill(
    params, paged, prompt, length, key, table,
    *, model, cfg, temperature, top_k, block_size,
):
    """Compiled prefill: contiguous forward over the padded prompt,
    first-token sample (``fold_in(key, 0)`` — ``generate``'s schedule),
    and the page scatter.  One compile per prompt bucket.  Recovery
    replays reuse this same program over ``prompt + generated-so-far``
    and discard the sampled token."""
    p_pad = prompt.shape[1]
    scratch = model.init_cache(cfg, 1, p_pad)
    logits, scratch = model.forward_cached(params, prompt, cfg, scratch, 0)
    last = jax.lax.dynamic_index_in_dim(
        logits, length - 1, axis=1, keepdims=False
    )
    first = _sample(
        last, jax.random.fold_in(key, 0), temperature, top_k
    ).astype(jnp.int32)[0]
    paged = write_prompt(paged, scratch, table, length, block_size=block_size)
    return first, paged


@partial(
    jax.jit,
    static_argnames=(
        "model", "cfg", "temperature", "top_k", "eos_id", "n_steps",
    ),
    donate_argnums=(1,),
)
def _decode_chunk(
    params, paged, tokens, positions, n_gen, done, keys, block_tables,
    *, model, cfg, temperature, top_k, eos_id, n_steps,
):
    """Compiled decode chunk: ``n_steps`` scan-fused ``forward_paged``
    steps over every slot.  Post-EOS slots keep emitting EOS (solo
    ``generate`` semantics); retired slots scribble on the trash page.
    Returns ``(new paged cache, tokens (n_steps, S))``."""

    def one(carry, _):
        tok, cache, pos, n, dn = carry
        logits, cache = model.forward_paged(
            params, tok[:, None], cfg, cache, block_tables, pos
        )
        step_keys = jax.vmap(jax.random.fold_in)(keys, n)
        nxt = jax.vmap(
            lambda lg, k: _sample(lg[None], k, temperature, top_k)[0]
        )(logits[:, -1], step_keys).astype(jnp.int32)
        if eos_id is not None:
            nxt = jnp.where(dn, eos_id, nxt)
            dn = dn | (nxt == eos_id)
        return (nxt, cache, pos + 1, n + 1, dn), nxt

    (tok, paged, pos, n, dn), out = jax.lax.scan(
        one, (tokens, paged, positions, n_gen, done), None, length=n_steps
    )
    return paged, out


class Engine:
    """Continuous-batching serving engine over one model family.

    Single-host, single-threaded: drive it from ``handle.tokens()`` /
    ``handle.result()`` / :meth:`drain`, or call :meth:`step` yourself.

    Parameters
    ----------
    params : the family's parameter pytree (raw or ``prep_decode``-prepped;
        prepped once at construction when the family supports it).
    model / cfg : the family module + config (the ``generate`` protocol).
    num_slots : decode batch width — concurrent running requests.
    block_size : KV page size in tokens.
    num_blocks : page-pool size; default reserves dense capacity
        (``num_slots`` × the max request) so nothing backpressures unless
        you size it down — sizing it down is the point of paging.
    max_model_len : longest admissible ``prompt + max_new_tokens``; also
        the block-table width, i.e. the decode attention span.  Keep it at
        your real traffic's max, NOT ``cfg.max_seq_len``.
    temperature / top_k / eos_id : engine-static sampling config.
    decode_chunk : decode steps fused per host sync.  Recycling happens at
        chunk boundaries, so large chunks trade slot-turnaround (and thus
        a little throughput under churn) for far fewer host round-trips.
        Deadlines/cancellations are also observed at chunk boundaries.
    max_prefills_per_tick : the prefill/decode interleave knob
        (see :class:`.scheduler.FIFOScheduler`).
    max_queue / max_ttft_s : the overload detector's bounds (both None →
        never overloaded; see :class:`.lifecycle.OverloadDetector`).
    shed_policy : ``"reject-new"`` (overloaded ``submit`` raises
        :class:`.lifecycle.EngineOverloaded`) or ``"drop-oldest"`` (the
        oldest *waiting* request is failed with it instead and the new
        one is admitted).
    max_recoveries : per-request replay budget of the crash-recovery
        supervisor before a typed :class:`.lifecycle.RecoveryFailed`.
    drain_deadline_s : wall-clock budget for in-flight work once a drain
        begins; the remainder fails with
        :class:`.lifecycle.RequestPreempted` (retryable).
    handle_preemption : install the SIGTERM/SIGINT flag handlers
        (:mod:`torchdistx_tpu.resilience.preemption`) so a preemption
        signal drains the engine; programmatic notice goes through
        ``preemption.request()`` either way.  The flag is process-global
        and cleared once acted on (the same convention ``fit()`` uses):
        run ONE preemption consumer per process — an engine and a
        training loop (or two engines) sharing a process would race for
        the notice.  Retire an engine without a drain via
        :meth:`close`, which restores the handlers it installed.
    """

    def __init__(
        self,
        params,
        *,
        model,
        cfg,
        num_slots: int = 8,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        max_model_len: Optional[int] = None,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        eos_id: Optional[int] = None,
        decode_chunk: int = 8,
        max_prefills_per_tick: int = 1,
        min_prefill_bucket: int = 16,
        max_queue: Optional[int] = None,
        max_ttft_s: Optional[float] = None,
        shed_policy: str = "reject-new",
        max_recoveries: int = 2,
        drain_deadline_s: float = 30.0,
        handle_preemption: bool = True,
    ):
        self.model = model
        self.cfg = cfg
        if num_slots < 1:
            # Zero slots would park every request at the FIFO head with
            # no slot ever freeing — tokens() would spin step() forever.
            raise ValueError("num_slots must be >= 1")
        self.num_slots = num_slots
        self.block_size = block_size
        self.max_model_len = int(max_model_len or cfg.max_seq_len)
        if self.max_model_len > cfg.max_seq_len:
            raise ValueError(
                f"max_model_len ({self.max_model_len}) exceeds "
                f"cfg.max_seq_len ({cfg.max_seq_len})"
            )
        self.temperature = float(temperature)
        self.top_k = top_k
        self.eos_id = eos_id
        self.decode_chunk = int(decode_chunk)
        if self.decode_chunk < 1:
            raise ValueError("decode_chunk must be >= 1")
        self.min_prefill_bucket = int(min_prefill_bucket)
        if self.min_prefill_bucket < 1:
            # _bucket doubles up from this value; <= 0 would never
            # terminate.
            raise ValueError("min_prefill_bucket must be >= 1")
        if shed_policy not in ("reject-new", "drop-oldest"):
            raise ValueError(
                f"shed_policy {shed_policy!r}: expected 'reject-new' or "
                "'drop-oldest'"
            )
        self.shed_policy = shed_policy
        self.max_recoveries = int(max_recoveries)
        if self.max_recoveries < 0:
            raise ValueError("max_recoveries must be >= 0")
        self.drain_deadline_s = float(drain_deadline_s)
        self.max_prefills_per_tick = max_prefills_per_tick

        self._table_width = blocks_needed(self.max_model_len, block_size)
        if num_blocks is None:
            num_blocks = 1 + num_slots * self._table_width
        self.allocator = BlockAllocator(num_blocks, block_size)
        self.scheduler = FIFOScheduler(max_prefills_per_tick)
        self.detector = OverloadDetector(max_queue, max_ttft_s)

        prep = getattr(model, "prep_decode", None)
        self._params = prep(params, cfg) if prep is not None else params
        self._cache = init_paged_cache(model, cfg, num_blocks, block_size)

        s = num_slots
        self._slot_req: list[Optional[Request]] = [None] * s
        self._tokens = np.zeros((s,), np.int32)  # each slot's current token
        self._positions = np.zeros((s,), np.int32)  # its next cache slot
        self._n_gen = np.zeros((s,), np.int32)  # tokens sampled so far
        self._done = np.ones((s,), bool)  # idle slots read as done
        self._keys = np.zeros((s, 2), np.uint32)
        self._tables = np.zeros((s, self._table_width), np.int32)
        self._emitted = np.zeros((s,), np.int64)  # tokens pushed to handles

        self._next_rid = 0
        self._admit_no = 0  # admission attempts (serve.admit fault site)
        self._prefill_no = 0  # prefill dispatches (serve.prefill site)
        self._decode_no = 0  # decode chunks attempted (serve.step site)
        self._recover_no = 0  # supervisor replay attempts (serve.recover)
        self._decode_s = 0.0
        self._decode_tokens = 0
        self._consec_decode_failures = 0
        self._n_shed = 0
        self._n_expired = 0
        self._n_cancelled = 0
        self._n_recoveries = 0
        self._n_preempted = 0
        # Bounded: stats() reports percentiles over the most recent
        # window, and a long-lived engine must not grow per-request state.
        self._ttft = deque(maxlen=4096)

        self._drain_t0: Optional[float] = None
        self._drain_sp = None
        self._handle_preemption = handle_preemption
        self._handlers_preexisting = _preemption.installed()
        if handle_preemption:
            _preemption.install()
        self._health = Health.STARTING
        _G_HEALTH.set(self._health.value)

    # ------------------------------------------------------------------
    # Submission / draining

    def submit(
        self,
        prompt,
        *,
        max_new_tokens: int,
        key: Any = None,
        deadline_s: Optional[float] = None,
    ) -> RequestHandle:
        """Queue a request; returns its streaming handle.

        ``key``: an int seed or a PRNG key array — the SAME key a solo
        ``generate(params, prompt[None], key, ...)`` call would take, for
        token parity.  Default: a key derived from the request id.

        ``deadline_s``: wall-clock budget from submission.  A request
        that has not finished when it expires fails with
        :class:`.lifecycle.DeadlineExceeded` at the next chunk boundary
        and releases its pages there.

        Admissibility is validated HERE, immediately: a request that
        could never run — oversized for ``max_model_len``, needing more
        pages than the engine owns — raises ``ValueError`` now rather
        than parking forever at the FIFO head (where ``tokens()`` would
        spin the engine without progress).  Raises the retryable
        :class:`.lifecycle.EngineDraining` when the engine is draining
        or stopped, and sheds per ``shed_policy`` when overloaded.
        """
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(prompt) + max_new_tokens
        if total > self.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens})"
                f" = {total} exceeds max_model_len ({self.max_model_len})"
            )
        if len(prompt) > self._bucket(len(prompt)):
            # Unreachable while _bucket caps at max_model_len >= total,
            # but pinned: a prompt wider than the widest prefill bucket
            # would admit and then crash (or worse, truncate) at prefill.
            raise ValueError(
                f"prompt ({len(prompt)}) exceeds the widest prefill "
                f"bucket ({self._bucket(len(prompt))})"
            )
        if blocks_needed(total, self.block_size) > self.allocator.capacity:
            raise ValueError(
                "request needs more pages than the engine owns "
                f"({blocks_needed(total, self.block_size)} > "
                f"{self.allocator.capacity}); raise num_blocks"
            )
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be > 0 (or None)")
        # Normalize the key BEFORE any shedding side effect: a malformed
        # key must raise without having killed a drop-oldest victim.
        if key is None:
            key = self._next_rid
        if isinstance(key, int):
            key = jax.random.PRNGKey(key)
        key = np.asarray(key).astype(np.uint32).reshape(2)
        if self._health in (Health.DRAINING, Health.STOPPED):
            raise EngineDraining(
                f"engine is {self._health.value}; submit to another replica"
            )
        if self.detector.overloaded(
            len(self.scheduler), self.max_prefills_per_tick
        ):
            self._set_health(Health.OVERLOADED)
            if self.shed_policy == "reject-new":
                _T_SHED.add()
                self._n_shed += 1
                raise EngineOverloaded(
                    "engine overloaded "
                    f"(queue={len(self.scheduler)}, est_ttft="
                    f"{self.detector.est_ttft_s(len(self.scheduler), self.max_prefills_per_tick):.3f}s);"
                    " retry with backoff"
                )
            victim = self.scheduler.shed_oldest()
            if victim is not None:
                _T_SHED.add()
                self._n_shed += 1
                victim.handle._fail(
                    EngineOverloaded(
                        f"request {victim.rid} shed under load (drop-oldest)"
                    )
                )

        rid = self._next_rid
        self._next_rid += 1
        handle = RequestHandle(self, rid)
        deadline = (
            time.perf_counter() + deadline_s if deadline_s is not None else None
        )
        self.scheduler.push(
            Request(
                rid, prompt, int(max_new_tokens), key, handle,
                deadline=deadline,
            )
        )
        _T_REQUESTS.add()
        return handle

    def drain(self) -> None:
        """Step until every submitted request has finished."""
        while len(self.scheduler) or self._n_running():
            self.step()

    def health(self) -> Health:
        """Current :class:`.lifecycle.Health` state."""
        return self._health

    def est_ttft_s(self) -> float:
        """Estimated wait-for-prefill of a request arriving now.

        Router hook (:mod:`torchdistx_tpu.fleet`): the PER-ENGINE value
        behind the process-global ``serve.est_ttft_s`` gauge — a fleet
        of replicas in one process shares that gauge, so anything
        load-balancing across engines must read this instead."""
        return self.detector.est_ttft_s(
            len(self.scheduler), self.max_prefills_per_tick
        )

    def begin_drain(self) -> None:
        """Start a graceful drain NOW, without a preemption signal.

        Router/lifecycle hook: the same path a SIGTERM takes — admission
        closes, the waiting queue fails with retryable typed errors, and
        subsequent :meth:`step` calls finish in-flight work under
        ``drain_deadline_s`` before the engine lands STOPPED.  No-op on
        an engine already DRAINING or STOPPED."""
        if self._health not in (Health.DRAINING, Health.STOPPED):
            self._begin_drain()

    def _set_health(self, health: Health) -> None:
        if health is not self._health:
            self._health = health
            _G_HEALTH.set(health.value)

    def _n_running(self) -> int:
        return sum(r is not None for r in self._slot_req)

    # ------------------------------------------------------------------
    # The engine tick

    def step(self) -> None:
        """One tick: act on preemption, reap expired/cancelled requests,
        admit + prefill (up to the interleave knob), then one decode
        chunk over the running slots."""
        if self._health is Health.STOPPED:
            # Raising (rather than a silent no-op) keeps a stray
            # handle.tokens() loop from spinning a dead engine forever.
            raise EngineDraining("engine is stopped")
        t0 = time.perf_counter()
        if self._health is not Health.DRAINING and _preemption.requested():
            self._begin_drain()
        self._reap_phase()
        if self._health is not Health.DRAINING:
            self._admit_phase()
        self._decode_phase()
        if self._health is Health.DRAINING:
            self._drain_tick()
        elif self._health is Health.STARTING:
            self._set_health(Health.READY)
        elif self._health is Health.OVERLOADED and not self.detector.overloaded(
            len(self.scheduler), self.max_prefills_per_tick
        ):
            self._set_health(Health.READY)
        self.detector.observe_tick(time.perf_counter() - t0)
        # A tick that completed the drain must not re-write the routing
        # gauges _finish_drain just cleared — a stopped engine leaves no
        # stale readings behind.  A live engine re-asserts BOTH every
        # tick (not just on transitions): in a fleet, a peer reaching
        # STOPPED clears the process-global gauges, and the next live
        # replica's tick is what restores them.
        if self._health is not Health.STOPPED:
            _G_HEALTH.set(self._health.value)
            if self.detector.enabled:
                _G_EST_TTFT.set(
                    round(
                        self.detector.est_ttft_s(
                            len(self.scheduler), self.max_prefills_per_tick
                        ),
                        4,
                    )
                )
        _G_RUNNING.set(self._n_running())

    # ------------------------------------------------------------------
    # Lifecycle: reap, drain

    def _reap_phase(self) -> None:
        """Chunk-boundary lifecycle sweep: deadline expiries and client
        cancellations, waiting and running both.  Pages release here —
        'the next chunk boundary' of the documented contract."""
        now = time.perf_counter()
        expired, cancelled = self.scheduler.purge(now)
        for req in expired:
            self._n_expired += 1
            _T_EXPIRED.add()
            req.handle._fail(
                DeadlineExceeded(
                    f"request {req.rid} expired in queue before prefill"
                )
            )
        for req in cancelled:
            self._n_cancelled += 1
            _T_CANCELLED.add()
            req.handle._fail(
                RequestCancelled(f"request {req.rid} cancelled while queued")
            )
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            if req.handle._cancel_requested:
                self._n_cancelled += 1
                _T_CANCELLED.add()
                self._fail_running_slot(
                    slot, RequestCancelled(f"request {req.rid} cancelled")
                )
            elif req.expired(now):
                self._n_expired += 1
                _T_EXPIRED.add()
                self._fail_running_slot(
                    slot,
                    DeadlineExceeded(
                        f"request {req.rid} exceeded its deadline after "
                        f"{self._emitted[slot]} tokens"
                    ),
                )

    def _fail_running_slot(self, slot: int, error) -> None:
        """Abort a running slot: pages back, handle failed typed, slot
        cleared.  The ONE place the release-on-failure choreography
        lives (reap, drain deadline, and close all route here)."""
        req = self._slot_req[slot]
        self.allocator.free(req.blocks)
        req.blocks = None
        req.handle._fail(error)
        self._clear_slot(slot)

    def _begin_drain(self) -> None:
        """Preemption observed: close admission, flush the queue with a
        retryable error, and give in-flight work ``drain_deadline_s``."""
        self._set_health(Health.DRAINING)
        self._drain_t0 = time.perf_counter()
        self._drain_sp = _telemetry.start_span(
            "serve.drain",
            n_running=self._n_running(),
            n_waiting=len(self.scheduler),
        )
        # The flag is acted on (the convention fit() set): a later
        # engine/run in this process starts clean; a platform that is
        # really going down keeps signalling.
        _preemption.clear()
        for req in self.scheduler.flush():
            self._n_preempted += 1
            _T_PREEMPTED.add()
            req.handle._fail(
                RequestPreempted(
                    f"request {req.rid} flushed before prefill: engine "
                    "draining; retry against another replica"
                )
            )

    def _drain_tick(self) -> None:
        if self._n_running() == 0:
            self._finish_drain(timed_out=False)
            return
        if time.perf_counter() - self._drain_t0 > self.drain_deadline_s:
            for slot, req in enumerate(self._slot_req):
                if req is None:
                    continue
                self._n_preempted += 1
                _T_PREEMPTED.add()
                self._fail_running_slot(
                    slot,
                    RequestPreempted(
                        f"request {req.rid} preempted mid-stream: drain "
                        f"deadline ({self.drain_deadline_s}s) expired after "
                        f"{self._emitted[slot]} tokens; retry against "
                        "another replica"
                    ),
                )
            self._finish_drain(timed_out=True)

    def _finish_drain(self, *, timed_out: bool) -> None:
        if self._drain_sp is not None:
            self._drain_sp.end(timed_out=timed_out)
            self._drain_sp = None
        self._set_health(Health.STOPPED)
        # The serving gauges are process-global: a stopped engine must
        # not leave its last readings behind for a router (or an
        # operator tailing the trace) to load-balance on — clear them;
        # the next live replica's tick re-sets both.
        _G_HEALTH.set(None)
        _G_EST_TTFT.set(None)
        if self._handle_preemption and not self._handlers_preexisting:
            _preemption.uninstall()

    def close(self) -> None:
        """Stop the engine NOW: fail queued and in-flight work with
        retryable typed errors, release every page, and restore the
        signal handlers this engine installed.  Idempotent.

        The graceful path is a drain (SIGTERM / ``preemption.request()``
        + stepping); ``close()`` is for retiring an engine without one —
        otherwise the handlers it installed at construction would
        outlive it and swallow the process's next Ctrl-C."""
        if self._health is Health.STOPPED:
            return
        for req in self.scheduler.flush():
            self._n_preempted += 1
            _T_PREEMPTED.add()
            req.handle._fail(
                EngineDraining(
                    f"request {req.rid} rejected: engine closed before "
                    "prefill; retry against another replica"
                )
            )
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            self._n_preempted += 1
            _T_PREEMPTED.add()
            self._fail_running_slot(
                slot,
                RequestPreempted(
                    f"request {req.rid} aborted after "
                    f"{self._emitted[slot]} tokens: engine closed; retry "
                    "against another replica"
                ),
            )
        self._finish_drain(timed_out=False)

    # ------------------------------------------------------------------
    # Admission

    def _admit_phase(self) -> None:
        if not len(self.scheduler):
            return
        free_slots = [
            i for i, r in enumerate(self._slot_req) if r is None
        ]
        if not free_slots:
            # Slot-bound stall with work waiting: the scheduler owns the
            # backpressure rule, so route through it (its limit==0 path
            # counts the stall exactly like a page-bound one — an
            # invisible stall reads as a healthy idle engine).
            self.scheduler.pop_admissible(0, self.allocator, self.block_size)
            return
        self._admit_no += 1
        try:
            kind = faults.fire("serve.admit", self._admit_no)
        except OSError:
            # Transient admit failure: nothing was popped or allocated —
            # the very next tick retries the same FIFO head.
            _T_ADMIT_RETRIES.add()
            return
        if kind is not None:
            # Cooperation kinds (nan) at this site mean "this admission
            # tick is poisoned": skip it — a consumed spec that silently
            # did nothing would defeat the registry's whole point.
            _T_ADMIT_RETRIES.add()
            return
        batch = self.scheduler.pop_admissible(
            len(free_slots), self.allocator, self.block_size
        )
        for i, req in enumerate(batch):
            self._prefill_no += 1
            try:
                kind = faults.fire("serve.prefill", self._prefill_no)
            except OSError:
                # Transient prefill failure before dispatch: the request
                # (and the rest of the batch) returns to the FIFO head.
                _T_PREFILL_RETRIES.add()
                self.scheduler.requeue([req] + batch[i + 1:])
                return
            except BaseException:
                # Fatal kinds propagate, but the popped request must not
                # vanish from every queue on the way out — a handle in
                # neither the FIFO nor a slot spins tokens() forever.
                self.scheduler.requeue([req] + batch[i + 1:])
                raise
            if kind is not None:  # nan: poisoned prefill tick — skip it
                _T_PREFILL_RETRIES.add()
                self.scheduler.requeue([req] + batch[i + 1:])
                return
            slot = free_slots.pop(0)
            try:
                self._prefill_into(slot, req)
            except (KeyboardInterrupt, SystemExit):
                self.scheduler.requeue([req] + batch[i + 1:])
                raise
            except faults.FatalInjectedFault:
                self.scheduler.requeue([req] + batch[i + 1:])
                raise
            except Exception as err:
                # Supervised prefill: the reservation was already
                # released (see _prefill_into); if the donated pool was
                # consumed, rebuild it and replay the live slots, then
                # charge THIS request's budget and retry it from the
                # queue — or fail it typed once the budget is gone.
                if self._pool_lost():
                    self._supervise_recovery(err)
                req.recoveries += 1
                if req.recoveries > self.max_recoveries:
                    _T_RECOVERY_FAILURES.add()
                    req.handle._fail(
                        RecoveryFailed(
                            f"request {req.rid} aborted: prefill failed "
                            f"{req.recoveries} times ({err!r})"
                        )
                    )
                    self.scheduler.requeue(batch[i + 1:])
                else:
                    _T_PREFILL_RETRIES.add()
                    # ONE requeue call: the failed request must land at
                    # the head, AHEAD of its batch-mates (two calls
                    # would appendleft the tail in front of it).
                    self.scheduler.requeue([req] + batch[i + 1:])
                return

    def _prefill_dispatch(self, req: Request, seq: np.ndarray):
        """The ONE prefill choreography (admission and recovery replay
        both route here): reserve the request's full page quota, pad
        ``seq`` to its bucket, run the compiled prefill (pool donated),
        and free the reservation before any error surfaces — a leaked
        reservation drives the engine into permanent backpressure.
        Returns ``(sampled_token, table)``."""
        length = len(seq)
        blocks = self.allocator.alloc(
            blocks_needed(req.cache_tokens, self.block_size)
        )
        if blocks is None:  # admission reserved cumulatively / allocator reset
            raise RuntimeError("prefill could not reserve its promised pages")
        req.blocks = blocks
        bucket = self._bucket(length)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :length] = seq
        table = np.zeros((self._table_width,), np.int32)
        table[: len(blocks)] = blocks
        try:
            first, self._cache = _prefill(
                self._params, self._cache, padded, length, req.key, table,
                model=self.model, cfg=self.cfg,
                temperature=self.temperature, top_k=self.top_k,
                block_size=self.block_size,
            )
        except BaseException:
            self.allocator.free(blocks)
            req.blocks = None
            raise
        return int(first), table

    def _prefill_into(self, slot: int, req: Request) -> None:
        s = len(req.prompt)
        with _telemetry.span(
            "serve.prefill", slot=slot, prompt_len=s, bucket=self._bucket(s)
        ):
            first, table = self._prefill_dispatch(req, req.prompt)
        req.handle.ttft_s = time.perf_counter() - req.submit_t
        self._ttft.append(req.handle.ttft_s)
        _G_TTFT.set(round(req.handle.ttft_s, 4))

        self._slot_req[slot] = req
        self._tokens[slot] = first
        self._positions[slot] = s
        self._n_gen[slot] = 1
        self._done[slot] = False
        self._keys[slot] = req.key
        self._tables[slot] = table
        self._emitted[slot] = 0
        # _push_token retires immediately on a first-token EOS or a
        # budget of one — the slot never enters the decode batch.
        self._push_token(slot, first)

    def _bucket(self, prompt_len: int) -> int:
        """Prompt pad length: next power of two (one prefill compile per
        bucket), capped at ``max_model_len``."""
        b = self.min_prefill_bucket
        while b < prompt_len:
            b *= 2
        return min(b, self.max_model_len)

    # ------------------------------------------------------------------
    # Decode + the recovery supervisor

    def _decode_phase(self) -> None:
        if not self._n_running():
            return
        self._decode_no += 1
        try:
            kind = faults.fire("serve.step", self._decode_no)
        except OSError:
            # Transient: state untouched, next tick re-runs the chunk —
            # decode is pure, so the retry is token-identical.
            _T_STEP_RETRIES.add()
            return
        if kind == "nan":
            # Poisoned step: skip BEFORE dispatch (committed state is the
            # prior state bit-identically — the serving analog of the
            # train loop's skip-step guard), count it, keep going.
            _T_SKIPPED.add()
            return
        sp = _telemetry.start_span(
            "serve.step",
            n_active=self._n_running(),
            chunk=self.decode_chunk,
        )
        t0 = time.perf_counter()
        try:
            self._cache, out = _decode_chunk(
                self._params, self._cache,
                self._tokens, self._positions, self._n_gen, self._done,
                self._keys, self._tables,
                model=self.model, cfg=self.cfg,
                temperature=self.temperature, top_k=self.top_k,
                eos_id=self.eos_id, n_steps=self.decode_chunk,
            )
        except (KeyboardInterrupt, SystemExit):
            sp.cancel()
            raise
        except faults.FatalInjectedFault:
            sp.cancel()
            raise
        except Exception as err:
            sp.cancel()
            self._consec_decode_failures += 1
            if not self._pool_lost() and self._consec_decode_failures <= 1:
                # The donation was not consumed and nothing committed:
                # decode is pure over committed state, so the next
                # tick's re-run is free and token-identical.  One free
                # retry — a deterministic error must not spin, so the
                # second consecutive failure escalates below.
                _T_STEP_RETRIES.add()
                return
            # The chunk held the donated cache (or keeps failing): the
            # supervisor rebuilds the pool and replays every live
            # request token-identically, under per-request budgets.
            self._consec_decode_failures = 0
            self._supervise_recovery(err)
            return
        out = np.asarray(out)  # (chunk, S) — the one host sync per chunk
        self._consec_decode_failures = 0
        dt = time.perf_counter() - t0
        self._decode_s += dt

        committed = 0
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            for tok in out[:, slot]:
                self._push_token(slot, int(tok))
                committed += 1
                if self._slot_req[slot] is None:  # retired mid-chunk
                    break
            else:
                # Still running: roll the slot's device-visible state
                # forward by the whole chunk (post-EOS/budget overshoot
                # inside the chunk stays inside the slot's own pages).
                self._tokens[slot] = out[-1, slot]
                self._positions[slot] += self.decode_chunk
                self._n_gen[slot] += self.decode_chunk
        self._decode_tokens += committed
        if self._decode_s > 0:
            _G_DECODE_TPS.set(round(self._decode_tokens / self._decode_s, 1))
        sp.end(tokens=committed)

    def _pool_lost(self) -> bool:
        """True when a failed donated call consumed the page pool."""
        return any(
            isinstance(x, jax.Array) and x.is_deleted()
            for x in jax.tree.leaves(self._cache)
        )

    def _supervise_recovery(self, error: BaseException) -> None:
        """Restore servability after a failed device call, replaying the
        live requests instead of failing them.

        The pool (and with it every live request's KV) is assumed gone:
        a fresh zeroed pool is installed, the allocator map reset, and
        each live request re-prefilled over ``prompt + generated-so-far``
        — ``fold_in(key, n_gen)`` sampling makes the continuation
        token-identical, greedy and sampled.  Each recovery event (and
        each failed replay) charges the request's ``max_recoveries``
        budget; exhaustion is a typed, *retryable*
        :class:`.lifecycle.RecoveryFailed` — never a silently truncated
        stream.  A failed replay may itself have consumed the fresh pool,
        so the whole pass restarts (budgets keep it finite).
        """
        self._n_recoveries += 1
        _T_RECOVERIES.add()
        sp = _telemetry.start_span(
            "serve.recover",
            n_live=self._n_running(),
            error=type(error).__name__,
        )
        pending = [
            (slot, req)
            for slot, req in enumerate(self._slot_req)
            if req is not None
        ]
        for _, req in pending:
            req.recoveries += 1
        while True:
            replayed = 0  # an aborted pass's replays died with its pool
            self.allocator.reset()
            self._cache = fresh_pool(self._cache)
            still = []
            for slot, req in pending:
                if req.recoveries > self.max_recoveries:
                    req.blocks = None
                    _T_RECOVERY_FAILURES.add()
                    req.handle._fail(
                        RecoveryFailed(
                            f"request {req.rid} aborted: recovery budget "
                            f"({self.max_recoveries}) exhausted after "
                            f"{self._emitted[slot]} tokens ({error!r})"
                        )
                    )
                    self._clear_slot(slot)
                else:
                    still.append((slot, req))
            pending = still
            if not pending:
                break
            failed = False
            for slot, req in pending:
                self._recover_no += 1
                try:
                    kind = faults.fire("serve.recover", self._recover_no)
                    if kind is not None:
                        # Cooperation kinds (nan) poison THIS replay
                        # attempt — a consumed spec that silently did
                        # nothing would defeat the registry's point.
                        raise faults.InjectedFault(
                            f"poisoned replay attempt ({kind})"
                        )
                    self._replay_into(slot, req)
                    replayed += 1
                except (KeyboardInterrupt, SystemExit):
                    sp.cancel()
                    raise
                except faults.FatalInjectedFault:
                    sp.cancel()
                    raise
                except Exception:
                    # This replay's donated call may have consumed the
                    # fresh pool too: charge the failing request and
                    # restart the whole pass from a clean map.
                    req.recoveries += 1
                    failed = True
                    break
            if not failed:
                break
        sp.end(n_replayed=replayed)

    def _replay_into(self, slot: int, req: Request) -> None:
        """Re-prefill a live request's ``prompt + generated-so-far`` into
        fresh pages, restoring the slot exactly where it was.

        The committed tokens live on the handle; all but the last were
        already *fed* to the model (the last is the slot's pending input
        token), so the replayed sequence is ``prompt + tokens[:-1]`` and
        the reused prefill program's sampled token — a recomputation of
        an already-committed one — is discarded.  The next decode step
        samples with ``fold_in(key, n_gen)``, the exact key the
        uninterrupted run would have used."""
        toks = req.handle._tokens
        n_gen = len(toks)
        seq = np.concatenate(
            [req.prompt, np.asarray(toks[:-1], np.int32)]
        ).astype(np.int32)
        # Same dispatch as admission; the sampled token is a
        # recomputation of an already-committed one and is discarded.
        _, table = self._prefill_dispatch(req, seq)
        self._slot_req[slot] = req
        self._tokens[slot] = toks[-1]
        self._positions[slot] = len(seq)
        self._n_gen[slot] = n_gen
        self._done[slot] = False
        self._keys[slot] = req.key
        self._tables[slot] = table
        self._emitted[slot] = n_gen

    # ------------------------------------------------------------------
    # Token commit / retirement

    def _push_token(self, slot: int, token: int) -> None:
        """Commit one token to the slot's handle; retire on EOS/budget."""
        req = self._slot_req[slot]
        req.handle._push(token)
        self._emitted[slot] += 1
        _T_TOKENS.add()
        if self._emitted[slot] >= req.max_new_tokens or (
            self.eos_id is not None and token == self.eos_id
        ):
            self._retire(slot)

    def _retire(self, slot: int) -> None:
        req = self._slot_req[slot]
        self.allocator.free(req.blocks)
        req.blocks = None
        req.handle._finish()
        _T_FINISHED.add()
        self._clear_slot(slot)

    def _clear_slot(self, slot: int) -> None:
        self._slot_req[slot] = None
        self._tokens[slot] = 0
        self._positions[slot] = 0
        self._n_gen[slot] = 0
        self._done[slot] = True
        self._tables[slot] = 0  # idle slots scribble on the trash page

    # ------------------------------------------------------------------
    # Introspection

    def stats(self) -> dict:
        """Host-side serving stats (TTFT percentiles, sustained decode,
        lifecycle counts)."""
        out = {
            "health": self._health.value,
            "requests": self._next_rid,
            "running": self._n_running(),
            "waiting": len(self.scheduler),
            "decode_tokens": self._decode_tokens,
            "decode_s": round(self._decode_s, 4),
            "block_utilization": round(self.allocator.utilization(), 4),
            "shed": self._n_shed,
            "expired": self._n_expired,
            "cancelled": self._n_cancelled,
            "recoveries": self._n_recoveries,
            "preempted": self._n_preempted,
        }
        if self._decode_s > 0:
            out["decode_tokens_per_s"] = round(
                self._decode_tokens / self._decode_s, 1
            )
        if self._ttft:
            t = np.asarray(self._ttft)
            out["ttft_p50_s"] = round(float(np.percentile(t, 50)), 4)
            out["ttft_p95_s"] = round(float(np.percentile(t, 95)), 4)
        return out
