"""Continuous-batching engine: fixed decode slots over a paged KV cache.

Orca-style iteration-level scheduling (Yu et al., OSDI '22) on top of a
vLLM-style paged cache (Kwon et al., SOSP '23), specialized for the TPU
idiom of this stack: **two compiled programs total** serve any traffic
mix —

* a jitted **prefill chunk** per chunk-length bucket: ``prefill_chunk``
  suffix tokens through the family's ``forward_paged`` — the chunk's KV
  scatters into the request's pages and every chunk query attends the
  request's full cached prefix (partial-prefix attention over the block
  table) plus itself; the final chunk also samples the first token.  A
  prompt longer than one chunk prefills across ticks, **interleaved**
  with decode chunks (``max_prefills_per_tick`` now budgets CHUNKS per
  tick), so a 16k-token prompt never head-of-line blocks the running
  streams for more than one chunk;
* ONE jitted **decode chunk**: ``decode_chunk`` steps of the family's
  ``forward_paged`` over all ``num_slots`` slots, ``lax.scan``-fused so
  the host syncs once per chunk, not once per token.

**Prefix caching** (``prefix_cache=True``): full pages of prompt tokens
are content-addressed in a refcounted LRU index
(:class:`.prefix.PrefixIndex`).  A new request whose prompt extends a
cached prefix maps those pages into its block table
(:meth:`.blocks.BlockAllocator.share`) and prefills only the un-cached
suffix; a stream about to write into a shared page gets a private copy
first (**copy-on-write**, :func:`.cache.copy_pages` — never the trash
page).  Unreferenced cached prefixes evict LRU under allocator
pressure, so the cache can never cause an admission stall an empty
cache would not.

Slots admit and retire independently — the moment a sequence hits EOS or
its token budget (observed at the next chunk boundary), its pages free
and the next FIFO request prefills into them.  No request ever waits for
a batch-mate.

**Token parity with solo** :func:`~torchdistx_tpu.models.generate.generate`
is a correctness invariant, not an aspiration: the paged attention path
masks exactly like the contiguous one, per-slot sampling keys are
``fold_in(request_key, n_generated)`` (the same schedule ``generate``
uses), and ``_sample`` is literally the same function — so an engine
under out-of-order admission and mid-stream recycling emits the same
tokens a solo call would.  ``tests/test_serving.py`` pins this, greedy
and sampled.

Sampling config (temperature/top_k/eos) is **engine-level static** — it
is baked into the two compiled programs, exactly as it is baked into a
``generate`` call.  Per-request knobs are prompt, budget, key, and
``deadline_s``.

Request lifecycle (see :mod:`.lifecycle` and ``docs/serving.md``):
per-request **deadlines** and client **cancellation** act at chunk
boundaries (pages released, handles raise typed errors); a bounded
queue with a configurable **shedding policy** (``reject-new`` |
``drop-oldest``) driven by an :class:`.lifecycle.OverloadDetector`
guards admission; and SIGTERM (via
:mod:`torchdistx_tpu.resilience.preemption`) moves the engine through
the :class:`.lifecycle.Health` state machine — admission stops,
in-flight work finishes under ``drain_deadline_s``, the remainder fails
with a *retryable* typed error, never a silent truncation.

Crash recovery: the **supervisor** wraps prefill/decode dispatch.  The
compiled calls hold the page pool DONATED, so a failed device call may
consume every live request's KV — instead of failing them loudly, the
supervisor rebuilds the pool (:func:`.cache.fresh_pool`), resets the
allocator, and *replays* each live request by re-prefilling
``prompt + tokens-generated-so-far``.  Because sampling keys are
``fold_in(key, n_gen)``, the continuation is token-identical — greedy
and sampled — under a per-request ``max_recoveries`` budget before a
typed :class:`.lifecycle.RecoveryFailed`.

**QoS** (``Engine(scheduler="qos")``): admission moves from FIFO to the
SLO-aware :class:`.qos.QoSScheduler` — strict priority classes, per-
tenant weighted fair queueing over prefill-chunk cost, earliest-
deadline-first inside a (class, tenant) queue — and the engine gains
**preemption** of running lower-class streams under page or slot
pressure: **swap-to-host** (private pages gather to a host buffer and
free — shared prefix pages stay mapped on their kept refs; the slot
parks out of the decode batch exactly like a PREFILLING slot until
pressure subsides) or **drop-and-replay** (pages free, the request
requeues carrying its generated-so-far tokens and re-prefills them on
re-admission).  Both resume token-identically — ``fold_in(key, n_gen)``
again — and ``scheduler="fifo"`` (the default) leaves every existing
behavior byte-identical.

**Observability** (docs/observability.md): every request carries a
trace context (``trace_id``/``engine``/``hop``) and emits a lifecycle
event stream (``req.submitted → req.queued → req.admitted →
req.prefill_chunk×N → req.first_token → req.preempted/req.swapped/
req.resumed → req.finished | req.failed``) that
``scripts/trace_report.py`` reconstructs into per-request timelines;
latency distributions (queue wait, prefill, TTFT, per-token decode,
preemption outage) land in per-engine labeled telemetry histograms —
``stats()`` reads its percentiles from them — and the crash-recovery
supervisor dumps the telemetry flight recorder before every replay
pass.  With the ops plane attached the tick itself decomposes under
the time plane (``serve.tick_phase_s{phase=}`` histograms + the
``serve.host_overhead_frac`` host/device split, and a rate-limited
profiler capture when the watchdog/monitor/storm detector fires — see
:mod:`torchdistx_tpu.telemetry.timeplane`).  All of it is free when
nothing records: no events, no trace-id formatting, no record dicts.

Fault sites (``TDX_FAULT``): ``serve.admit`` and ``serve.prefill`` —
``io``/``nan`` requeue at the FIFO head and the next tick retries;
``serve.step`` — ``io`` leaves state untouched (tick retries), ``nan``
marks the chunk poisoned and the engine skips it pre-dispatch (decode is
a pure function of committed state, so the re-run is token-identical —
the serving analog of the training loop's skip-step non-finite guard);
``serve.recover`` — fails one supervisor replay attempt, consuming
recovery budget; ``serve.swap`` — fails one swap-to-host gather (read-
only, device state untouched) and the preemption falls back to
drop-and-replay; ``serve.migrate_out`` — fails one stream-migration
export before its page gather (the source stream keeps running,
untouched); ``serve.migrate_in`` — fails one migration import after the
destination allocated pages but before the scatter (the partial page set
frees, the stream falls back to cold replay); ``serve.materialize`` —
fails one model-pool weight materialization attempt (the skeleton is
untouched, the next tick with demand retries; see :mod:`.modelpool`).
``fatal`` propagates everywhere: fatal means fatal.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import nullcontext
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry as _telemetry
from ..telemetry import audit as _audit
from ..telemetry import ops as _ops
from ..telemetry import perf as _perf
from ..telemetry import timeplane as _timeplane
from ..models.generate import _sample
from ..resilience import faults
from ..resilience import preemption as _preemption
from .blocks import BlockAllocator, blocks_needed
from .cache import (
    copy_pages,
    fresh_pool,
    init_paged_cache,
    pool_geometry,
    swap_in_pages,
    swap_out_pages,
)
from .lifecycle import (
    DeadlineExceeded,
    DeterminismDiverged,
    EngineDraining,
    EngineOverloaded,
    Health,
    MigrationIncompatible,
    OverloadDetector,
    RecoveryFailed,
    RequestCancelled,
    RequestPreempted,
)
from . import journal as _journal_mod
from .journal import RequestJournal
from .modelpool import DEFAULT_MODEL, ModelPool
from .prefix import PrefixIndex, page_hashes
from .qos import QoSScheduler
from .scheduler import FIFOScheduler, Request, RequestHandle

__all__ = ["Engine"]

_T_REQUESTS = _telemetry.counter("serve.requests")
_T_FINISHED = _telemetry.counter("serve.finished")
_T_TOKENS = _telemetry.counter("serve.tokens_out")
_T_ADMIT_RETRIES = _telemetry.counter("serve.admit_retries")
_T_PREFILL_RETRIES = _telemetry.counter("serve.prefill_retries")
_T_STEP_RETRIES = _telemetry.counter("serve.step_retries")
_T_SKIPPED = _telemetry.counter("serve.skipped_steps")
_T_SHED = _telemetry.counter("serve.shed")
_T_EXPIRED = _telemetry.counter("serve.expired")
_T_CANCELLED = _telemetry.counter("serve.cancelled")
_T_RECOVERIES = _telemetry.counter("serve.recoveries")
_T_RECOVERY_FAILURES = _telemetry.counter("serve.recovery_failures")
_T_PREEMPTED = _telemetry.counter("serve.preempted")
_T_PREEMPT_SWAP = _telemetry.counter("serve.preemptions_swap")
_T_PREEMPT_REPLAY = _telemetry.counter("serve.preemptions_replay")
_T_PREFIX_HITS = _telemetry.counter("serve.prefix_hits")
_T_PREFIX_HIT_TOKENS = _telemetry.counter("serve.prefix_hit_tokens")
_T_COW = _telemetry.counter("serve.cow_copies")
_T_PREFIX_EVICTIONS = _telemetry.counter("serve.prefix_evictions")
_T_IDLE_TICKS = _telemetry.counter("serve.idle_ticks")
_T_CORRUPTIONS = _telemetry.counter("serve.corruptions")
_T_MIGRATIONS_OUT = _telemetry.counter("serve.migrations_out")
_T_MIGRATIONS_IN = _telemetry.counter("serve.migrations_in")
_T_MIGRATED_PAGES = _telemetry.counter("serve.migrated_pages")
_T_FORKS = _telemetry.counter("serve.forks")
_G_RUNNING = _telemetry.gauge("serve.running_slots")
_G_DECODE_TPS = _telemetry.gauge("serve.decode_tok_s")
_G_TTFT = _telemetry.gauge("serve.ttft_s")
_G_EST_TTFT = _telemetry.gauge("serve.est_ttft_s")
_G_HEALTH = _telemetry.gauge("serve.health")

# Process-wide engine-id mint: every Engine gets a stable label
# ("eng0", "eng1", ...) for its per-engine metrics and trace context,
# unless the caller names it (Engine(engine_id="replica-a")).
_ENGINE_SEQ = itertools.count()

# Live engines per weights-ledger key: N replicas over one params pytree
# register "weights" once, and the bytes leave the ledger when the LAST
# engine using that pytree stops — a hot-swapped fleet's retired model
# versions must not pile up on mem.hbm_bytes{component=weights} forever
# (that would corrupt exactly the OOM forensics the ledger exists for).
# Locked: construction and teardown may race across threads, and a lost
# refcount update would retire a serving version's bytes early.
_WEIGHTS_REFS: dict = {}
_WEIGHTS_LOCK = threading.Lock()


@partial(
    jax.jit,
    static_argnames=("model", "cfg"),
    donate_argnums=(1,),
)
def _prefill_chunk(params, paged, tokens, start, table, *, model, cfg):
    """Compiled NON-final prefill chunk: ``tokens (1, Cb)`` — suffix
    tokens at positions ``start .. start+Cb-1`` — through the family's
    ``forward_paged``: the chunk's KV scatters into the request's pages
    and every chunk query attends the cached prefix (shared pages
    included) plus itself.  Logits are returned to nobody — XLA dead-code
    eliminates the head matmul.  One compile per chunk bucket."""
    _, paged = model.forward_paged(
        params, tokens, cfg, paged, table[None], start
    )
    return paged


@partial(
    jax.jit,
    static_argnames=("model", "cfg", "temperature", "top_k"),
    donate_argnums=(1,),
)
def _prefill_chunk_last(
    params, paged, tokens, start, last_idx, key, table,
    *, model, cfg, temperature, top_k,
):
    """Compiled FINAL prefill chunk: the chunk scatter/attention of
    :func:`_prefill_chunk` plus the first-token sample from the last
    real token's logits (``fold_in(key, 0)`` — ``generate``'s schedule,
    so outputs stay token-identical whatever the chunking).  Positions
    past ``last_idx`` are padding: their KV lands in the request's own
    not-yet-decoded tail (overwritten by decode before it is ever read)
    or the trash page, and their logits are ignored.  Recovery replays
    reuse this same program over ``prompt + generated-so-far`` and
    discard the sampled token."""
    logits, paged = model.forward_paged(
        params, tokens, cfg, paged, table[None], start
    )
    last = jax.lax.dynamic_index_in_dim(
        logits, last_idx, axis=1, keepdims=False
    )
    with jax.named_scope("sample"):
        first = _sample(
            last, jax.random.fold_in(key, 0), temperature, top_k
        ).astype(jnp.int32)[0]
    return first, paged


@partial(
    jax.jit,
    static_argnames=(
        "model", "cfg", "temperature", "top_k", "eos_id", "n_steps",
    ),
    donate_argnums=(1,),
)
def _decode_chunk(
    params, paged, tokens, positions, n_gen, done, keys, block_tables,
    *, model, cfg, temperature, top_k, eos_id, n_steps,
):
    """Compiled decode chunk: ``n_steps`` scan-fused ``forward_paged``
    steps over every slot.  Post-EOS slots keep emitting EOS (solo
    ``generate`` semantics); retired slots scribble on the trash page.
    Returns ``(new paged cache, tokens (n_steps, S))``."""

    def one(carry, _):
        tok, cache, pos, n, dn = carry
        logits, cache = model.forward_paged(
            params, tok[:, None], cfg, cache, block_tables, pos
        )
        with jax.named_scope("sample"):
            step_keys = jax.vmap(jax.random.fold_in)(keys, n)
            nxt = jax.vmap(
                lambda lg, k: _sample(lg[None], k, temperature, top_k)[0]
            )(logits[:, -1], step_keys).astype(jnp.int32)
        if eos_id is not None:
            nxt = jnp.where(dn, eos_id, nxt)
            dn = dn | (nxt == eos_id)
        return (nxt, cache, pos + 1, n + 1, dn), nxt

    (tok, paged, pos, n, dn), out = jax.lax.scan(
        one, (tokens, paged, positions, n_gen, done), None, length=n_steps
    )
    return paged, out


# Compile observatory (docs/observability.md, "Perf plane"): the three
# compiled programs under stable labels — decode must compile exactly
# once per engine shape (the steady-state invariant the recompile-storm
# detector guards), prefill once per chunk bucket.  Late-bound through
# the module globals so the chaos tests' monkeypatched stand-ins
# (``engine._decode_chunk = flaky``) keep working, uninstrumented.
_JP_PREFILL = _perf.JitProgram(lambda: _prefill_chunk, "prefill_chunk")
_JP_PREFILL_LAST = _perf.JitProgram(
    lambda: _prefill_chunk_last, "prefill_chunk_last"
)
_JP_DECODE = _perf.JitProgram(lambda: _decode_chunk, "decode_chunk")


class Engine:
    """Continuous-batching serving engine over one model family.

    Single-host, single-threaded: drive it from ``handle.tokens()`` /
    ``handle.result()`` / :meth:`drain`, or call :meth:`step` yourself.

    Parameters
    ----------
    params : the family's parameter pytree (raw or ``prep_decode``-prepped;
        prepped once at construction when the family supports it).
    model / cfg : the family module + config (the ``generate`` protocol).
    num_slots : decode batch width — concurrent running requests.
    block_size : KV page size in tokens.
    num_blocks : page-pool size; default reserves dense capacity
        (``num_slots`` × the max request) so nothing backpressures unless
        you size it down — sizing it down is the point of paging.
    max_model_len : longest admissible ``prompt + max_new_tokens``; also
        the block-table width, i.e. the decode attention span.  Keep it at
        your real traffic's max, NOT ``cfg.max_seq_len``.
    temperature / top_k / eos_id : engine-static sampling config.
    decode_chunk : decode steps fused per host sync.  Recycling happens at
        chunk boundaries, so large chunks trade slot-turnaround (and thus
        a little throughput under churn) for far fewer host round-trips.
        Deadlines/cancellations are also observed at chunk boundaries.
    max_prefills_per_tick : the prefill/decode interleave knob, now in
        prefill CHUNKS per tick (see :class:`.scheduler.FIFOScheduler`);
        for prompts no longer than ``prefill_chunk`` it is the old
        requests-per-tick knob unchanged.
    prefill_chunk : prefill tokens dispatched per compiled chunk.  A
        prompt suffix longer than this splits across ticks, interleaved
        with decode — a 16k prompt stalls running streams for at most
        one chunk's forward per tick instead of the whole prompt's.
        Smaller chunks mean smoother decode but more dispatches (and the
        per-chunk block-table attention re-reads the prefix).
    prefix_cache : content-address full prompt pages in a refcounted LRU
        index so requests sharing a cached prefix skip its prefill
        (copy-on-write on divergence, LRU eviction under pressure).
        ON by default: outputs are token-identical either way and
        eviction is admission-safe (the cache can never cause a stall
        an empty cache would not).  Opt out (``False``) for code that
        asserts on raw ``num_in_use`` accounting — sharing keeps
        finished requests' full prompt pages resident in the index
        (``num_in_use == len(engine.prefix)`` at idle, every indexed
        page refcount 1) until pressure evicts them or the engine
        stops.
    scheduler : ``"fifo"`` (default — byte-identical to the pre-QoS
        engine) or ``"qos"`` (:class:`.qos.QoSScheduler`: strict
        priority classes, per-tenant weighted fair queueing over
        prefill-chunk cost, EDF within a class — plus preemption of
        running lower-class streams, see ``preempt_mechanism``).
    tenant_weights : ``{tenant: weight}`` fair-queueing shares
        (``scheduler="qos"`` only); unlisted tenants weigh 1.
    preempt_mechanism : how page pressure preempts a running
        lower-class stream under QoS: ``"swap"`` (default — pages to a
        host buffer, slot parks, swapped back in when pressure drops)
        or ``"replay"`` (pages freed, request requeues with its
        generated-so-far tokens and re-prefills them on re-admission).
        Slot pressure always uses replay (only replay frees a slot);
        a failed swap falls back to replay.  Both are invisible in the
        token stream.
    max_queue / max_ttft_s : the overload detector's bounds (both None →
        never overloaded; see :class:`.lifecycle.OverloadDetector`).
    shed_policy : ``"reject-new"`` (overloaded ``submit`` raises
        :class:`.lifecycle.EngineOverloaded`), ``"drop-oldest"`` (the
        oldest *waiting* request is failed with it instead and the new
        one is admitted), or ``"by-priority"`` (QoS only: the victim is
        the lowest class, youngest first — an arrival that is itself
        the lowest class is the one rejected).
    max_recoveries : per-request replay budget of the crash-recovery
        supervisor before a typed :class:`.lifecycle.RecoveryFailed`.
    drain_deadline_s : wall-clock budget for in-flight work once a drain
        begins; the remainder fails with
        :class:`.lifecycle.RequestPreempted` (retryable).
    engine_id : stable label for this engine's per-engine metrics
        (``serve.health{engine=...}``, the latency histograms) and its
        trace context (docs/observability.md).  Default: a process-wide
        mint ("eng0", "eng1", ...).  In a fleet, name replicas so traces
        read well — and REUSE the retired replica's id when respawning:
        labeled instruments live in the process-wide registry for the
        process lifetime (standard label-cardinality economics), so a
        churn of fresh ids grows the registry and every exported
        counters snapshot, while a reused id continues the same
        instruments.
    ops_port : opt into the live ops plane
        (:mod:`torchdistx_tpu.telemetry.ops`): an HTTP endpoint serving
        ``/metrics`` (Prometheus text exposition of the whole telemetry
        registry), ``/healthz`` (this engine's Health; non-200 when not
        READY/STARTING, connection-refused once STOPPED tore the plane
        down), and ``/requests`` (live per-request timelines off the
        flight ring) — plus a stall watchdog thread and the SLO
        burn-rate monitor, and per-tick utilization attribution gauges
        (``serve.occupancy``/``serve.prefill_budget``/``serve.page_util``
        /``serve.churn``/``serve.goodput`` and the ``serve.tick_s``
        histogram, all ``{engine=...}``-labeled).  ``0`` binds an
        ephemeral port; engines passing the same non-zero port share
        one plane.  Default: ``TDX_OPS_PORT`` when set, else off — and
        off costs nothing per tick (no gauge writes, no allocation).
    ops_config : :class:`torchdistx_tpu.telemetry.ops.OpsConfig` —
        watchdog deadline, SLO targets/windows, bind host.  Applies
        when this engine CREATES the plane; joiners share as-is.
    handle_preemption : install the SIGTERM/SIGINT flag handlers
        (:mod:`torchdistx_tpu.resilience.preemption`) so a preemption
        signal drains the engine; programmatic notice goes through
        ``preemption.request()`` either way.  The flag is process-global
        and cleared once acted on (the same convention ``fit()`` uses):
        run ONE preemption consumer per process — an engine and a
        training loop (or two engines) sharing a process would race for
        the notice.  Retire an engine without a drain via
        :meth:`close`, which restores the handlers it installed.
    role : disaggregation role of this engine in a fleet —
        ``"mixed"`` (default: serves anything, the solo-engine
        behavior), ``"prefill"`` (the router steers long prompts here;
        streams migrate OUT to a decode-role peer once their prefill
        completes), or ``"decode"`` (protected from long prompts; the
        natural :meth:`migrate_in` destination).  The role changes
        nothing engine-side — admission, ticking, and recovery are
        identical — it is a routing/migration hint the
        :class:`~torchdistx_tpu.fleet.FleetRouter` and autoscaler read
        (docs/fleet.md, "Disaggregation & stream migration").  Exported
        as the ``serve.role{engine=...}`` labeled gauge, pruned at
        STOPPED.
    model_version : weights-version tag folded into every request's
        determinism digest (docs/observability.md, "Audit plane").  Tag
        real weight versions distinctly (hot-swap standbys especially):
        the fleet's digest-based failover verification then rejects a
        version-mixed stream even when the token ids happen to agree.
    audit_sample : fraction of COMPLETED requests the shadow auditor
        (:class:`torchdistx_tpu.telemetry.audit.ShadowAuditor`)
        re-executes through the engine's own chunked-prefill + decode
        programs — zero new compiled geometries — at the lowest QoS
        class, only on ticks with no user work waiting, and
        digest-compares against the original stream
        (``TDX_AUDIT_SAMPLE`` when None; 0/unset = off).  A mismatch
        bumps ``audit.divergences``, latches
        ``serve.diverging{engine=...}`` (the engine reads OVERLOADED —
        routed around like a stall — until :meth:`clear_divergence`),
        and flight-dumps ``reason="divergence"`` with both token
        streams for ``scripts/incident_replay.py`` to bisect.
    model_pool : a :class:`~.modelpool.ModelPool` of deferred-init
        skeleton models to serve ALONGSIDE this engine's own model,
        all decoding into this one page pool (docs/serving.md, "Model
        plane").  Binding validates every registered skeleton's KV page
        geometry against the live pool; ``submit(model=tag)`` then
        routes traffic per model — weights materialize on first demand
        (one model per tick, after the decode dispatch, so a cold
        model's load stall never blocks a hot model's token cadence)
        and evict LRU under the pool's residency knobs.  Each model's
        ``model_version`` seeds its requests' determinism digests, and
        the prefix index namespaces page hashes by model tag — two
        models never share a KV page or a digest.  None (default):
        single-model engine, bit-identical behavior.
    """

    def __init__(
        self,
        params,
        *,
        model,
        cfg,
        num_slots: int = 8,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        max_model_len: Optional[int] = None,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        eos_id: Optional[int] = None,
        decode_chunk: int = 8,
        max_prefills_per_tick: int = 1,
        prefill_chunk: int = 512,
        prefix_cache: bool = True,
        min_prefill_bucket: int = 16,
        scheduler: str = "fifo",
        tenant_weights: Optional[dict] = None,
        preempt_mechanism: str = "swap",
        max_queue: Optional[int] = None,
        max_ttft_s: Optional[float] = None,
        shed_policy: str = "reject-new",
        max_recoveries: int = 2,
        drain_deadline_s: float = 30.0,
        handle_preemption: bool = True,
        engine_id: Optional[str] = None,
        ops_port: Optional[int] = None,
        ops_config: Optional[_ops.OpsConfig] = None,
        role: str = "mixed",
        model_version: str = "v0",
        audit_sample: Optional[float] = None,
        model_pool: Optional[ModelPool] = None,
        journal: Optional[RequestJournal] = None,
    ):
        self.model = model
        self.cfg = cfg
        self.model_version = str(model_version)
        self.engine_id = (
            str(engine_id) if engine_id is not None
            else f"eng{next(_ENGINE_SEQ)}"
        )
        if num_slots < 1:
            # Zero slots would park every request at the FIFO head with
            # no slot ever freeing — tokens() would spin step() forever.
            raise ValueError("num_slots must be >= 1")
        self.num_slots = num_slots
        self.block_size = block_size
        self.max_model_len = int(max_model_len or cfg.max_seq_len)
        if self.max_model_len > cfg.max_seq_len:
            raise ValueError(
                f"max_model_len ({self.max_model_len}) exceeds "
                f"cfg.max_seq_len ({cfg.max_seq_len})"
            )
        self.temperature = float(temperature)
        self.top_k = top_k
        self.eos_id = eos_id
        self.decode_chunk = int(decode_chunk)
        if self.decode_chunk < 1:
            raise ValueError("decode_chunk must be >= 1")
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.min_prefill_bucket = int(min_prefill_bucket)
        if self.min_prefill_bucket < 1:
            # _chunk_bucket doubles up from this value; <= 0 would never
            # terminate.
            raise ValueError("min_prefill_bucket must be >= 1")
        if scheduler not in ("fifo", "qos"):
            raise ValueError(
                f"scheduler {scheduler!r}: expected 'fifo' or 'qos'"
            )
        self._qos = scheduler == "qos"
        if tenant_weights is not None and not self._qos:
            raise ValueError(
                "tenant_weights needs scheduler='qos' (the FIFO scheduler "
                "ignores tenancy — a silently-dropped weight map would "
                "masquerade as fairness)"
            )
        if preempt_mechanism not in ("swap", "replay"):
            raise ValueError(
                f"preempt_mechanism {preempt_mechanism!r}: expected "
                "'swap' or 'replay'"
            )
        self.preempt_mechanism = preempt_mechanism
        if shed_policy not in ("reject-new", "drop-oldest", "by-priority"):
            raise ValueError(
                f"shed_policy {shed_policy!r}: expected 'reject-new', "
                "'drop-oldest', or 'by-priority'"
            )
        if shed_policy == "by-priority" and not self._qos:
            raise ValueError(
                "shed_policy='by-priority' needs scheduler='qos' (the FIFO "
                "scheduler has no priority classes to shed by)"
            )
        self.shed_policy = shed_policy
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"role {role!r}: expected 'prefill', 'decode', or 'mixed'"
            )
        self.role = role
        self.max_recoveries = int(max_recoveries)
        if self.max_recoveries < 0:
            raise ValueError("max_recoveries must be >= 0")
        self.drain_deadline_s = float(drain_deadline_s)
        self.max_prefills_per_tick = max_prefills_per_tick

        self._table_width = blocks_needed(self.max_model_len, block_size)
        if num_blocks is None:
            num_blocks = 1 + num_slots * self._table_width
        self.allocator = BlockAllocator(num_blocks, block_size)
        self.scheduler = (
            QoSScheduler(max_prefills_per_tick, tenant_weights)
            if self._qos
            else FIFOScheduler(max_prefills_per_tick)
        )
        # Per-engine queue-depth family (serve.queue_depth{engine=...}):
        # the unlabeled gauge is process-global and N replicas in one
        # process clobber it — a fleet router or autoscaler must read
        # the labeled family.  Pruned at STOPPED (_finish_drain).
        self.scheduler.bind_engine(self.engine_id)
        self.detector = OverloadDetector(max_queue, max_ttft_s)
        self.prefix: Optional[PrefixIndex] = (
            PrefixIndex(block_size) if prefix_cache else None
        )

        prep = getattr(model, "prep_decode", None)
        self._params = prep(params, cfg) if prep is not None else params
        self._cache = init_paged_cache(model, cfg, num_blocks, block_size)
        self._pool_nbytes = _perf.pytree_nbytes(self._cache)
        self._page_nbytes = self._pool_nbytes // max(1, num_blocks)
        self._swap_host_bytes = 0
        # The weights-ledger anchor: the identity of the CALLER's first
        # params leaf, not the prepped tree (prep_decode mints a fresh
        # pytree per engine, so N replicas constructed from one
        # materialized pytree would otherwise register N times).  The
        # anchor leaf is RETAINED so the id cannot be recycled onto a
        # different weight set while this engine lives (a collided key
        # would merge two versions' bytes into one entry); one leaf,
        # not the whole raw tree — the prepped tree shares most leaves
        # anyway.  Registration itself happens at the END of __init__,
        # after everything fallible: a constructor that raises (ops
        # port in use, signal handlers off the main thread) must not
        # leak ledger entries no _finish_drain will ever release.
        leaves = jax.tree.leaves(params)
        self._weights_anchor = leaves[0] if leaves else params
        self._weights_key = f"params:{id(self._weights_anchor)}"

        s = num_slots
        self._slot_req: list[Optional[Request]] = [None] * s
        self._tokens = np.zeros((s,), np.int32)  # each slot's current token
        self._positions = np.zeros((s,), np.int32)  # its next cache slot
        self._n_gen = np.zeros((s,), np.int32)  # tokens sampled so far
        self._done = np.ones((s,), bool)  # idle slots read as done
        self._keys = np.zeros((s, 2), np.uint32)
        self._tables = np.zeros((s, self._table_width), np.int32)
        self._emitted = np.zeros((s,), np.int64)  # tokens pushed to handles
        # Slots mid-prefill, in admission order: they hold pages and a
        # slot but are NOT in the decode batch (their device-visible
        # table stays 0 → trash) until their last chunk samples the
        # first token.  Strict FIFO: the head gets every chunk of the
        # tick's budget until it completes.
        self._prefill_q: list[int] = []
        # Slots swapped to host (QoS preemption): they park in their
        # slot, out of the decode batch exactly like PREFILLING slots
        # (device table 0 → trash, done=True).  Only PRIVATE pages
        # (refcount 1) transfer to host and free; shared pages (prefix
        # index / CoW peers also hold them) stay mapped on the refs the
        # request keeps — swapping them would duplicate them at
        # swap-in.  slot -> (host KV pytree of the private rows,
        # layout) where layout[i] is the kept page id or None for the
        # i-th table position (None rows match host-buffer order).
        self._swapped: dict[int, tuple] = {}
        # Parallel sampling (submit(n=4), docs/serving.md "Model plane"):
        # per fork group, the ENGINE-held share references on the
        # parent's prompt-covering pages — created when the parent's
        # prefill completes, so siblings admitted later map them without
        # re-prefilling even if the parent has already retired.  Swept
        # once every sibling is terminal (_reap_phase); freed wholesale
        # at drain; cleared without frees after an allocator reset (the
        # pages died with the pool).  parent rid -> [page ids].
        self._fork_donors: dict[int, list] = {}
        # parent rid -> the sibling Requests of the group (parent
        # excluded — the donor exists for THEM).
        self._fork_groups: dict[int, list] = {}
        # Cold pool models with demand (submit seen / admission head
        # held), in demand order: the materialize phase serves ONE per
        # tick, after the decode dispatch.  Insertion-ordered dict used
        # as an ordered set.
        self._materialize_wanted: dict[str, None] = {}

        self._next_rid = 0
        self._admit_no = 0  # admission attempts (serve.admit fault site)
        self._prefill_no = 0  # prefill dispatches (serve.prefill site)
        self._decode_no = 0  # decode chunks attempted (serve.step site)
        self._recover_no = 0  # supervisor replay attempts (serve.recover)
        self._swap_no = 0  # swap-out attempts (serve.swap fault site)
        self._migrate_out_no = 0  # stream exports (serve.migrate_out site)
        self._migrate_in_no = 0  # stream imports (serve.migrate_in site)
        self._preempted_this_tick = False  # swap-in back-off after a preempt
        self._decode_s = 0.0
        self._decode_tokens = 0
        self._consec_decode_failures = 0
        self._n_shed = 0
        self._n_expired = 0
        self._n_cancelled = 0
        self._n_recoveries = 0
        self._n_preempted = 0
        self._n_preempt_swap = 0
        self._n_preempt_replay = 0
        self._n_migrated_out = 0
        self._n_migrated_in = 0
        self._n_cow = 0
        self._n_forks = 0

        # Per-engine labeled metrics (docs/observability.md): N fleet
        # replicas in one process each get their own readings instead of
        # clobbering the process-global gauges (which are still set, for
        # back-compat, by whichever engine ticked last — and cleared at
        # STOPPED so a router never load-balances on a dead engine's
        # leavings; the labeled gauge needs no such workaround, its final
        # "stopped" reading is unambiguous).  Histograms are bounded
        # fixed-bucket state — a long-lived engine does not grow
        # per-request lists — and always accumulate, sink or no sink:
        # stats() reads its percentiles from them.
        eid = self.engine_id
        self._lg_health = _telemetry.gauge("serve.health", engine=eid)
        self._lg_est_ttft = _telemetry.gauge("serve.est_ttft_s", engine=eid)
        self._lg_running = _telemetry.gauge("serve.running_slots", engine=eid)
        self._h_queue_wait = _telemetry.histogram(
            "serve.queue_wait_s", engine=eid
        )
        self._h_prefill = _telemetry.histogram("serve.prefill_s", engine=eid)
        self._h_ttft = _telemetry.histogram("serve.ttft_s", engine=eid)
        self._h_tpot = _telemetry.histogram("serve.tpot_s", engine=eid)
        self._h_outage = _telemetry.histogram(
            "serve.preempt_outage_s", engine=eid
        )
        # Disaggregation role (docs/fleet.md): a labeled gauge so an
        # operator (and the autoscaler's role-aware placement) can read
        # the fleet's role split off /metrics.  Pruned at STOPPED like
        # every per-engine dynamic-label family.
        self._lg_role = _telemetry.gauge("serve.role", engine=eid)
        self._lg_role.set(self.role)

        self._drain_t0: Optional[float] = None
        self._drain_sp = None
        self._handle_preemption = handle_preemption
        self._handlers_preexisting = _preemption.installed()
        if handle_preemption:
            _preemption.install()
        self._health = Health.STARTING
        _G_HEALTH.set(self._health.value)
        self._lg_health.set(self._health.value)

        # Audit plane (docs/observability.md, "Audit plane"): the
        # divergence latch plus the opt-in shadow auditor.  Validation
        # happens HERE, BEFORE the ops-plane attach and the perf-plane
        # registrations below — a constructor that raises on a bad
        # audit_sample must not leave a half-built engine watched by a
        # plane no _finish_drain will ever unwatch.
        self._diverging = False
        if audit_sample is None:
            audit_sample = _audit.env_audit_sample()
        self._auditor: Optional[_audit.ShadowAuditor] = (
            _audit.ShadowAuditor(self, audit_sample)
            if audit_sample
            else None
        )

        # Model plane (docs/serving.md, "Model plane"): bind the pool —
        # geometry validation for every registered skeleton happens
        # here, BEFORE the ops-plane attach and the perf-plane
        # registrations, so an incompatible model rejects the
        # constructor rather than the first unlucky request.
        self.model_pool = model_pool
        if model_pool is not None:
            model_pool._bind(self)

        # Durability plane (docs/resilience.md, "Durability"): claim the
        # request journal — geometry check, ownership lock, config
        # record — still BEFORE the ops-plane attach and the perf-plane
        # registrations, so a refused claim (another live engine owns
        # the journal: typed JournalOwned) rejects the constructor
        # cleanly instead of leaking watched planes.
        self._journal: Optional[RequestJournal] = None
        if journal is not None:
            self._bind_journal(journal)

        # Live ops plane (docs/observability.md, "Ops plane").  The
        # tick counter always counts (one int add — the watchdog's
        # progress key reads it); everything else — the per-tick
        # attribution gauges below, the watchdog thread, the HTTP
        # listener — exists only once a plane is attached (or
        # ops.enable_tick_attribution() forced attribution on), so the
        # disabled path pays nothing per tick.
        self._tick_no = 0
        self._was_idle = False  # last tick's idleness (gauge-zeroing edge)
        self._g_occupancy = None  # per-tick gauges, minted on first use
        # Time plane (docs/observability.md, "Time plane"): the per-tick
        # phase timer (live only inside step(), only with the ops plane
        # or forced attribution on) and its lazily minted histogram
        # family, both owned by telemetry.timeplane.
        self._tick_timer: Optional[_timeplane.TickTimer] = None
        self._tp_state = None
        self._ops_plane: Optional[_ops.OpsPlane] = None
        if ops_port is None:
            ops_port = _ops.env_ops_port()
        if ops_port is not None:
            self._ops_plane = _ops.attach_engine(
                self, port=int(ops_port), config=ops_config
            )
        if _telemetry.events_enabled():
            # The engine's geometry, stamped into the event stream: a
            # flight dump then carries everything incident_replay.py
            # needs to rebuild an equivalent engine (the weights come
            # from the operator — bytes don't belong in a trace).
            _telemetry.event(
                "serve.engine_config",
                engine=self.engine_id,
                num_slots=num_slots,
                block_size=block_size,
                num_blocks=int(num_blocks),
                max_model_len=self.max_model_len,
                temperature=self.temperature,
                top_k=top_k,
                eos_id=eos_id,
                decode_chunk=self.decode_chunk,
                prefill_chunk=self.prefill_chunk,
                max_prefills_per_tick=max_prefills_per_tick,
                scheduler=scheduler,
                role=self.role,
                model_version=self.model_version,
            )

        # Perf plane (docs/observability.md, "Perf plane"), LAST —
        # nothing after this can raise, so every registration is
        # balanced by _finish_drain: arm the compile observatory and
        # put this engine's device bytes on the HBM ledger.  Weights
        # dedupe by params identity (refcounted — N replicas over one
        # materialized pytree are one copy of HBM, retiring with the
        # last of them); the pool is per engine.  kv_swap_host /
        # prefix_cache_held are live accounts, synced per tick (ops
        # plane on) and at every OOM dump.
        _perf.install_monitoring()
        with _WEIGHTS_LOCK:
            _WEIGHTS_REFS[self._weights_key] = (
                _WEIGHTS_REFS.get(self._weights_key, 0) + 1
            )
        _perf.ledger.register(
            "weights", _perf.pytree_nbytes(self._params),
            owner=self._weights_key,
        )
        _perf.ledger.register(
            "kv_pool", self._pool_nbytes, owner=self.engine_id
        )

    # ------------------------------------------------------------------
    # Request tracing (docs/observability.md, "Request tracing")

    def _event(self, name: str, req: Request, **attrs) -> None:
        """Emit one request-lifecycle event carrying the trace context.
        Free for untraced requests: ``trace_id`` stays None when nothing
        was recording at submit, and the guard here is one attribute
        read — no record dict, no string formatting."""
        if req.trace_id is None:
            return
        _telemetry.event(
            name, rid=req.trace_id, engine=self.engine_id, hop=req.hop,
            **attrs,
        )

    def _trace_ctx(self, req: Request):
        """Context manager stamping ``rid``/``engine``/``hop`` onto every
        span started inside (the serve.prefill chunk spans); a no-op
        nullcontext for untraced requests."""
        if req.trace_id is None:
            return nullcontext()
        return _telemetry.tracing(
            rid=req.trace_id, engine=self.engine_id, hop=req.hop
        )

    # ------------------------------------------------------------------
    # Submission / draining

    def submit(
        self,
        prompt,
        *,
        max_new_tokens: int,
        key: Any = None,
        deadline_s: Optional[float] = None,
        tenant: str = "default",
        priority: int = 0,
        model: Optional[str] = None,
        n: int = 1,
        trace_id: Optional[str] = None,
        hop: int = 0,
        _audit_of: Optional[str] = None,
    ) -> RequestHandle:
        """Queue a request; returns its streaming handle.

        ``model``: a tag registered on this engine's
        :class:`~.modelpool.ModelPool` — the request decodes under THAT
        model's weights (materialized on demand) with its
        ``model_version`` seeding the determinism digest and its tag
        namespacing the prefix-cache page hashes.  None (default): the
        engine's own construction-time model, unchanged semantics.

        ``n``: parallel samples of this one prompt (``n > 1`` forks the
        request into ``n`` siblings).  Siblings SHARE the parent's
        prompt pages — the prompt prefills once; each fork pays only
        its marginal pages, diverging copy-on-write — and sample
        independently: sibling ``i``'s key is ``fold_in(key, i)``, so
        each is token-identical to a solo ``submit`` with that folded
        key (``n == 1`` leaves the key untouched).  The returned handle
        is sibling 0; ``handle.siblings`` lists all ``n`` handles in
        index order.  Each sibling is its own request end to end — own
        deadline, own digest, own lifecycle — cancel one and the rest
        keep decoding.

        ``trace_id`` / ``hop``: the request-scoped trace context (see
        docs/observability.md).  A router forwards ONE id across every
        failover hop (``hop`` counts re-submissions) so the hops
        reconstruct into a single timeline; left unset, the engine mints
        ``"{engine_id}-r{rid}"`` — lazily, only when something is
        recording, so the disabled path formats no strings.

        ``key``: an int seed or a PRNG key array — the SAME key a solo
        ``generate(params, prompt[None], key, ...)`` call would take, for
        token parity.  Default: a key derived from the request id.

        ``deadline_s``: wall-clock budget from submission.  A request
        that has not finished when it expires fails with
        :class:`.lifecycle.DeadlineExceeded` at the next chunk boundary
        and releases its pages there.  Under ``scheduler="qos"`` the
        deadline also *orders*: earliest-deadline-first within a
        (priority, tenant) queue.

        ``tenant`` / ``priority``: the request's QoS context —
        fair-queueing share owner and priority class (higher admits
        first and preempts running lower classes under pressure).
        Inert under the default FIFO scheduler; carried either way so a
        router can forward them unconditionally.

        Admissibility is validated HERE, immediately: a request that
        could never run — oversized for ``max_model_len``, needing more
        pages than the engine owns — raises ``ValueError`` now rather
        than parking forever at the FIFO head (where ``tokens()`` would
        spin the engine without progress).  Raises the retryable
        :class:`.lifecycle.EngineDraining` when the engine is draining
        or stopped, and sheds per ``shed_policy`` when overloaded.
        """
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(prompt) + max_new_tokens
        if total > self.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens})"
                f" = {total} exceeds max_model_len ({self.max_model_len})"
            )
        if blocks_needed(total, self.block_size) > self.allocator.capacity:
            raise ValueError(
                "request needs more pages than the engine owns "
                f"({blocks_needed(total, self.block_size)} > "
                f"{self.allocator.capacity}); raise num_blocks"
            )
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be > 0 (or None)")
        tenant = str(tenant)
        if not tenant:
            raise ValueError("tenant must be a non-empty string")
        priority = int(priority)
        n = int(n)
        if n < 1:
            raise ValueError("n must be >= 1")
        # Model resolution BEFORE any shedding side effect (same rule as
        # the key below): an unknown tag must raise without having
        # killed a drop-oldest victim.
        model = DEFAULT_MODEL if model is None else str(model)
        pool_entry = None
        if model != DEFAULT_MODEL:
            if self.model_pool is None:
                raise ValueError(
                    f"submit(model={model!r}) needs an Engine constructed "
                    "with model_pool=ModelPool(...)"
                )
            if model not in self.model_pool:
                raise ValueError(
                    f"model {model!r} is not registered on this engine's "
                    f"pool; known tags: {self.model_pool.tags()}"
                )
            pool_entry = self.model_pool._entries[model]
        model_version = (
            pool_entry.model_version if pool_entry is not None
            else self.model_version
        )
        # Normalize the key BEFORE any shedding side effect: a malformed
        # key must raise without having killed a drop-oldest victim.
        if key is None:
            key = self._next_rid
        if isinstance(key, int):
            key = jax.random.PRNGKey(key)
        key = np.asarray(key).astype(np.uint32).reshape(2)
        if self._health in (Health.DRAINING, Health.STOPPED):
            raise EngineDraining(
                f"engine is {self._health.value}; submit to another replica"
            )
        if pool_entry is not None:
            # Demand noted now (LRU clock + the materialize queue when
            # cold): the weights can be loading while the request waits
            # its turn in the queue.
            self.model_pool._touch(model)
            if not pool_entry.ready:
                self._materialize_wanted[model] = None
        # Prefill cost in chunks: the TTFT estimate drains the queue at
        # max_prefills_per_tick CHUNKS per tick, so a long prompt must
        # weigh as many chunks, not 1.  A prefix-cache hit shrinks the
        # suffix (probe only — no refcounts taken; the authoritative
        # match happens at admission).
        suffix = len(prompt)
        hashes = None
        if self.prefix is not None:
            # Hashed ONCE per request: admission reuses these (the hash
            # is a pure function of the prompt — and of the MODEL: pool
            # models namespace the chain with their tag, so the same
            # prompt under two models can never share a page).
            hashes = page_hashes(
                prompt, self.block_size,
                pool_entry.namespace if pool_entry is not None else b"",
            )
            suffix = max(
                1, len(prompt) - self.prefix.probe(hashes) * self.block_size
            )
        n_chunks = -(-suffix // self.prefill_chunk)
        # The arrival's OWN prefill cost counts too: a 16k prompt on an
        # idle engine still waits n_chunks ticks for its first token.
        # The detector's estimate adds one chunk for the arrival, so
        # pass the remaining n_chunks - 1 alongside the queue's.
        if self.detector.overloaded(
            len(self.scheduler), self.max_prefills_per_tick,
            queued_chunks=self._pending_prefill_chunks() + n_chunks - 1,
        ):
            self._set_health(Health.OVERLOADED)
            if self.shed_policy == "reject-new":
                _T_SHED.add()
                self._n_shed += 1
                raise EngineOverloaded(
                    "engine overloaded "
                    f"(queue={len(self.scheduler)}, est_ttft="
                    f"{self.est_ttft_s():.3f}s);"
                    " retry with backoff"
                )
            if self.shed_policy == "by-priority":
                # Victim = lowest class, youngest first — and only a
                # STRICTLY lower class than the arrival's: an arrival
                # that is itself the lowest WAITING class is the one
                # shed.  An empty queue has no class to compare against
                # — the overload is all in-flight work — so the arrival
                # is admitted (same as drop-oldest with no victim) and
                # the admit phase's preemption resolves the pressure.
                victim = self.scheduler.shed_lowest(below_priority=priority)
                if victim is not None:
                    _T_SHED.add()
                    self._n_shed += 1
                    victim.handle._fail(
                        EngineOverloaded(
                            f"request {victim.rid} (priority="
                            f"{victim.priority}) shed under load "
                            "(by-priority)"
                        )
                    )
                elif len(self.scheduler):
                    raise EngineOverloaded(
                        "engine overloaded and the arriving request is "
                        f"the lowest waiting class (priority={priority});"
                        " retry with backoff"
                    )
            else:
                victim = self.scheduler.shed_oldest()
                if victim is not None:
                    _T_SHED.add()
                    self._n_shed += 1
                    victim.handle._fail(
                        EngineOverloaded(
                            f"request {victim.rid} shed under load "
                            "(drop-oldest)"
                        )
                    )

        deadline = (
            time.perf_counter() + deadline_s if deadline_s is not None else None
        )
        base_key = key
        handles: list[RequestHandle] = []
        reqs: list[Request] = []
        parent_rid = self._next_rid
        for i in range(n):
            rid = self._next_rid
            self._next_rid += 1
            # Sibling key schedule: fold_in(base, i) for EVERY group
            # member, so sibling i is token-identical to a solo submit
            # with key=fold_in(key, i) — and the digest is built from
            # the folded key, so an audit replay (resubmitted n=1 with
            # the recorded key) hashes to the same identity.  n == 1
            # keeps the caller's key untouched: solo submissions stay
            # bit-compatible with the pre-fork engine.
            k = (
                base_key if n == 1
                else np.asarray(
                    jax.random.fold_in(base_key, i)
                ).astype(np.uint32).reshape(2)
            )
            handle = RequestHandle(self, rid)
            tid = trace_id
            if tid is None:
                if _telemetry.events_enabled():
                    tid = f"{self.engine_id}-r{rid}"
            elif i > 0:
                # A caller-pinned id stays unique per sibling: the fork
                # index suffixes it, so the n timelines reconstruct
                # separately under one visible group prefix.
                tid = f"{trace_id}.f{i}"
            req = Request(
                rid, prompt, int(max_new_tokens), k, handle,
                deadline=deadline,
                # Siblings ride the parent's prompt pages: their true
                # marginal prefill is one last-token chunk — the WFQ
                # fare and the TTFT estimate must charge that, not the
                # full prompt.
                n_chunks=n_chunks if i == 0 else 1,
                hashes=hashes,
                tenant=tenant, priority=priority,
                trace_id=tid, hop=int(hop),
                digest=_audit.DeterminismDigest(prompt, k),
                audit_of=_audit_of,
                model_tag=model, model_version=model_version,
                fork_of=None if i == 0 else parent_rid,
                fork_index=i,
            )
            handle._req = req
            # Traced requests carry their replay identity (prompt ids +
            # normalized key) on req.submitted so a flight dump is a
            # runnable repro (scripts/incident_replay.py); built ONLY
            # when tracing — the disabled path allocates no lists.
            extra = {}
            if tid is not None:
                extra["prompt"] = [int(t) for t in prompt]
                extra["key"] = [int(kk) for kk in k]
                if _audit_of is not None:
                    extra["audit_of"] = _audit_of
            if model != DEFAULT_MODEL:
                extra["model"] = model
            if n > 1:
                extra["n"] = n
                extra["fork_index"] = i
            self._event(
                "req.submitted", req,
                n_prompt=len(prompt), max_new=int(max_new_tokens),
                tenant=tenant, priority=priority,
                deadline_s=deadline_s, n_chunks=req.n_chunks, **extra,
            )
            handles.append(handle)
            reqs.append(req)
        if n > 1:
            self._n_forks += n - 1
            _T_FORKS.add(n - 1)
            self._fork_groups[parent_rid] = reqs[1:]
            siblings = list(handles)
            for h in handles:
                h.siblings = siblings
        for req in reqs:
            if self._journal is not None and req.audit_of is None:
                # Durability: the replay identity lands in the journal
                # the moment the request is accepted (audit replays are
                # shadow traffic — resuming one cold would re-audit a
                # stream that no longer exists).
                self._journal_admit(req)
            self.scheduler.push(req)
            self._event("req.queued", req, queue_depth=len(self.scheduler))
            _T_REQUESTS.add()
            if pool_entry is not None:
                self.model_pool._note_request(model)
        return handles[0]

    def drain(self) -> None:
        """Step until every submitted request has finished — shadow
        audits included: a drain leaves no sampled-but-unchecked
        streams behind."""
        while (
            len(self.scheduler) or self._n_running() or self.audit_backlog()
        ):
            self.step()

    def audit_backlog(self) -> int:
        """Shadow audits sampled but not yet submitted (0 with auditing
        off).  In-flight audits occupy the ordinary queue/slots and are
        visible there; drive loops that wait on ``scheduler``/running
        should also wait on this."""
        return 0 if self._auditor is None else self._auditor.backlog()

    def health(self) -> Health:
        """Current :class:`.lifecycle.Health` state."""
        return self._health

    def est_ttft_s(self) -> float:
        """Estimated wait-for-prefill of a request arriving now.

        Router hook (:mod:`torchdistx_tpu.fleet`): the PER-ENGINE value
        behind the process-global ``serve.est_ttft_s`` gauge — a fleet
        of replicas in one process shares that gauge, so anything
        load-balancing across engines must read this instead."""
        return self.detector.est_ttft_s(
            self._pending_prefill_chunks(), self.max_prefills_per_tick
        )

    def _pending_prefill_chunks(self) -> int:
        """Prefill work ahead of a new arrival, in chunks: the waiting
        queue's estimates plus the un-prefilled remainder of every slot
        mid-prefill."""
        pending = self.scheduler.pending_prefill_chunks()
        for slot in self._prefill_q:
            req = self._slot_req[slot]
            if req is not None:
                left = max(1, req.replay_len() - req.prefill_pos)
                pending += -(-left // self.prefill_chunk)
        return pending

    # ------------------------------------------------------------------
    # Model plane (docs/serving.md, "Model plane")

    def _model_ready(self, req: Request) -> bool:
        """Admission gate: can ``req``'s model serve RIGHT NOW?  A cold
        pool model holds the queue head WITHOUT popping it — and notes
        the demand, so the materialize phase loads the weights
        out-of-band and the head admits on a later tick."""
        if req.model_tag == DEFAULT_MODEL:
            return True
        entry = self.model_pool._entries.get(req.model_tag)
        if entry is None or entry.ready:
            return True
        self._materialize_wanted.setdefault(req.model_tag, None)
        self.model_pool._note_stall(req.model_tag)
        return False

    def _page_need(self, req: Request) -> int:
        """Admission page reservation for ``req`` — a fork sibling with
        a live donor charges only its MARGINAL pages (the generation
        tail); everything else charges the full quota."""
        n_total = blocks_needed(req.cache_tokens, self.block_size)
        if req.fork_of is not None and not req.handle._tokens:
            donor = self._fork_donors.get(req.fork_of)
            if donor is not None:
                return max(0, n_total - len(donor))
        return n_total

    def _model_ctx(self, tag: str) -> tuple:
        """The ``(model, cfg, params)`` triple a dispatch for ``tag``
        runs under.  Pool models must be resident: admission gates on
        residency and eviction refuses models with live slots, so a
        miss here means external interference — fail loudly."""
        if tag == DEFAULT_MODEL:
            return self.model, self.cfg, self._params
        entry = self.model_pool._entries[tag]
        if entry.params is None:
            raise RuntimeError(
                f"model {tag!r} lost its weights with live work on the "
                "engine (evicted externally mid-flight?)"
            )
        return entry.model, entry.cfg, entry.params

    def _model_in_use(self, tag: str) -> bool:
        """True while any SLOT — running, prefilling, or swapped out —
        serves ``tag``: the model-pool eviction pin.  Queued requests
        don't pin: admission re-demands materialization."""
        return any(
            req is not None and req.model_tag == tag
            for req in self._slot_req
        )

    def _materialize_phase(self) -> None:
        """Materialize the oldest demanded cold model — ONE per tick.
        A transient failure (``serve.materialize`` ``io``/``nan``, a
        flaky checkpoint read) leaves the skeleton untouched and the
        demand queued: the next tick retries.  Anything else propagates
        out of ``step()`` — a factory that cannot produce weights is an
        operator problem, not a retry loop."""
        tag = next(iter(self._materialize_wanted))
        try:
            self.model_pool.ensure(tag)
        except (KeyboardInterrupt, SystemExit):
            raise
        except faults.FatalInjectedFault:
            raise
        except OSError:
            self.model_pool.materialize_retries += 1
            return
        self._materialize_wanted.pop(tag, None)

    def _sweep_fork_donors(self) -> None:
        """Release a fork group's donor pages once every sibling is
        terminal — nothing will ever map them again.  The parent does
        not pin its own donor (it holds its own references)."""
        if not self._fork_groups:
            return
        for gid in list(self._fork_groups):
            if all(
                req.handle._done for req in self._fork_groups[gid]
            ):
                donor = self._fork_donors.pop(gid, None)
                if donor:
                    self.allocator.free(donor)
                del self._fork_groups[gid]

    def begin_drain(self) -> None:
        """Start a graceful drain NOW, without a preemption signal.

        Router/lifecycle hook: the same path a SIGTERM takes — admission
        closes, the waiting queue fails with retryable typed errors, and
        subsequent :meth:`step` calls finish in-flight work under
        ``drain_deadline_s`` before the engine lands STOPPED.  No-op on
        an engine already DRAINING or STOPPED."""
        if self._health not in (Health.DRAINING, Health.STOPPED):
            self._begin_drain()

    def _set_health(self, health: Health) -> None:
        if health is not self._health:
            self._health = health
            _G_HEALTH.set(health.value)
            # The labeled gauge keeps its final reading at STOPPED — per-
            # engine scoping needs no clear-on-STOPPED workaround.
            self._lg_health.set(health.value)

    def _n_running(self) -> int:
        return sum(r is not None for r in self._slot_req)

    def _n_decoding(self) -> int:
        """Slots in the decode batch (occupied, past their prefill,
        and not swapped out to host)."""
        return sum(
            r is not None for i, r in enumerate(self._slot_req)
            if i not in self._prefill_q and i not in self._swapped
        )

    # ------------------------------------------------------------------
    # The engine tick

    def step(self) -> None:
        """One tick: act on preemption, reap expired/cancelled requests,
        admit, advance prefills (up to ``max_prefills_per_tick`` chunks),
        then one decode chunk over the running slots."""
        if self._health is Health.STOPPED:
            # Raising (rather than a silent no-op) keeps a stray
            # handle.tokens() loop from spinning a dead engine forever.
            raise EngineDraining("engine is stopped")
        t0 = time.perf_counter()
        # Ops-plane gate, read once per tick: one attribute read + one
        # module-global read — the whole cost of the disabled path.
        ops_on = self._ops_plane is not None or _ops._TICK_ATTRIBUTION
        # Time-plane phase timer, same gate: a handful of perf_counter
        # marks per tick when on, nothing at all when off.
        timer = self._tick_timer = (
            _timeplane.TickTimer(t0) if ops_on else None
        )
        churn0 = (
            self._n_preempt_swap + self._n_preempt_replay
            + self._n_recoveries
        ) if ops_on else 0
        if timer is not None:
            timer.begin("schedule")
        if self._health is not Health.DRAINING and _preemption.requested():
            self._begin_drain()
        self._preempted_this_tick = False
        self._reap_phase()
        if self._auditor is not None:
            # Shadow audits ride the ordinary admission path, one per
            # tick at most, and only when no user work waits (the pump
            # checks) — before _admit_phase so a submitted audit admits
            # this same tick on an otherwise idle engine.
            if timer is not None:
                timer.begin("audit_pump")
            self._auditor.pump()
            if timer is not None:
                timer.begin("schedule")
        if self._health is not Health.DRAINING:
            self._admit_phase()
        # Swapped slots resume even while DRAINING — they are in-flight
        # work the drain contract promises to finish — but never on a
        # tick that just preempted (the pressure that forced the swap
        # out is by definition still there).
        if self._swapped:
            self._swap_in_phase()
        # Chunks advance even while DRAINING: a slot mid-prefill is
        # in-flight work the drain contract promises to finish.
        if timer is not None:
            timer.begin("prefill_dispatch")
        chunks = self._advance_prefills()
        committed = self._decode_phase()
        if timer is not None:
            timer.begin("schedule")
        if self._fork_groups:
            # A fork group whose last sibling retired in THIS tick's
            # decode frees its donor pages now, not next tick — a
            # drive-until-idle loop (drain()) must settle to zero pages
            # the tick the work completes.
            self._sweep_fork_donors()
        if self._materialize_wanted and self._health is not Health.DRAINING:
            # Model plane: serve ONE cold model's weight demand, strictly
            # AFTER this tick's decode dispatch — the materialize stall
            # lands between ticks, so a cold model's arrival never
            # freezes a hot model's token cadence mid-tick.
            self._materialize_phase()
        if self._health is Health.DRAINING:
            self._drain_tick()
        elif self._health is Health.STARTING:
            self._set_health(Health.READY)
        elif (
            self._health is Health.OVERLOADED
            # The divergence latch does NOT self-clear: a determinism
            # break is not pressure that drains away (clear_divergence).
            and not self._diverging
            and not self.detector.overloaded(
                len(self.scheduler), self.max_prefills_per_tick,
                queued_chunks=self._pending_prefill_chunks(),
            )
        ):
            self._set_health(Health.READY)
        if self._journal is not None:
            # Group commit (fsync='tick'): ONE durability point covers
            # every record this tick appended — admissions, chunk
            # commits, retirements.  Never raises; a failing disk
            # degrades the journal to async instead of blocking here.
            self._journal.sync()
        tick_s = time.perf_counter() - t0
        self.detector.observe_tick(tick_s)
        self._tick_no += 1
        # A fully idle tick (nothing ran, nothing waiting) publishes NO
        # attribution: idle readings would dilute occupancy/goodput
        # stats into meaninglessness on a lightly loaded engine.  It
        # still counts — an operator can tell idle from wedged — and
        # the FIRST idle tick zeroes the per-tick rate gauges once, so
        # a dashboard never reads the last busy tick's goodput off an
        # engine that has gone quiet.
        idle = (
            committed == 0 and chunks == 0 and not self._swapped
            and not len(self.scheduler) and self._n_running() == 0
            and self._health is not Health.STOPPED  # drain-completing tick
        )
        if idle:
            _T_IDLE_TICKS.add()
            if ops_on and not self._was_idle and self._g_occupancy is not None:
                self._g_occupancy.set(0)
                self._g_prefill_budget.set(0)
                self._g_churn.set(0)
                self._g_goodput.set(0)
        elif ops_on:
            self._tick_telemetry(tick_s, chunks, committed, churn0)
        if timer is not None:
            timer.end()
            self._tick_timer = None
            # A drain-completing tick must not re-mint the rows
            # _finish_drain just pruned — a stopped engine leaves no
            # time-plane readings behind (same rule as the routing
            # gauges below).
            if self._health is not Health.STOPPED:
                _timeplane.publish_tick(self, timer, tick_s, idle=idle)
        self._was_idle = idle
        # A tick that completed the drain must not re-write the routing
        # gauges _finish_drain just cleared — a stopped engine leaves no
        # stale readings behind.  A live engine re-asserts BOTH every
        # tick (not just on transitions): in a fleet, a peer reaching
        # STOPPED clears the process-global gauges, and the next live
        # replica's tick is what restores them.
        if self._health is not Health.STOPPED:
            _G_HEALTH.set(self._health.value)
            if self.detector.enabled:
                est = round(self.est_ttft_s(), 4)
                _G_EST_TTFT.set(est)
                self._lg_est_ttft.set(est)
        n_run = self._n_running()
        _G_RUNNING.set(n_run)
        self._lg_running.set(n_run)

    # ------------------------------------------------------------------
    # Ops plane: per-tick attribution + the watchdog hook

    def _tick_telemetry(
        self, tick_s: float, chunks: int, committed: int, churn0: int
    ) -> None:
        """Per-tick utilization attribution (docs/observability.md,
        "Ops plane") — called only with the ops plane attached or
        attribution forced on.  One reading per signal per tick, all
        ``{engine=...}``-labeled:

        * ``serve.occupancy`` — decode-batch slots in use / total: how
          full the one compiled decode chunk ran (queue-bound TTFT shows
          occupancy near 1; an idle engine shows 0).
        * ``serve.prefill_budget`` — prefill chunks dispatched / the
          per-tick budget: prefill-bound ticks pin this at 1.
        * ``serve.page_util`` — physical page-pool utilization:
          page-bound admission shows this saturated with occupancy low.
        * ``serve.churn`` — preemption/swap/recovery events this tick:
          preemption-bound service shows churn with occupancy high.
        * ``serve.goodput`` — committed decode tokens per tick-second
          (the serving analogue of train-side MFU); > 0 whenever the
          tick decoded, 0 on pure-prefill or idle ticks.
        * ``serve.tick_s`` — the tick-duration histogram behind the
          goodput denominator.
        * ``mem.pool_fragmentation`` — free-map scatter of the page
          pool (the HBM ledger's fragmentation estimate), plus a ledger
          sync of the live ``kv_swap_host`` / ``prefix_cache_held``
          accounts.
        """
        if self._g_occupancy is None:
            eid = self.engine_id
            self._g_occupancy = _telemetry.gauge("serve.occupancy", engine=eid)
            self._g_prefill_budget = _telemetry.gauge(
                "serve.prefill_budget", engine=eid
            )
            self._g_page_util = _telemetry.gauge("serve.page_util", engine=eid)
            self._g_churn = _telemetry.gauge("serve.churn", engine=eid)
            self._g_goodput = _telemetry.gauge("serve.goodput", engine=eid)
            self._h_tick = _telemetry.histogram("serve.tick_s", engine=eid)
            self._g_frag = _telemetry.gauge(
                "mem.pool_fragmentation", engine=eid
            )
        if self._tick_no % 16 == 1:  # tick_no pre-incremented: first tick writes
            # The free-map scan is O(free pages log free pages): a
            # sampled gauge (every 16th tick) keeps the instrumentation
            # from taxing the tick latency it exists to explain.
            self._g_frag.set(round(self.allocator.fragmentation(), 4))
        self._ledger_sync()
        self._g_occupancy.set(round(self._n_decoding() / self.num_slots, 4))
        self._g_prefill_budget.set(
            round(chunks / self.max_prefills_per_tick, 4)
        )
        self._g_page_util.set(round(self.allocator.utilization(), 4))
        self._g_churn.set(
            self._n_preempt_swap + self._n_preempt_replay
            + self._n_recoveries - churn0
        )
        self._g_goodput.set(
            round(committed / tick_s, 1) if tick_s > 0 and committed else 0
        )
        self._h_tick.observe(tick_s)

    def _mark_stalled(self) -> None:
        """Stall-watchdog hook (:class:`torchdistx_tpu.telemetry.ops
        .StallWatchdog`, possibly another thread): a wedged engine
        reads OVERLOADED so a fleet router routes around it.  Its own
        next real tick — proof the wedge cleared — restores READY via
        the normal overload re-check."""
        if self._health in (Health.STARTING, Health.READY):
            self._set_health(Health.OVERLOADED)

    def _mark_diverging(self) -> None:
        """Divergence hook (:mod:`torchdistx_tpu.telemetry.audit`): a
        shadow-audit digest mismatch or a failed resume verification
        LATCHES this engine — ``serve.diverging{engine=...}`` set, and
        the engine reads OVERLOADED so a fleet router routes around it
        the same way it routes around stalls and recompile storms.
        Unlike those, the latch never self-clears: ticks keep serving
        in-flight work, but only :meth:`clear_divergence` (an operator
        action, after incident replay) restores routability."""
        self._diverging = True
        _telemetry.gauge("serve.diverging", engine=self.engine_id).set(1)
        if self._health in (Health.STARTING, Health.READY):
            self._set_health(Health.OVERLOADED)

    def clear_divergence(self) -> None:
        """Operator acknowledgement: drop the divergence latch (the
        gauge reads 0 until the engine stops); the next tick's overload
        re-check restores READY when no real pressure remains."""
        self._diverging = False
        _telemetry.gauge("serve.diverging", engine=self.engine_id).set(0)

    # ------------------------------------------------------------------
    # Perf plane: HBM ledger sync + OOM forensics

    def _ledger_sync(self) -> None:
        """Refresh this engine's live ledger accounts: host-resident
        swap staging and the pages the prefix index holds (the latter a
        view INSIDE ``kv_pool`` — attribution, not additional HBM).
        Called per tick with the ops plane on, and before every OOM
        dump so the forensic snapshot is current."""
        _perf.ledger.register(
            "kv_swap_host", self._swap_host_bytes, owner=self.engine_id
        )
        if self.prefix is not None:
            _perf.ledger.register(
                "prefix_cache_held",
                len(self.prefix) * self._page_nbytes,
                owner=self.engine_id,
            )

    def _oom_check(self, err: BaseException, site: str) -> None:
        """RESOURCE_EXHAUSTED forensics: when a failed device call is a
        device OOM, snapshot the HBM ledger into the flight record —
        the post-mortem then reads *what held the memory* (weights vs
        pool vs swap vs cached prefixes), not just that it ran out."""
        if _perf.is_oom(err):
            self._ledger_sync()
            _perf.oom_dump(
                "device_oom", engine=self.engine_id, site=site,
                error=f"{type(err).__name__}: {err}",
                pool_fragmentation=round(self.allocator.fragmentation(), 4),
            )

    def _pool_exhausted(self, site: str, need: int) -> None:
        """Page-pool exhaustion forensics: a reservation the admission
        quota promised could not be met (allocator map changed under
        the tick, CoW under chronic pressure).  Same ledger-carrying
        flight dump as a device OOM, under ``reason="pool_exhausted"``."""
        self._ledger_sync()
        _perf.oom_dump(
            "pool_exhausted", engine=self.engine_id, site=site,
            pages_needed=need, pages_free=self.allocator.num_free,
            pages_in_use=self.allocator.num_in_use,
            pool_fragmentation=round(self.allocator.fragmentation(), 4),
        )

    # ------------------------------------------------------------------
    # Lifecycle: reap, drain

    def _reap_phase(self) -> None:
        """Chunk-boundary lifecycle sweep: deadline expiries and client
        cancellations, waiting and running both.  Pages release here —
        'the next chunk boundary' of the documented contract."""
        now = time.perf_counter()
        expired, cancelled = self.scheduler.purge(now)
        for req in expired:
            self._n_expired += 1
            _T_EXPIRED.add()
            req.handle._fail(
                DeadlineExceeded(
                    f"request {req.rid} expired in queue before prefill"
                )
            )
        for req in cancelled:
            self._n_cancelled += 1
            _T_CANCELLED.add()
            req.handle._fail(
                RequestCancelled(f"request {req.rid} cancelled while queued")
            )
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            if req.handle._cancel_requested:
                self._n_cancelled += 1
                _T_CANCELLED.add()
                self._fail_running_slot(
                    slot, RequestCancelled(f"request {req.rid} cancelled")
                )
            elif req.expired(now):
                self._n_expired += 1
                _T_EXPIRED.add()
                self._fail_running_slot(
                    slot,
                    DeadlineExceeded(
                        f"request {req.rid} exceeded its deadline after "
                        f"{self._emitted[slot]} tokens"
                    ),
                )
        self._sweep_fork_donors()

    def _fail_running_slot(self, slot: int, error) -> None:
        """Abort a running slot: pages back, handle failed typed, slot
        cleared.  The ONE place the release-on-failure choreography
        lives (reap, drain deadline, and close all route here).  A
        swapped slot owns no pages — its host buffer is discarded and
        the allocator's swap account settled instead."""
        req = self._slot_req[slot]
        if slot in self._swapped:
            self._discard_swapped(slot)
        elif req.blocks:
            self.allocator.free(req.blocks)
        req.blocks = None
        req.handle._fail(error)
        self._clear_slot(slot)

    def _begin_drain(self) -> None:
        """Preemption observed: close admission, flush the queue with a
        retryable error, and give in-flight work ``drain_deadline_s``."""
        self._set_health(Health.DRAINING)
        self._drain_t0 = time.perf_counter()
        self._drain_sp = _telemetry.start_span(
            "serve.drain",
            detached=True,
            n_running=self._n_running(),
            n_waiting=len(self.scheduler),
        )
        # The flag is acted on (the convention fit() set): a later
        # engine/run in this process starts clean; a platform that is
        # really going down keeps signalling.
        _preemption.clear()
        # Pending weight demand dies with the queue it served: the
        # requests that wanted those models are flushed below.
        self._materialize_wanted.clear()
        for req in self.scheduler.flush():
            self._n_preempted += 1
            _T_PREEMPTED.add()
            req.handle._fail(
                RequestPreempted(
                    f"request {req.rid} flushed before prefill: engine "
                    "draining; retry against another replica",
                    resumable=True,  # zero tokens yielded: resubmit = resume
                )
            )

    def _drain_tick(self) -> None:
        if self._n_running() == 0:
            self._finish_drain(timed_out=False)
            return
        if time.perf_counter() - self._drain_t0 > self.drain_deadline_s:
            for slot, req in enumerate(self._slot_req):
                if req is None:
                    continue
                self._n_preempted += 1
                _T_PREEMPTED.add()
                self._fail_running_slot(
                    slot,
                    RequestPreempted(
                        f"request {req.rid} preempted mid-stream: drain "
                        f"deadline ({self.drain_deadline_s}s) expired after "
                        f"{self._emitted[slot]} tokens; retry against "
                        "another replica",
                        resumable=self._emitted[slot] == 0,
                    ),
                )
            self._finish_drain(timed_out=True)

    def _finish_drain(self, *, timed_out: bool) -> None:
        if self._drain_sp is not None:
            self._drain_sp.end(timed_out=timed_out)
            self._drain_sp = None
        # Fork donors die with the engine, same rule as cached prefixes:
        # drop the engine-held share references so nothing stays mapped.
        for donor in self._fork_donors.values():
            self.allocator.free(donor)
        self._fork_donors.clear()
        self._fork_groups.clear()
        if self.prefix is not None:
            # Cached prefixes die with the engine: drop the index's page
            # references so a stopped engine owns nothing.
            self.prefix.release(self.allocator)
        self._set_health(Health.STOPPED)
        # The serving gauges are process-global: a stopped engine must
        # not leave its last readings behind for a router (or an
        # operator tailing the trace) to load-balance on — clear them;
        # the next live replica's tick re-sets both.
        _G_HEALTH.set(None)
        _G_EST_TTFT.set(None)
        if self._handle_preemption and not self._handlers_preexisting:
            _preemption.uninstall()
        # Ops-plane teardown (docs/observability.md, "Ops plane"): a
        # STOPPED engine leaves the plane — its watchdog stops and its
        # /healthz entry goes with it; when it was the plane's last
        # engine (and no router retains it), the HTTP listener shuts
        # down too: no dangling threads, and the port refuses — the
        # strongest non-200 /healthz a scraper can observe.
        if self._ops_plane is not None:
            self._ops_plane.unwatch(self)
            self._ops_plane = None
        # The divergence latch gauge is a dynamic label family: prune it
        # with the engine (the flag itself survives for introspection).
        _telemetry.remove("serve.diverging", engine=self.engine_id)
        # Same rule for the scheduler's per-engine queue-depth family:
        # replica churn must not grow /metrics by one series per engine
        # ever seen.
        _telemetry.remove("serve.queue_depth", engine=self.engine_id)
        # And for the disaggregation-role family: the role is a routing
        # hint, and a stopped engine routes nothing.
        _telemetry.remove("serve.role", engine=self.engine_id)
        # Time-plane teardown: the tick-phase histogram family and the
        # host-overhead gauge leave the registry with the engine — no
        # serve.tick_phase_s row survives a drain (bounded cardinality
        # under replica churn, same rule as serve.stalled).
        self._tp_state = None
        _timeplane.prune_engine(self.engine_id)
        # HBM ledger teardown: a stopped engine's pool/swap/prefix
        # accounts leave the ledger; weights leave when the LAST engine
        # sharing the params pytree stops (peers may still serve it).
        _perf.ledger.unregister("kv_pool", owner=self.engine_id)
        _perf.ledger.unregister("kv_swap_host", owner=self.engine_id)
        _perf.ledger.unregister("prefix_cache_held", owner=self.engine_id)
        with _WEIGHTS_LOCK:
            left = _WEIGHTS_REFS.get(self._weights_key, 1) - 1
            if left <= 0:
                _WEIGHTS_REFS.pop(self._weights_key, None)
            else:
                _WEIGHTS_REFS[self._weights_key] = left
        if left <= 0:
            _perf.ledger.unregister("weights", owner=self._weights_key)
        self._weights_anchor = None  # release the id pin with the entry
        # Durability-plane teardown: the close above already journaled
        # every in-flight stream's typed retirement (the handle funnel),
        # so the sealed journal records a fully-retired run — close
        # flushes, fsyncs, and releases the ownership lock.
        if self._journal is not None:
            self._journal.close()
        # Model-plane teardown: pool models' weights, ledger rows, and
        # per-engine labeled families all leave with the engine.
        if self.model_pool is not None:
            self.model_pool._close()

    def close(self) -> None:
        """Stop the engine NOW: fail queued and in-flight work with
        retryable typed errors, release every page, and restore the
        signal handlers this engine installed.  Idempotent.

        The graceful path is a drain (SIGTERM / ``preemption.request()``
        + stepping); ``close()`` is for retiring an engine without one —
        otherwise the handlers it installed at construction would
        outlive it and swallow the process's next Ctrl-C."""
        if self._health is Health.STOPPED:
            return
        for req in self.scheduler.flush():
            self._n_preempted += 1
            _T_PREEMPTED.add()
            req.handle._fail(
                EngineDraining(
                    f"request {req.rid} rejected: engine closed before "
                    "prefill; retry against another replica"
                )
            )
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            self._n_preempted += 1
            _T_PREEMPTED.add()
            self._fail_running_slot(
                slot,
                RequestPreempted(
                    f"request {req.rid} aborted after "
                    f"{self._emitted[slot]} tokens: engine closed; retry "
                    "against another replica",
                    resumable=self._emitted[slot] == 0,
                ),
            )
        self._finish_drain(timed_out=False)

    # ------------------------------------------------------------------
    # Admission

    def _admit_phase(self) -> None:
        if not len(self.scheduler):
            return
        if self._prefill_q:
            if self._qos:
                # A strictly-higher-class head must not wait out a
                # lower class's chunked prefill (priority inversion
                # through the prefill queue): abort-and-requeue those
                # prefills — they have no committed tokens, so the
                # requeue is the cheap end of drop-and-replay.
                self._preempt_prefills()
            if self._prefill_q:
                # Prefill-busy: popping more requests would only park
                # them on pages with zero progress (chunks drain
                # strictly FIFO).  Admission resumes the tick the queue
                # of chunks empties.
                return
        if self._qos:
            # Before admission reads the free lists: a waiting request
            # of a strictly higher class may preempt running lower ones
            # to make room — same tick, so a high-priority arrival never
            # waits out a low-priority stream's whole budget.
            self._qos_preempt()
        free_slots = [
            i for i, r in enumerate(self._slot_req) if r is None
        ]
        if not free_slots:
            # Slot-bound stall with work waiting: the scheduler owns the
            # backpressure rule, so route through it (its limit==0 path
            # counts the stall exactly like a page-bound one — an
            # invisible stall reads as a healthy idle engine).
            self.scheduler.pop_admissible(0, self.allocator, self.block_size)
            return
        self._admit_no += 1
        try:
            kind = faults.fire("serve.admit", self._admit_no)
        except OSError:
            # Transient admit failure: nothing was popped or allocated —
            # the very next tick retries the same FIFO head.
            _T_ADMIT_RETRIES.add()
            return
        if kind is not None:
            # Cooperation kinds (nan) at this site mean "this admission
            # tick is poisoned": skip it — a consumed spec that silently
            # did nothing would defeat the registry's whole point.
            _T_ADMIT_RETRIES.add()
            return
        batch = self.scheduler.pop_admissible(
            len(free_slots), self.allocator, self.block_size,
            reclaim=self._reclaim_pages,
            need=self._page_need, ready=self._model_ready,
        )
        for i, req in enumerate(batch):
            try:
                self._start_prefill(free_slots[i], req)
            except (KeyboardInterrupt, SystemExit):
                self.scheduler.requeue([req] + batch[i + 1:])
                raise
            except Exception:
                # Host-side reservation failure (nothing dispatched, the
                # reservation rolled back): the request — and the rest
                # of the batch, which must not jump it — returns to the
                # FIFO head.
                _T_PREFILL_RETRIES.add()
                self.scheduler.requeue([req] + batch[i + 1:])
                return

    # ------------------------------------------------------------------
    # QoS preemption: swap-to-host / drop-and-replay (scheduler="qos")

    def _preempt_prefills(self) -> None:
        """Abort mid-prefill slots when the waiting head outranks every
        one of them: their pages return, they re-enter the QoS queues
        (losing only the chunks already dispatched), and this tick's
        prefill budget goes to the higher class instead.  Nothing
        happens while any prefilling slot is the head's class or above
        — chunk progress is never sacrificed to an equal."""
        head = self.scheduler.peek()
        if head is None or not self._model_ready(head):
            # A cold-model head cannot admit this tick: aborting chunk
            # progress for it would be pure waste (the materialize phase
            # was just notified; next tick it outranks for real).
            return
        if not all(
            self._slot_req[slot].priority < head.priority
            for slot in self._prefill_q
        ):
            return
        for slot in list(self._prefill_q):
            req = self._abort_prefill(slot)
            req.n_chunks = self._replay_chunks(req)
            req.preempt_t = time.perf_counter()
            self._event(
                "req.preempted", req, mechanism="replay",
                reason="prefill_requeue", n_tokens=0,
            )
            self.scheduler.push(req)
            self._n_preempt_replay += 1
            _T_PREEMPT_REPLAY.add()
        self._preempted_this_tick = True

    def _replay_chunks(self, req: Request) -> int:
        """A preemption victim's resume cost in chunks — what the
        re-prefill of prompt + generated-so-far will really dispatch,
        minus whatever prefix the index still holds (re-admission maps
        it again), mirroring submit's cache-aware estimate.  The WFQ
        fare and the TTFT estimate both read it."""
        seq_len = req.replay_len()
        cached = 0
        if self.prefix is not None and req.hashes:
            cached = self.prefix.probe(req.hashes) * self.block_size
        return -(-max(1, seq_len - cached) // self.prefill_chunk)

    def _qos_preempt(self) -> None:
        """Make room for a waiting higher-class request by preempting
        running strictly-lower-class streams.  Victim order: lowest
        class first, youngest first (least work lost).  Two pressures,
        two mechanisms:

        * **slot pressure** (every slot occupied) → **drop-and-replay**
          on one victim: its pages release and it requeues with its
          generated-so-far tokens; re-admission re-prefills
          ``prompt + tokens`` via the supervisor's replay sequence —
          ``fold_in(key, n_gen)`` keeps the resumed stream
          token-identical;
        * **page pressure** (the head's reservation exceeds the free
          list) → ``preempt_mechanism`` per victim: ``"swap"`` copies
          the victim's private pages to a host buffer and frees them,
          keeping shared ones mapped (the slot stays parked, out of
          the decode batch like a PREFILLING slot, until
          :meth:`_swap_in_phase` brings it back); ``"replay"``
          drops and requeues as above.  A ``serve.swap`` ``io`` fault
          falls back to drop-and-replay — the gather is read-only, so
          the failed swap leaves device state untouched.
        """
        head = self.scheduler.peek()
        if head is None or not self._model_ready(head):
            # Same cold-model rule as _preempt_prefills: never preempt
            # running streams for a head that cannot admit this tick.
            return
        victims = sorted(
            (
                slot
                for slot, req in enumerate(self._slot_req)
                if req is not None and req.priority < head.priority
            ),
            key=lambda s: (
                self._slot_req[s].priority, -self._slot_req[s].rid,
            ),
        )
        if not victims:
            return
        if all(r is not None for r in self._slot_req):
            # Slot pressure: only replay frees a slot (a swapped slot
            # stays parked in its slot).  Swapped victims qualify too —
            # their host buffer is discarded and they requeue.
            self._preempt_slot(victims.pop(0), mechanism="replay")
        need = blocks_needed(head.cache_tokens, self.block_size)
        if need > self.allocator.num_free:
            self._reclaim_pages(need - self.allocator.num_free)
        while need > self.allocator.num_free and victims:
            slot = victims.pop(0)
            if slot in self._swapped:
                # Its private pages are already on host and its kept
                # shared pages stay resident on the index's/peers'
                # references either way: nothing to free here.
                continue
            self._preempt_slot(slot, mechanism=self.preempt_mechanism)

    def _preempt_slot(self, slot: int, mechanism: str) -> None:
        """Preempt one running slot.  ``"swap"``: pages gather to host,
        slot parks (decode batch exit = the PREFILLING rule: device
        table 0 → trash, done=True).  ``"replay"``: pages release and
        the request re-enters the QoS queues carrying its committed
        tokens; a swapped victim's host buffer is discarded the same
        way."""
        req = self._slot_req[slot]
        self._preempted_this_tick = True
        if mechanism == "swap" and slot not in self._swapped:
            # Only PRIVATE pages (refcount 1) go to host: a shared page
            # (prefix index or a CoW peer holds it too) stays resident
            # whether we drop our ref or not, so transferring it would
            # free nothing now and duplicate it at swap-in.  The
            # request KEEPS its references on shared pages — sharing is
            # preserved across the preemption and others' writes still
            # see refcount > 1 and copy-on-write first.
            layout = [
                blk if self.allocator.refcount(blk) > 1 else None
                for blk in req.blocks
            ]
            priv = [
                blk for blk, kept in zip(req.blocks, layout)
                if kept is None
            ]
            self._swap_no += 1
            try:
                kind = faults.fire("serve.swap", self._swap_no)
                if kind is not None:
                    # Cooperation kinds (nan) poison this swap attempt:
                    # same contract as io — fall back to replay.
                    raise faults.InjectedFault(
                        f"poisoned swap attempt ({kind})"
                    )
                host = swap_out_pages(self._cache, priv) if priv else None
            except (KeyboardInterrupt, SystemExit):
                raise
            except faults.FatalInjectedFault:
                raise
            except Exception as err:
                # The gather is read-only: device state is untouched,
                # so drop-and-replay below is safe and token-identical.
                self._oom_check(err, "serve.swap_out")
            else:
                self.allocator.swap_out(priv)
                self._swapped[slot] = (host, layout)
                if host is not None:
                    self._swap_host_bytes += _perf.pytree_nbytes(host)
                req.blocks = None
                self._tables[slot] = 0
                self._done[slot] = True
                self._n_preempt_swap += 1
                _T_PREEMPT_SWAP.add()
                req.preempt_t = time.perf_counter()
                self._event(
                    "req.swapped", req, n_private=len(priv),
                    n_shared=len(layout) - len(priv),
                    n_tokens=len(req.handle._tokens),
                )
                return
        # Drop-and-replay (the swap fallback lands here too).
        if slot in self._swapped:
            self._discard_swapped(slot)
        elif req.blocks:
            self.allocator.free(req.blocks)
        self._reset_prefill_state(req)
        req.n_chunks = self._replay_chunks(req)
        self._clear_slot(slot)
        req.preempt_t = time.perf_counter()
        self._event(
            "req.preempted", req, mechanism="replay", reason="pressure",
            n_tokens=len(req.handle._tokens),
        )
        self.scheduler.push(req)
        self._n_preempt_replay += 1
        _T_PREEMPT_REPLAY.add()

    def _discard_swapped(self, slot: int) -> None:
        """Settle a swapped slot's accounts without resuming it (the
        request was cancelled, failed, or re-preempted to replay): the
        kept shared pages' references release, the host buffer is
        dropped, and the allocator forgets the host-resident rows."""
        host, layout = self._swapped.pop(slot)
        if host is not None:
            self._swap_host_bytes -= _perf.pytree_nbytes(host)
        kept = [blk for blk in layout if blk is not None]
        if kept:
            self.allocator.free(kept)
        self.allocator.drop_swapped(
            sum(1 for blk in layout if blk is None)
        )

    def _swap_in_phase(self) -> None:
        """Bring swapped slots back when pressure subsides: highest
        class first, oldest first.  A swapped slot never jumps a
        waiting *higher*-class head — its pages stay reserved for it —
        and never resumes on the tick that just preempted."""
        if self._preempted_this_tick:
            return
        head = self.scheduler.peek() if self._qos else None
        for slot in sorted(
            self._swapped,
            key=lambda s: (
                -self._slot_req[s].priority, self._slot_req[s].rid,
            ),
        ):
            req = self._slot_req[slot]
            toks = req.handle._tokens
            if toks and not req.digest.matches_stream(
                req.prompt, req.key, toks, req.model_version
            ):
                # Digest verification before the pages come back: a
                # corrupted committed buffer fails typed here — the
                # KV about to be mapped in no longer matches it.
                self._resume_diverged(slot, req, "swap-resume")
                continue
            host, layout = self._swapped[slot]
            n_priv = sum(1 for kept in layout if kept is None)
            reserve = 0
            if head is not None and head.priority > req.priority:
                reserve = blocks_needed(head.cache_tokens, self.block_size)
            short = n_priv + reserve - self.allocator.num_free
            if short > 0:
                self._reclaim_pages(short)
            if self.allocator.num_free - reserve < n_priv:
                continue
            pages = self.allocator.swap_in(n_priv)
            if pages is None:
                continue
            if n_priv:
                try:
                    self._cache = swap_in_pages(self._cache, host, pages)
                except (KeyboardInterrupt, SystemExit):
                    raise
                except faults.FatalInjectedFault:
                    raise
                except Exception as err:
                    # The scatter held the pool donated: a failure here
                    # is a device failure — the supervisor rebuilds and
                    # replays everything (swapped slots included, as
                    # replays).  The just-granted pages die with the
                    # map.
                    self._oom_check(err, "serve.swap_in")
                    self._swapped.pop(slot, None)
                    if host is not None:
                        self._swap_host_bytes -= _perf.pytree_nbytes(host)
                    self._supervise_recovery(err)
                    return
            del self._swapped[slot]
            if host is not None:
                self._swap_host_bytes -= _perf.pytree_nbytes(host)
            fresh = iter(pages)
            blocks = [
                kept if kept is not None else next(fresh)
                for kept in layout
            ]
            req.blocks = blocks
            table = np.zeros((self._table_width,), np.int32)
            table[: len(blocks)] = blocks
            req.table = table
            self._tables[slot] = table
            self._done[slot] = False
            if req.preempt_t is not None:
                self._h_outage.observe(time.perf_counter() - req.preempt_t)
                req.preempt_t = None
            self._event(
                "req.resumed", req, mechanism="swap",
                n_tokens=len(req.handle._tokens),
            )

    # ------------------------------------------------------------------
    # Cross-engine stream migration (docs/fleet.md, "Disaggregation &
    # stream migration")

    def migratable_slots(self) -> list:
        """Slots whose stream can :meth:`migrate_out` right now:
        occupied, past prefill (committed tokens exist), resident on
        device (not swapped to host), and not already terminal."""
        return [
            slot
            for slot, req in enumerate(self._slot_req)
            if req is not None
            and slot not in self._prefill_q
            and slot not in self._swapped
            and req.handle._tokens
            and not req.handle._done
        ]

    def migrate_out(self, slot: int) -> dict:
        """Export one live decoding stream as a self-contained host
        snapshot a peer's :meth:`migrate_in` maps into its own pool
        mid-stream — the warm half of fleet failover/drain, and the
        prefill→decode handoff of role disaggregation.

        The page gather is read-only and EVERY page in the stream's
        table transfers — private AND shared: the destination has no
        prefix-index entry for our prompt, so shared/CoW prefix pages
        resolve into the snapshot rather than into a dangling
        cross-engine reference.  Only after the gather lands does the
        source release: our page references drop (shared pages stay
        with the prefix index at exactly the index-owned refcount), the
        slot clears, and the handle stays LIVE — the stream continues
        on the destination, nothing terminal is surfaced here.

        Raises (``serve.migrate_out`` fault, pool already lost, gather
        failure) strictly BEFORE any source mutation: a failed export
        leaves the stream running untouched."""
        req = self._slot_req[slot]
        if req is None:
            raise ValueError(f"slot {slot} is idle; nothing to migrate")
        if slot in self._prefill_q or slot in self._swapped:
            raise ValueError(
                f"slot {slot} is not decoding on-device (mid-prefill or "
                "swapped out); migrate only resident decode streams"
            )
        toks = req.handle._tokens
        if not toks or req.handle._done:
            raise ValueError(
                f"request {req.rid} has no live committed stream to migrate"
            )
        self._migrate_out_no += 1
        kind = faults.fire("serve.migrate_out", self._migrate_out_no)
        if kind is not None:
            # Cooperation kinds (nan) poison this export attempt: same
            # contract as io — the caller's stream keeps running here.
            raise faults.InjectedFault(
                f"poisoned migration export ({kind})"
            )
        sp = _telemetry.start_span(
            "serve.migrate_out", slot=slot,
            n_pages=len(req.blocks), n_tokens=len(toks),
        )
        try:
            if self._pool_lost():
                raise RuntimeError(
                    "source pool is gone; this stream recovers by replay"
                )
            host = swap_out_pages(self._cache, req.blocks)
        except BaseException:
            # Read-only gather: device and slot state are untouched —
            # the stream keeps running on THIS engine.
            sp.cancel()
            raise
        n_pages = len(req.blocks)
        snapshot = {
            "req": req,
            "host": host,
            "n_pages": n_pages,
            "geometry": pool_geometry(self._cache),
            "block_size": self.block_size,
            # Per-request, not per-engine: a pool-model stream migrates
            # under ITS model's tag+version, and the destination must
            # resolve that tag on its own pool before importing.
            "model_tag": req.model_tag,
            "model_version": req.model_version,
            "src_engine": self.engine_id,
            "digest": req.digest.hexdigest(),
            "n_tokens": len(toks),
        }
        # Handoff point: everything below must not fail — from here the
        # snapshot owns the stream's KV and the source owns nothing.
        self.allocator.free(req.blocks)
        req.blocks = None
        req.table = None
        req.preempt_t = time.perf_counter()  # outage clock: out → in
        self._event(
            "req.migrated_out", req, n_pages=n_pages, n_tokens=len(toks),
        )
        # Journal ownership transfer (docs/resilience.md, "Durability"):
        # the stream leaves THIS journal retired (outcome=migrated) and
        # enters the destination's as a handoff admit — it lives in
        # exactly one journal, so a crash on either side resumes it
        # exactly once.
        self._journal_retire(req, outcome="migrated")
        self._clear_slot(slot)
        self._n_migrated_out += 1
        _T_MIGRATIONS_OUT.add()
        _T_MIGRATED_PAGES.add(n_pages)
        sp.end(n_pages=n_pages, n_tokens=len(toks))
        return snapshot

    def migrate_in(self, snapshot: dict) -> RequestHandle:
        """Map a :meth:`migrate_out` snapshot into this engine's pool and
        resume the stream mid-flight — zero recompute: the pages scatter
        in, the slot restores exactly where the source left it, and the
        next decode step samples with ``fold_in(key, n_gen)``, the key
        the uninterrupted run would have used.

        Ordered so nothing can corrupt this pool or leak a page:

        1. **compatibility** — weights version, page geometry
           (``L``/``block_size``/``Hkv``/``Dh``/dtype), and table fit
           are validated BEFORE anything allocates; a mismatch raises
           typed, retryable :class:`.lifecycle.MigrationIncompatible`
           (the stream falls back to a cold key-pinned replay);
        2. **arrival digest** — the committed tokens re-hash against the
           stream's determinism digest; a mismatch is a typed
           :class:`.lifecycle.DeterminismDiverged` through the
           divergence funnel (``audit.divergences`` + flight dump),
           never a silent import;
        3. **capacity** — a free slot and ``n_pages`` fresh pages (the
           prefix-eviction reserve applies); shortage raises retryable
           :class:`.lifecycle.EngineOverloaded`;
        4. **import** — the ``serve.migrate_in`` fault site fires
           between allocation and scatter: any failure here frees the
           partial page set (or, if the donated scatter consumed the
           pool, runs the recovery supervisor) and re-raises — the
           caller cold-replays, no double-serve, no leak.

        On success the request's handle is re-bound to THIS engine and
        returned: an iterator already consuming it continues seamlessly.
        """
        req = snapshot["req"]
        toks = list(req.handle._tokens)
        if req.handle._done:
            raise ValueError(
                f"request {req.rid} is already terminal; nothing to import"
            )
        if self._health in (Health.DRAINING, Health.STOPPED):
            raise EngineDraining(
                f"engine is {self._health.value}; migrate to another replica"
            )
        tag = snapshot.get("model_tag", DEFAULT_MODEL)
        if tag == DEFAULT_MODEL:
            local_version = self.model_version
        else:
            # A pool-model stream needs its model HERE, registered AND
            # resident: an import must never stall mid-scatter on a
            # weight load, and a missing model is a typed retryable
            # incompatibility (the caller cold-replays or tries a peer).
            if self.model_pool is None or tag not in self.model_pool:
                raise MigrationIncompatible(
                    f"stream is on model {tag!r} but this engine's pool "
                    "does not register it; migrate to a replica that does"
                )
            dst_entry = self.model_pool._entries[tag]
            if not dst_entry.ready:
                raise MigrationIncompatible(
                    f"model {tag!r} is registered here but not "
                    "materialized; warm it (ModelPool.ensure) before "
                    "importing its streams"
                )
            local_version = dst_entry.model_version
        if snapshot.get("model_version") != local_version:
            raise MigrationIncompatible(
                f"weights version mismatch: snapshot "
                f"{snapshot.get('model_version')!r} != engine "
                f"{local_version!r} — a cross-version migration "
                "would interleave two models in one stream"
            )
        if snapshot.get("block_size") != self.block_size:
            raise MigrationIncompatible(
                f"page size mismatch: snapshot block_size="
                f"{snapshot.get('block_size')} != engine block_size="
                f"{self.block_size}"
            )
        if snapshot.get("geometry") != pool_geometry(self._cache):
            raise MigrationIncompatible(
                "pool geometry mismatch (layers / page size / heads / "
                "head_dim / dtype); fall back to a key-pinned replay"
            )
        n_pages = int(snapshot["n_pages"])
        if n_pages > self._table_width or (
            req.cache_tokens > self.max_model_len
        ):
            raise MigrationIncompatible(
                f"stream needs {n_pages} pages / {req.cache_tokens} "
                f"positions but this engine's table holds "
                f"{self._table_width} pages ({self.max_model_len} positions)"
            )
        if n_pages > self.allocator.capacity:
            raise MigrationIncompatible(
                f"stream needs {n_pages} pages but this engine owns "
                f"{self.allocator.capacity}"
            )
        # Arrival verification (audit plane): the committed buffer must
        # still hash to the stream's digest before its KV is mapped in.
        if toks and not req.digest.matches_stream(
            req.prompt, req.key, toks, req.model_version
        ):
            _audit.record_divergence(
                self,
                rid=req.trace_id,
                where="migrate-in",
                expected_digest=req.digest.hexdigest(),
                replayed_digest=_audit.DeterminismDigest.of_stream(
                    req.prompt, req.key, toks, req.model_version
                ).hexdigest(),
                n_tokens=len(toks),
            )
            err = DeterminismDiverged(
                f"request {req.rid} arrived with a committed stream that "
                f"no longer matches its determinism digest after "
                f"{len(toks)} tokens"
            )
            req.handle._fail(err)
            raise err
        slot = next(
            (i for i, r in enumerate(self._slot_req) if r is None), None
        )
        if slot is None:
            raise EngineOverloaded(
                "no free slot for the migrated stream; retry elsewhere"
            )
        pages = self._alloc_pages(n_pages)
        if pages is None:
            self._pool_exhausted("serve.migrate_in", n_pages)
            raise EngineOverloaded(
                f"could not reserve {n_pages} pages for the migrated "
                "stream; retry elsewhere"
            )
        self._migrate_in_no += 1
        sp = _telemetry.start_span(
            "serve.migrate_in", slot=slot,
            n_pages=n_pages, n_tokens=len(toks),
            src=snapshot.get("src_engine"),
        )
        try:
            kind = faults.fire("serve.migrate_in", self._migrate_in_no)
            if kind is not None:
                raise faults.InjectedFault(
                    f"poisoned migration import ({kind})"
                )
            self._cache = swap_in_pages(self._cache, snapshot["host"], pages)
        except (KeyboardInterrupt, SystemExit, faults.FatalInjectedFault):
            sp.cancel()
            self.allocator.free(pages)
            raise
        except Exception as err:
            sp.cancel()
            if self._pool_lost():
                # The donated scatter consumed the pool: the supervisor
                # rebuilds it and replays THIS engine's live streams;
                # the arriving stream was never installed — its granted
                # pages die with the allocator reset, and the caller's
                # cold-replay fallback owns it.
                self._oom_check(err, "serve.migrate_in")
                self._supervise_recovery(err)
            else:
                self.allocator.free(pages)
            raise
        n_gen = len(toks)
        table = np.zeros((self._table_width,), np.int32)
        table[:n_pages] = pages
        req.blocks = list(pages)
        req.table = table
        req.handle._engine = self
        req.hop += 1  # a migration is a placement hop in the timeline
        self._slot_req[slot] = req
        self._tokens[slot] = toks[-1]
        self._positions[slot] = len(req.prompt) + n_gen - 1
        self._n_gen[slot] = n_gen
        self._done[slot] = False
        self._keys[slot] = req.key
        self._tables[slot] = table
        self._emitted[slot] = n_gen
        if req.preempt_t is not None:
            self._h_outage.observe(time.perf_counter() - req.preempt_t)
            req.preempt_t = None
        self._n_migrated_in += 1
        _T_MIGRATIONS_IN.add()
        self._event(
            "req.migrated_in", req, n_pages=n_pages, n_tokens=n_gen,
            src=snapshot.get("src_engine"),
        )
        if self._journal is not None and req.audit_of is None:
            # The receiving half of the ownership transfer: a handoff
            # admit carrying the committed prefix + digest, so a crash
            # HERE resumes the stream mid-flight from this journal.
            self._journal_admit(req, tokens=toks)
        sp.end(n_pages=n_pages, n_tokens=n_gen)
        return req.handle

    # ------------------------------------------------------------------
    # Chunked prefill + the prefix cache

    def _reclaim_pages(self, n: int) -> int:
        """Evict up to ``n`` unreferenced cached-prefix pages (LRU) —
        the allocator-pressure valve admission and CoW pull."""
        if self.prefix is None:
            return 0
        freed = self.prefix.evict(n, self.allocator)
        if freed:
            _T_PREFIX_EVICTIONS.add(freed)
        return freed

    def _alloc_pages(self, n: int) -> Optional[list]:
        """``allocator.alloc`` with the prefix cache as the fallback
        reserve: under pressure, cached-but-unreferenced pages evict LRU
        before an allocation fails."""
        if n == 0:
            return []
        got = self.allocator.alloc(n)
        if got is None and self.prefix is not None:
            self._reclaim_pages(n - self.allocator.num_free)
            got = self.allocator.alloc(n)
        return got

    def _start_prefill(self, slot: int, req: Request) -> None:
        """Host-side admission of one request into the PREFILLING state:
        map the longest cached prefix (shared, refcounted), reserve
        private pages for the rest of the table, and queue the slot for
        chunk dispatch.  No device work happens here; on any failure the
        reservation rolls back completely.

        A drop-and-replay preemption victim re-admits through this same
        path: its prefill runs over :meth:`~.scheduler.Request
        .replay_seq` (``prompt + generated-so-far``) instead of the
        prompt — the supervisor's replay sequence, chunked and
        interleaved with decode like any admission — and
        :meth:`_complete_prefill` restores the slot mid-stream instead
        of sampling a first token."""
        seq_len = req.replay_len()
        n_total = blocks_needed(req.cache_tokens, self.block_size)
        shared: list = []
        cached_len = 0
        donor = (
            self._fork_donors.get(req.fork_of)
            if req.fork_of is not None and not req.handle._tokens
            else None
        )
        if donor is not None:
            # Fork sibling: map the parent's prompt-covering pages —
            # ALL of them, the partial last page included (KV of an
            # identical history is identical), which the prefix index
            # could never offer (it names full pages only).  The
            # sibling re-runs just the last prompt token to get its
            # first-sample logits; that write copy-on-writes the last
            # shared page first.  A sibling whose donor never appeared
            # (parent failed before completing prefill) or that is
            # replay-resuming falls through to the standard path.
            self.allocator.share(donor)
            shared = list(donor)
            cached_len = len(req.prompt)
        elif self.prefix is not None:
            if req.hashes is None:  # belt-and-braces: submit() hashed once
                req.hashes = page_hashes(req.prompt, self.block_size)
            shared = self.prefix.match(req.hashes)
            if shared:
                self.allocator.share(shared)
                cached_len = len(shared) * self.block_size
        priv = self._alloc_pages(n_total - len(shared))
        if priv is None:
            # pop_admissible reserved the FULL quota, so this is only
            # reachable if the map changed under us (supervisor reset
            # mid-tick); undo the share and let the caller requeue.
            if shared:
                self.allocator.free(shared)
            self._pool_exhausted("serve.start_prefill", n_total - len(shared))
            raise RuntimeError("prefill could not reserve its promised pages")
        if donor is None and cached_len and not req.hit_counted:
            # Counted once per REQUEST, not per admission attempt — a
            # transiently-failed prefill that requeues and re-admits
            # must not inflate the hit rate past 1.0.
            req.hit_counted = True
            self.prefix.hits += 1
            self.prefix.hit_tokens += cached_len
            _T_PREFIX_HITS.add()
            _T_PREFIX_HIT_TOKENS.add(cached_len)
        req.blocks = shared + priv
        table = np.zeros((self._table_width,), np.int32)
        table[: len(req.blocks)] = req.blocks
        req.table = table
        req.n_cached = cached_len
        # Full-sequence hit: the first sample (or a resume's discarded
        # recomputation) still needs the last token's logits, so
        # recompute exactly that token — its write lands in the final
        # shared page, which copy-on-write privatizes first.
        req.prefill_pos = min(cached_len, seq_len - 1)
        self._slot_req[slot] = req
        # Slot arrays stay idle (done=True, device table 0 → trash)
        # until the last chunk installs them — the decode batch must not
        # see a half-prefilled slot.
        self._prefill_q.append(slot)
        if req.admit_t is None:
            # First admission only: the queue-wait phase ends here.  A
            # re-admission (drop-and-replay resume, transient-failure
            # requeue) is preemption outage, not queue wait.
            req.admit_t = time.perf_counter()
            self._h_queue_wait.observe(req.admit_t - req.submit_t)
        self._event(
            "req.admitted", req, slot=slot, cached_tokens=cached_len,
            n_blocks=len(req.blocks),
        )

    def _advance_prefills(self) -> int:
        """Dispatch up to ``max_prefills_per_tick`` prefill chunks,
        strictly FIFO: the head slot gets the whole budget until its
        prompt completes — that is what bounds a 16k prompt's impact on
        running streams to one chunk per tick.  Returns the number of
        chunks dispatched (the tick's ``serve.prefill_budget`` reading)."""
        budget = self.max_prefills_per_tick
        while budget > 0 and self._prefill_q:
            slot = self._prefill_q[0]
            req = self._slot_req[slot]
            seq = req.replay_seq()  # = prompt, unless resuming a preempt
            start = req.prefill_pos
            end = min(start + self.prefill_chunk, len(seq))
            self._prefill_no += 1
            try:
                kind = faults.fire("serve.prefill", self._prefill_no)
            except OSError:
                # Transient: chunk state is intact (nothing dispatched);
                # the next tick retries this same chunk.
                _T_PREFILL_RETRIES.add()
                break
            if kind is not None:  # nan: poisoned prefill tick — skip it
                _T_PREFILL_RETRIES.add()
                break
            try:
                first = self._dispatch_chunk(slot, req, seq, start, end)
            except (KeyboardInterrupt, SystemExit):
                raise
            except faults.FatalInjectedFault:
                raise
            except Exception as err:
                self._on_prefill_failure(req, err)
                break
            req.prefill_pos = end
            budget -= 1
            if first is not None:
                self._prefill_q.pop(0)
                self._complete_prefill(slot, req, first)
        return self.max_prefills_per_tick - budget

    def _chunk_bucket(self, n: int) -> int:
        """Chunk pad length: next power of two from ``min_prefill_bucket``
        (one compile per bucket), capped at ``prefill_chunk`` — every
        non-final chunk is exactly ``prefill_chunk`` wide."""
        b = self.min_prefill_bucket
        while b < n:
            b *= 2
        return min(b, max(self.prefill_chunk, n))

    def _cow_shared_pages(self, req: Request, lo: int, hi: int) -> None:
        """Copy-on-write every SHARED page the positions ``[lo, hi)``
        would write: a page with more than one reference (the prefix
        index's, another stream's) is immutable history — the writer
        gets a private device-side copy (:func:`.cache.copy_pages`) and
        the table entry swaps to it.  Never page 0: table rows are real
        pages or 0, and 0 rows are skipped (their writes steer to trash
        by construction)."""
        bs = self.block_size
        first_blk = lo // bs
        last_blk = min(-(-hi // bs), self._table_width)
        for idx in range(first_blk, last_blk):
            page = int(req.table[idx])
            if page == 0:
                continue  # unreserved tail: the scatter steers it to trash
            if self.allocator.refcount(page) <= 1:
                continue
            fresh = self._alloc_pages(1)
            if fresh is None:
                self._pool_exhausted("serve.cow", 1)
                raise RuntimeError("copy-on-write could not reserve a page")
            self._cache = copy_pages(
                self._cache, np.int32(page), np.int32(fresh[0])
            )
            req.table[idx] = fresh[0]
            req.blocks[req.blocks.index(page)] = fresh[0]
            self.allocator.free([page])  # drop OUR reference on the shared one
            self._n_cow += 1
            _T_COW.add()

    def _run_chunk(
        self, seq, table, start: int, end: int, key,
        model_tag: str = DEFAULT_MODEL,
    ):
        """Dispatch ONE compiled prefill chunk of ``seq[start:end]``
        against ``table``, under ``model_tag``'s weights.  Returns the
        sampled first token on the final chunk (``end == len(seq)``),
        else None.  Pool-model chunks run the SAME two jitted programs
        — a tag sharing the engine's family and cfg shares its
        compiles; the observatory label carries the tag so per-model
        compile attribution stays readable."""
        n = end - start
        bucket = self._chunk_bucket(n)
        model, cfg, params = self._model_ctx(model_tag)
        suffix = "" if model_tag == DEFAULT_MODEL else f":{model_tag}"
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = seq[start:end]
        pos = np.full((1,), start, np.int32)
        if end >= len(seq):
            first, self._cache = _JP_PREFILL_LAST.call(
                self, f"prefill_chunk_last:b{bucket}{suffix}",
                params, self._cache, tokens, pos,
                np.int32(end - 1 - start), key, table,
                model=model, cfg=cfg,
                temperature=self.temperature, top_k=self.top_k,
            )
            tt = self._tick_timer
            if tt is not None:
                # The int() below is the prefill-side host sync (the
                # sampled token materializes here): count it as
                # device_wait, or a prefill-bound tick would read as
                # host-bound on serve.host_overhead_frac.
                tt.begin("device_wait")
            first = int(first)
            if tt is not None:
                tt.begin("prefill_dispatch")
            return first
        self._cache = _JP_PREFILL.call(
            self, f"prefill_chunk:b{bucket}{suffix}",
            params, self._cache, tokens, pos, table,
            model=model, cfg=cfg,
        )
        return None

    def _dispatch_chunk(
        self, slot: int, req: Request, seq, start: int, end: int
    ):
        """One admission-path chunk of ``seq`` (the prompt, or a
        resume's replay sequence): CoW anything the chunk (padding
        included) would write, then run it."""
        bucket = self._chunk_bucket(end - start)
        self._cow_shared_pages(req, start, start + bucket)
        self._event(
            "req.prefill_chunk", req, start=start, n=end - start,
            last=end >= len(seq),
        )
        with self._trace_ctx(req), _telemetry.span(
            "serve.prefill", slot=slot, start=start, n=end - start,
            bucket=bucket, cached=req.n_cached,
        ):
            return self._run_chunk(
                seq, req.table, start, end, req.key, req.model_tag
            )

    def _complete_prefill(self, slot: int, req: Request, first: int) -> None:
        """Last chunk done: register the prompt's full pages in the
        prefix index and install the slot into the decode batch."""
        if (
            req.rid in self._fork_groups
            and req.rid not in self._fork_donors
        ):
            # Fork parent: pin the prompt-covering pages for the
            # siblings with ENGINE-held references — the donor survives
            # the parent retiring (even on its very first token) and
            # outlives the prefix index's full-page-only view.
            n_prompt_pages = min(
                blocks_needed(len(req.prompt), self.block_size),
                len(req.blocks),
            )
            donor = [int(req.table[i]) for i in range(n_prompt_pages)]
            self.allocator.share(donor)
            self._fork_donors[req.rid] = donor
        if self.prefix is not None and req.hashes:
            self.prefix.register(
                req.hashes,
                [int(req.table[i]) for i in range(len(req.hashes))],
                self.allocator,
            )
        toks = req.handle._tokens
        now = time.perf_counter()
        if toks:
            # A drop-and-replay preemption victim resuming: the sampled
            # token is a recomputation of an already-committed one —
            # discard it; the pending input is the last committed token
            # and the key schedule continues at fold_in(key, n_gen).
            # TTFT was recorded at the original first token.
            # The resume verifies the committed buffer against the
            # request's determinism digest FIRST (O(1) memory — one
            # re-hash, one compare): a corrupted buffer must fail
            # typed, never silently poison the continuation.
            if not req.digest.matches_stream(
                req.prompt, req.key, toks, req.model_version
            ):
                self._resume_diverged(slot, req, "preempt-replay-resume")
                return
            self._tokens[slot] = toks[-1]
            self._positions[slot] = req.replay_len()
            self._n_gen[slot] = len(toks)
            self._done[slot] = False
            self._keys[slot] = req.key
            self._tables[slot] = req.table
            self._emitted[slot] = len(toks)
            if req.preempt_t is not None:
                self._h_outage.observe(now - req.preempt_t)
                req.preempt_t = None
            self._event(
                "req.resumed", req, mechanism="replay", n_tokens=len(toks)
            )
            return
        req.handle.ttft_s = now - req.submit_t
        self._h_ttft.observe(req.handle.ttft_s)
        if req.preempt_t is not None:
            # Preempted (or recovery-requeued) before its first token:
            # the stall since then is outage, not prefill.
            self._h_outage.observe(now - req.preempt_t)
            req.preempt_t = None
        elif req.admit_t is not None:
            self._h_prefill.observe(now - req.admit_t)
        if req.trace_id is not None:
            # The digest here is the request's ADMITTED identity
            # (prompt bytes + key schedule, no tokens yet) — enough to
            # match a first-token event against an incident replay; the
            # full-stream snapshot lands on req.finished.
            self._event(
                "req.first_token", req, ttft_s=round(req.handle.ttft_s, 6),
                digest=req.digest.hexdigest(),
            )
        _G_TTFT.set(round(req.handle.ttft_s, 4))
        s = len(req.prompt)
        self._tokens[slot] = first
        self._positions[slot] = s
        self._n_gen[slot] = 1
        self._done[slot] = False
        self._keys[slot] = req.key
        self._tables[slot] = req.table
        self._emitted[slot] = 0
        # _push_token retires immediately on a first-token EOS or a
        # budget of one — the slot never enters the decode batch.
        self._push_token(slot, first)
        if self._journal is not None:
            # The first token is a commit point like any chunk boundary
            # (a first-token retirement journals via the funnel instead).
            self._journal_commit(req, len(req.handle._tokens) - 1)

    @staticmethod
    def _reset_prefill_state(req: Request) -> None:
        """Forget a request's in-progress prefill (its pages are gone —
        freed or reclaimed by an allocator reset) so a re-admission
        starts clean.  ``hit_counted`` deliberately survives: the hit
        rate counts requests, not admission attempts."""
        req.blocks = None
        req.table = None
        req.prefill_pos = 0
        req.n_cached = 0

    def _resume_diverged(self, slot: int, req: Request, where: str) -> None:
        """A resume's digest verification failed: the committed-token
        buffer was corrupted while the stream was parked.  Latch the
        engine (divergence funnel: ``audit.divergences`` + the
        ``serve.diverging`` gauge + a flight dump) and fail the request
        typed — never feed a poisoned buffer back to the model."""
        toks = list(req.handle._tokens)
        _audit.record_divergence(
            self,
            rid=req.trace_id,
            where=where,
            expected_digest=req.digest.hexdigest(),
            replayed_digest=_audit.DeterminismDigest.of_stream(
                req.prompt, req.key, toks, req.model_version
            ).hexdigest(),
            n_tokens=len(toks),
        )
        self._fail_running_slot(
            slot,
            DeterminismDiverged(
                f"request {req.rid} resume ({where}): committed tokens no "
                f"longer match the determinism digest after {len(toks)} "
                "tokens"
            ),
        )

    def _abort_prefill(self, slot: int) -> Request:
        """Back a PREFILLING slot fully out: pages returned (shared ones
        just drop our reference), chunk state reset, slot idle.  Returns
        the request, ready to requeue or fail."""
        req = self._slot_req[slot]
        if req.blocks:
            self.allocator.free(req.blocks)
        self._reset_prefill_state(req)
        self._clear_slot(slot)
        return req

    def _on_prefill_failure(self, req: Request, err: BaseException) -> None:
        """A chunk dispatch raised.  If the donated pool was consumed the
        supervisor owns everything (prefilling slots requeue, decoding
        slots replay).  Otherwise charge the failing request's recovery
        budget and restart its prefill from the FIFO head — together
        with every prefill admitted behind it, so the failure cannot
        cost anyone their place in line."""
        self._oom_check(err, "serve.prefill")
        if self._pool_lost():
            self._supervise_recovery(err)
            return
        reqs = [self._abort_prefill(slot) for slot in list(self._prefill_q)]
        req.recoveries += 1
        if req.recoveries > self.max_recoveries:
            _T_RECOVERY_FAILURES.add()
            req.handle._fail(
                RecoveryFailed(
                    f"request {req.rid} aborted: prefill failed "
                    f"{req.recoveries} times ({err!r})"
                )
            )
            self.scheduler.requeue([r for r in reqs if r is not req])
        else:
            _T_PREFILL_RETRIES.add()
            # ONE requeue call: the failed request lands at the head,
            # AHEAD of the prefills admitted behind it (two calls would
            # appendleft the tail in front of it).
            self.scheduler.requeue(reqs)

    def _prefill_dispatch(self, req: Request, seq: np.ndarray):
        """Synchronous full-sequence prefill — the recovery replay path
        (recovery is rare, so no tick interleaving): reserve the
        request's full page quota, run every chunk back to back, and
        free the reservation before any error surfaces — a leaked
        reservation drives the engine into permanent backpressure.
        Returns ``(sampled_token, table)``.  No prefix-index interaction:
        replays only run against a freshly-reset pool, where the index
        is empty by definition."""
        need = blocks_needed(req.cache_tokens, self.block_size)
        blocks = self._alloc_pages(need)
        if blocks is None:  # admission reserved cumulatively / allocator reset
            self._pool_exhausted("serve.replay_prefill", need)
            raise RuntimeError("prefill could not reserve its promised pages")
        req.blocks = blocks
        table = np.zeros((self._table_width,), np.int32)
        table[: len(blocks)] = blocks
        try:
            first = None
            for start in range(0, len(seq), self.prefill_chunk):
                end = min(start + self.prefill_chunk, len(seq))
                first = self._run_chunk(
                    seq, table, start, end, req.key, req.model_tag
                )
        except BaseException:
            self.allocator.free(blocks)
            req.blocks = None
            raise
        return first, table

    # ------------------------------------------------------------------
    # Decode + the recovery supervisor

    def _decode_phase(self) -> int:
        """One decode chunk over the running slots; returns the number
        of tokens committed (the tick's ``serve.goodput`` numerator)."""
        if not self._n_decoding():
            return 0
        self._decode_no += 1
        try:
            kind = faults.fire("serve.step", self._decode_no)
        except OSError:
            # Transient: state untouched, next tick re-runs the chunk —
            # decode is pure, so the retry is token-identical.
            _T_STEP_RETRIES.add()
            return 0
        if kind == "nan":
            # Poisoned step: skip BEFORE dispatch (committed state is the
            # prior state bit-identically — the serving analog of the
            # train loop's skip-step guard), count it, keep going.
            _T_SKIPPED.add()
            return 0
        # "corrupt" (audit-plane fault, docs/resilience.md): the chunk
        # runs normally, then ONE committed token is flipped on the
        # host — a silent single-bit determinism break the shadow
        # auditor must catch (nothing else will: the device state keeps
        # the true token, so the stream stays plausible).
        corrupt = kind == "corrupt"
        # Group the decode batch by model.  The common case is ONE
        # group on the engine's own model and takes the exact
        # pre-model-plane path: no array copies, one dispatch.  With
        # pool models decoding, each group runs its own compiled chunk
        # over a masked view of the slot arrays — non-group slots ride
        # along as done-slots scribbling on the trash page (the same
        # rule idle/prefilling/swapped slots already obey), so the
        # sequential passes commute and donation stays safe (every
        # pass returns a fresh pool).  Two tags sharing the engine's
        # family and cfg share ONE compile: the jit cache keys on
        # (module, cfg, shapes), not on the tag.
        groups: dict[str, list] = {}
        for slot, req in enumerate(self._slot_req):
            if req is None or slot in self._prefill_q or slot in self._swapped:
                continue
            groups.setdefault(req.model_tag, []).append(slot)
        committed = 0
        for tag, slots in groups.items():
            got = self._decode_group(
                tag, slots, solo=len(groups) == 1, corrupt=corrupt
            )
            corrupt = False  # one flipped token per poisoned chunk
            if got is None:  # dispatch failed; handled (retry/recovery)
                break
            committed += got
        self._decode_tokens += committed
        if self._decode_s > 0:
            _G_DECODE_TPS.set(round(self._decode_tokens / self._decode_s, 1))
        return committed

    def _decode_group(
        self, tag: str, slots: list, *, solo: bool, corrupt: bool
    ) -> Optional[int]:
        """One compiled decode chunk over the slots of ONE model.
        ``solo`` (the whole decode batch is one model) passes the slot
        arrays through unmasked — bit-identical to the single-model
        engine.  Returns tokens committed, or None when the dispatch
        failed and the failure was already handled (free retry next
        tick, or the recovery supervisor ran)."""
        model, cfg, params = self._model_ctx(tag)
        if solo:
            done, tables = self._done, self._tables
        else:
            # Masked copies: non-group slots read done=True and table 0
            # (writes land on the trash page, outputs are discarded) —
            # their REAL state stays untouched for their own pass.
            done = np.ones_like(self._done)
            done[slots] = self._done[slots]
            tables = np.zeros_like(self._tables)
            tables[slots] = self._tables[slots]
        tt = self._tick_timer
        if tt is not None:
            tt.begin("decode_dispatch")
        attrs = {"n_active": len(slots), "chunk": self.decode_chunk}
        if tag != DEFAULT_MODEL:
            attrs["model"] = tag
        sp = _telemetry.start_span("serve.step", **attrs)
        t0 = time.perf_counter()
        try:
            self._cache, out = _JP_DECODE.call(
                self,
                None if tag == DEFAULT_MODEL else f"decode_chunk:{tag}",
                params, self._cache,
                self._tokens, self._positions, self._n_gen, done,
                self._keys, tables,
                model=model, cfg=cfg,
                temperature=self.temperature, top_k=self.top_k,
                eos_id=self.eos_id, n_steps=self.decode_chunk,
            )
        except (KeyboardInterrupt, SystemExit):
            sp.cancel()
            raise
        except faults.FatalInjectedFault:
            sp.cancel()
            raise
        except Exception as err:
            sp.cancel()
            self._oom_check(err, "serve.step")
            self._consec_decode_failures += 1
            if not self._pool_lost() and self._consec_decode_failures <= 1:
                # The donation was not consumed and nothing committed:
                # decode is pure over committed state, so the next
                # tick's re-run is free and token-identical.  One free
                # retry — a deterministic error must not spin, so the
                # second consecutive failure escalates below.
                _T_STEP_RETRIES.add()
                return None
            # The chunk held the donated cache (or keeps failing): the
            # supervisor rebuilds the pool and replays every live
            # request token-identically, under per-request budgets.
            self._consec_decode_failures = 0
            self._supervise_recovery(err)
            return None
        if tt is not None:
            # The dispatch gap: everything after here until the asarray
            # returns is the host blocked on device compute — the
            # device side of serve.host_overhead_frac.
            tt.begin("device_wait")
        out = np.asarray(out)  # (chunk, S) — the one host sync per chunk
        if tt is not None:
            tt.begin("commit")
        if corrupt and slots:
            out = out.copy()  # the jax-backed view may be read-only
            # Deterministic victim: the group's first decoding slot's
            # first token of this chunk, XOR 1.
            out[0, slots[0]] = int(out[0, slots[0]]) ^ 1
            _T_CORRUPTIONS.add()
        self._consec_decode_failures = 0
        dt = time.perf_counter() - t0
        self._decode_s += dt

        committed = 0
        jstate = None
        if self._journal is not None:
            # Chunk-boundary journal commits: capture (request, tokens
            # already committed) BEFORE the commit loop — a slot that
            # retires mid-chunk clears _slot_req, but retired streams
            # journal their outcome through the retirement funnel and
            # need no trailing commit record.
            jstate = [
                (self._slot_req[slot], len(self._slot_req[slot].handle._tokens))
                for slot in slots
            ]
        for slot in slots:
            for tok in out[:, slot]:
                self._push_token(slot, int(tok))
                committed += 1
                if self._slot_req[slot] is None:  # retired mid-chunk
                    break
            else:
                # Still running: roll the slot's device-visible state
                # forward by the whole chunk (post-EOS/budget overshoot
                # inside the chunk stays inside the slot's own pages).
                self._tokens[slot] = out[-1, slot]
                self._positions[slot] += self.decode_chunk
                self._n_gen[slot] += self.decode_chunk
        if jstate is not None:
            for jreq, jn0 in jstate:
                self._journal_commit(jreq, jn0)
        if committed:
            # Per-token decode time (TPOT): one aggregated observation
            # per chunk — each committed token cost one scan step of
            # this chunk's wall time.  No per-token call, no allocation.
            self._h_tpot.observe(dt / self.decode_chunk, n=committed)
        if tag != DEFAULT_MODEL:
            self.model_pool._note_tokens(tag, committed)
        sp.end(tokens=committed)
        return committed

    def _pool_lost(self) -> bool:
        """True when a failed donated call consumed the page pool."""
        return any(
            isinstance(x, jax.Array) and x.is_deleted()
            for x in jax.tree.leaves(self._cache)
        )

    def _supervise_recovery(self, error: BaseException) -> None:
        """Restore servability after a failed device call, replaying the
        live requests instead of failing them.

        The pool (and with it every live request's KV) is assumed gone:
        a fresh zeroed pool is installed, the allocator map reset, and
        each live request re-prefilled over ``prompt + generated-so-far``
        — ``fold_in(key, n_gen)`` sampling makes the continuation
        token-identical, greedy and sampled.  Each recovery event (and
        each failed replay) charges the request's ``max_recoveries``
        budget; exhaustion is a typed, *retryable*
        :class:`.lifecycle.RecoveryFailed` — never a silently truncated
        stream.  A failed replay may itself have consumed the fresh pool,
        so the whole pass restarts (budgets keep it finite).
        """
        self._n_recoveries += 1
        _T_RECOVERIES.add()
        # The post-mortem moment the flight recorder exists for: dump
        # the recent-records ring before the replay overwrites history.
        _telemetry.flight_dump(
            "serve.recover", engine=self.engine_id,
            error=type(error).__name__,
        )
        sp = _telemetry.start_span(
            "serve.recover",
            n_live=self._n_running(),
            error=type(error).__name__,
        )
        now = time.perf_counter()
        for slot in range(self.num_slots):
            req = self._slot_req[slot]
            if req is not None:
                req.recoveries += 1
                if req.preempt_t is None:
                    req.preempt_t = now
                self._event(
                    "req.preempted", req, mechanism="replay",
                    reason="recovery", n_tokens=len(req.handle._tokens),
                )
        # Slots still PREFILLING have no committed tokens to replay:
        # their (lost) pages come back with the allocator reset below,
        # and the requests restart from the FIFO head — in admission
        # order, within their recovery budgets.  The prefix index dies
        # with the pool: every cached page's KV is gone.
        requeue = []
        for slot in list(self._prefill_q):
            req = self._slot_req[slot]
            # No allocator.free here: the lost pool's map is reclaimed
            # wholesale by the reset below.
            self._reset_prefill_state(req)
            if req.recoveries > self.max_recoveries:
                _T_RECOVERY_FAILURES.add()
                req.handle._fail(
                    RecoveryFailed(
                        f"request {req.rid} aborted: recovery budget "
                        f"({self.max_recoveries}) exhausted before its "
                        f"prefill completed ({error!r})"
                    )
                )
            else:
                requeue.append(req)
            self._clear_slot(slot)
        self.scheduler.requeue(requeue)
        # Swapped slots: their host buffers are still valid, but the
        # committed tokens on the handle are all a replay needs —
        # discard the buffers and replay those streams like any
        # decoding slot.  The allocator reset below re-zeroes the swap
        # account along with the ownership map.
        self._swapped.clear()
        self._swap_host_bytes = 0
        # Fork donors died with the pool; drop the refs without frees
        # (the allocator reset below reclaims every page).  The groups
        # stay: a replaying parent re-creates its donor at prefill
        # completion, so siblings still waiting in the queue re-share.
        self._fork_donors.clear()
        if self.prefix is not None:
            self.prefix.clear()
        # Replay inputs verify against the determinism digest BEFORE
        # anything is re-prefilled: the supervisor replays exactly the
        # committed stream or fails it typed — never a corrupted one.
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            toks = req.handle._tokens
            if toks and not req.digest.matches_stream(
                req.prompt, req.key, toks, req.model_version
            ):
                self._resume_diverged(slot, req, "recovery-replay")
        pending = [
            (slot, req)
            for slot, req in enumerate(self._slot_req)
            if req is not None
        ]
        while True:
            replayed = 0  # an aborted pass's replays died with its pool
            self.allocator.reset()
            self._cache = fresh_pool(self._cache)
            still = []
            for slot, req in pending:
                if req.recoveries > self.max_recoveries:
                    req.blocks = None
                    _T_RECOVERY_FAILURES.add()
                    req.handle._fail(
                        RecoveryFailed(
                            f"request {req.rid} aborted: recovery budget "
                            f"({self.max_recoveries}) exhausted after "
                            f"{self._emitted[slot]} tokens ({error!r})"
                        )
                    )
                    self._clear_slot(slot)
                else:
                    still.append((slot, req))
            pending = still
            if not pending:
                break
            failed = False
            for slot, req in pending:
                self._recover_no += 1
                try:
                    kind = faults.fire("serve.recover", self._recover_no)
                    if kind is not None:
                        # Cooperation kinds (nan) poison THIS replay
                        # attempt — a consumed spec that silently did
                        # nothing would defeat the registry's point.
                        raise faults.InjectedFault(
                            f"poisoned replay attempt ({kind})"
                        )
                    self._replay_into(slot, req)
                    replayed += 1
                except (KeyboardInterrupt, SystemExit):
                    sp.cancel()
                    raise
                except faults.FatalInjectedFault:
                    sp.cancel()
                    raise
                except Exception:
                    # This replay's donated call may have consumed the
                    # fresh pool too: charge the failing request and
                    # restart the whole pass from a clean map.
                    req.recoveries += 1
                    failed = True
                    break
            if not failed:
                break
        sp.end(n_replayed=replayed)

    def _replay_into(self, slot: int, req: Request) -> None:
        """Re-prefill a live request's ``prompt + generated-so-far`` into
        fresh pages, restoring the slot exactly where it was.

        The committed tokens live on the handle; all but the last were
        already *fed* to the model (the last is the slot's pending input
        token), so the replayed sequence is ``prompt + tokens[:-1]`` and
        the reused prefill program's sampled token — a recomputation of
        an already-committed one — is discarded.  The next decode step
        samples with ``fold_in(key, n_gen)``, the exact key the
        uninterrupted run would have used."""
        toks = req.handle._tokens
        n_gen = len(toks)
        seq = np.concatenate(
            [req.prompt, np.asarray(toks[:-1], np.int32)]
        ).astype(np.int32)
        # Same dispatch as admission; the sampled token is a
        # recomputation of an already-committed one and is discarded.
        _, table = self._prefill_dispatch(req, seq)
        self._slot_req[slot] = req
        self._tokens[slot] = toks[-1]
        self._positions[slot] = len(seq)
        self._n_gen[slot] = n_gen
        self._done[slot] = False
        self._keys[slot] = req.key
        self._tables[slot] = table
        self._emitted[slot] = n_gen
        if req.preempt_t is not None:
            self._h_outage.observe(time.perf_counter() - req.preempt_t)
            req.preempt_t = None
        self._event(
            "req.resumed", req, mechanism="replay", reason="recovery",
            n_tokens=n_gen,
        )

    # ------------------------------------------------------------------
    # Token commit / retirement

    def _push_token(self, slot: int, token: int) -> None:
        """Commit one token to the slot's handle; retire on EOS/budget.
        The commit IS the digest update: the rolling determinism digest
        covers exactly the committed stream, whatever preemptions or
        recoveries happened between chunks (resumes re-commit nothing)."""
        req = self._slot_req[slot]
        req.handle._push(token)
        req.digest.update((token,), req.model_version)
        self._emitted[slot] += 1
        _T_TOKENS.add()
        if self._emitted[slot] >= req.max_new_tokens or (
            self.eos_id is not None and token == self.eos_id
        ):
            self._retire(slot)

    def _retire(self, slot: int) -> None:
        req = self._slot_req[slot]
        self.allocator.free(req.blocks)
        req.blocks = None
        req.handle._finish()
        _T_FINISHED.add()
        self._clear_slot(slot)
        if self._auditor is not None:
            # Completed requests feed the shadow auditor (audit replays
            # settle their digest comparison through the same hook).
            self._auditor.on_finished(req)

    def _clear_slot(self, slot: int) -> None:
        self._slot_req[slot] = None
        self._tokens[slot] = 0
        self._positions[slot] = 0
        self._n_gen[slot] = 0
        self._done[slot] = True
        self._tables[slot] = 0  # idle slots scribble on the trash page
        if slot in self._prefill_q:  # reaped/aborted mid-prefill
            self._prefill_q.remove(slot)

    # ------------------------------------------------------------------
    # Durability plane: the request journal + cold-restart resume
    # (docs/resilience.md, "Durability")

    def _bind_journal(self, journal: RequestJournal) -> None:
        """Adopt a journal: geometry check (read-only, BEFORE the
        claim — a config-mismatched engine must not steal the lock from
        the replica that could actually resume the streams), ownership
        claim (typed :class:`.lifecycle.JournalOwned` when a live
        engine holds it), then this engine's config record."""
        prior = journal.peek_config()
        if prior is not None:
            mine = self._journal_config()
            bad = [
                k for k in mine
                if k in prior and prior[k] != mine[k]
            ]
            if bad:
                raise ValueError(
                    "journal geometry mismatch on "
                    f"{bad}: journal has "
                    f"{ {k: prior[k] for k in bad} }, engine has "
                    f"{ {k: mine[k] for k in bad} } — resuming here "
                    "would continue the streams with different tokens"
                )
        journal.claim(self.engine_id)
        journal.write_config(engine=self.engine_id, **self._journal_config())
        self._journal = journal

    def _journal_config(self) -> dict:
        """The geometry a resume must agree on: anything baked into the
        compiled programs that changes WHICH tokens a stream commits."""
        return {
            "temperature": self.temperature,
            "top_k": self.top_k,
            "eos_id": self.eos_id,
            "decode_chunk": self.decode_chunk,
            "model_version": self.model_version,
        }

    def _journal_admit(self, req: Request, *, tokens=None) -> None:
        """Journal one request's replay identity — the ``req.submitted``
        payload, durable.  ``tokens`` marks a handoff admit (migration
        import): the committed prefix + digest snapshot ride along."""
        j = self._journal
        uid = j.next_uid()
        rec = {
            "t": "admit", "u": uid,
            "prompt": [int(t) for t in req.prompt],
            "key": [int(k) for k in req.key],
            "max_new": int(req.max_new_tokens),
            "model": req.model_tag, "version": req.model_version,
            "tenant": req.tenant, "priority": int(req.priority),
            # perf_counter deadlines die with the process: the journal
            # carries the wall-clock expiry, and resume converts back
            # (or fails the stream typed if the outage outlived it).
            "deadline": (
                None if req.deadline is None
                else time.time() + (req.deadline - time.perf_counter())
            ),
            "trace": req.trace_id,
        }
        if tokens:
            rec["tokens"] = [int(t) for t in tokens]
            rec["d"] = req.digest.hexdigest()
        try:
            j.append(rec)
        except OSError:
            _journal_mod._T_APPEND_ERRORS.add()
            return  # no uid: this stream rides unjournaled
        req._journal_uid = uid

    def _journal_commit(self, req: Request, n0: int) -> None:
        """Journal a chunk boundary's newly committed tokens (from
        index ``n0``) plus the rolling-digest snapshot after them."""
        uid = getattr(req, "_journal_uid", None)
        if uid is None:
            return
        delta = req.handle._tokens[n0:]
        if not delta:
            return
        try:
            self._journal.append({
                "t": "commit", "u": uid,
                "toks": [int(t) for t in delta],
                "n": len(req.handle._tokens),
                "d": req.digest.hexdigest(),
            })
        except OSError:
            _journal_mod._T_APPEND_ERRORS.add()

    def _journal_retire(
        self, req, error=None, outcome: Optional[str] = None
    ) -> None:
        """The retirement funnel: every terminal path — finish, fail,
        cancel, expiry, migration handoff — lands here (the handle's
        ``_finish``/``_fail`` call in), so a journaled stream can never
        be resurrected after its client already saw a terminal."""
        j = self._journal
        if j is None or req is None:
            return
        uid = getattr(req, "_journal_uid", None)
        if uid is None:
            return
        req._journal_uid = None
        if outcome is None:
            if error is None:
                outcome = "finished"
            elif isinstance(error, RequestCancelled):
                outcome = "cancelled"
            elif isinstance(error, DeadlineExceeded):
                outcome = "expired"
            else:
                outcome = "failed"
        rec = {
            "t": "retire", "u": uid, "outcome": outcome,
            "n": len(req.handle._tokens),
        }
        # Retirement usually lands mid-chunk, before the chunk's
        # trailing commit would have run (and it won't — the uid is
        # cleared above).  Journal the uncommitted tail here so the
        # folded entry always holds the stream the client saw.
        tail = req.handle._tokens[j.committed_n(uid):]
        if tail:
            rec["toks"] = [int(t) for t in tail]
        if error is not None:
            rec["error"] = type(error).__name__
        if req.digest is not None:
            rec["d"] = req.digest.hexdigest()
        try:
            j.append(rec)
        except OSError:
            _journal_mod._T_APPEND_ERRORS.add()

    def resume_from_journal(
        self, journal: Optional[RequestJournal] = None
    ) -> dict:
        """Cold-restart resume: re-admit every unfinished journaled
        stream through the existing replay machinery.

        Each stream re-prefills ``prompt + committed tokens`` and
        continues at ``fold_in(key, n_gen)`` — token-identical to the
        uninterrupted run, greedy and sampled.  Before anything is
        admitted, per stream:

        1. the journaled tokens re-hash against the journaled digest
           snapshot — a mismatch is a typed
           :class:`.lifecycle.DeterminismDiverged` through the
           divergence funnel, never a silently wrong stream;
        2. an expired wall-clock deadline fails typed
           :class:`.lifecycle.DeadlineExceeded` (the outage outlived
           the SLO — finishing late is not finishing);
        3. a pool-model stream demand-materializes its model via the
           :class:`.modelpool.ModelPool` before replay (an evicted
           model is re-loaded, an unregistered one fails typed).

        Pass ``journal`` to adopt one post-construction (the
        :meth:`FleetRouter.recover` path) — the claim is the
        double-resume guard: a second engine offered the same journal
        gets a typed :class:`.lifecycle.JournalOwned`.  Returns
        ``{journal uid: RequestHandle}`` — handles of failed streams
        carry their typed error; the rest stream from token 0 through
        completion as the engine steps."""
        if journal is not None:
            if self._journal is None:
                self._bind_journal(journal)
            elif journal is not self._journal:
                raise ValueError(
                    "engine already owns a different journal; resume "
                    "this one on a fresh engine"
                )
        j = self._journal
        if j is None:
            raise ValueError(
                "resume_from_journal needs a journal: construct with "
                "Engine(journal=RequestJournal(dir)) or pass one here"
            )
        if self._health in (Health.DRAINING, Health.STOPPED):
            raise EngineDraining(
                f"engine is {self._health.value}; resume on a live replica"
            )
        entries, _config = j.recover()
        sp = _telemetry.start_span(
            "serve.resume_cold", n_streams=len(entries)
        )
        now_wall = time.time()
        now_perf = time.perf_counter()
        handles: dict = {}
        for uid in sorted(entries):
            handles[uid] = self._resume_entry(
                entries[uid], now_wall, now_perf
            )
        n_live = sum(1 for h in handles.values() if not h._done)
        sp.end(n_resumed=n_live, n_failed=len(handles) - n_live)
        return handles

    def _resume_entry(self, e, now_wall: float, now_perf: float):
        """Re-admit ONE journaled stream (see resume_from_journal)."""
        rid = self._next_rid
        self._next_rid += 1
        handle = RequestHandle(self, rid)
        prompt = np.asarray(e.prompt, np.int32)
        key = np.asarray(e.key, np.uint32).reshape(2)
        digest = _audit.DeterminismDigest(prompt, key)
        if e.tokens:
            digest.update(e.tokens, e.model_version)
        tid = e.trace_id
        if tid is None and _telemetry.events_enabled():
            tid = f"{self.engine_id}-r{rid}"
        deadline = (
            None if e.deadline_wall is None
            else now_perf + (e.deadline_wall - now_wall)
        )
        replay_len = len(e.prompt) + max(0, len(e.tokens) - 1)
        n_chunks = -(-max(1, replay_len) // self.prefill_chunk)
        pool_entry = None
        if (
            e.model_tag != DEFAULT_MODEL
            and self.model_pool is not None
            and e.model_tag in self.model_pool
        ):
            pool_entry = self.model_pool._entries[e.model_tag]
        hashes = None
        if self.prefix is not None and len(prompt):
            hashes = page_hashes(
                prompt, self.block_size,
                pool_entry.namespace if pool_entry is not None else b"",
            )
        req = Request(
            rid, prompt, int(e.max_new_tokens), key, handle,
            deadline=deadline, n_chunks=n_chunks, hashes=hashes,
            tenant=e.tenant, priority=e.priority,
            trace_id=tid, digest=digest,
            model_tag=e.model_tag, model_version=e.model_version,
        )
        handle._req = req
        handle._tokens = list(e.tokens)
        req._journal_uid = e.uid
        if not len(prompt) or key.shape != (2,):
            handle._fail(RecoveryFailed(
                f"journaled stream {e.uid} has no replayable identity "
                "(empty prompt or malformed key)"
            ))
            return handle
        # 1. Journal integrity: the committed tokens must still hash to
        # the journaled digest snapshot — a corrupted record set fails
        # typed through the divergence funnel, never replays wrong.
        if e.tokens and e.digest is not None:
            got = digest.hexdigest()
            if got != e.digest:
                _audit.record_divergence(
                    self, rid=tid, where="journal-resume",
                    expected_digest=e.digest, replayed_digest=got,
                    n_tokens=len(e.tokens),
                )
                handle._fail(DeterminismDiverged(
                    f"journaled stream {e.uid}: committed tokens no "
                    "longer match the journaled digest after "
                    f"{len(e.tokens)} tokens"
                ))
                return handle
        # 2. The outage may have outlived the deadline: typed, counted.
        if e.deadline_wall is not None and now_wall > e.deadline_wall:
            _journal_mod._T_RESUME_EXPIRED.add()
            handle._fail(DeadlineExceeded(
                f"journaled stream {e.uid} expired "
                f"{now_wall - e.deadline_wall:.1f}s before the restart"
            ))
            return handle
        # 3. Model plane: re-materialize an evicted pool model on
        # demand BEFORE replay; an unregistered or re-versioned model
        # cannot continue the stream token-identically — typed.
        if e.model_tag != DEFAULT_MODEL:
            if pool_entry is None:
                handle._fail(RecoveryFailed(
                    f"journaled stream {e.uid} is on model "
                    f"{e.model_tag!r}, which this engine's pool does "
                    "not register"
                ))
                return handle
            if pool_entry.model_version != e.model_version:
                handle._fail(MigrationIncompatible(
                    f"journaled stream {e.uid} ran model {e.model_tag!r} "
                    f"version {e.model_version!r}; this pool registers "
                    f"{pool_entry.model_version!r}"
                ))
                return handle
            self.model_pool._touch(e.model_tag)
            if not pool_entry.ready:
                self._materialize_wanted[e.model_tag] = None
        self.scheduler.push(req)
        _T_REQUESTS.add()
        _journal_mod._T_RESUMED.add()
        self._event(
            "serve.resumed_cold", req,
            uid=e.uid, n_tokens=len(e.tokens),
            n_prompt=len(e.prompt), model=e.model_tag,
        )
        return handle

    # ------------------------------------------------------------------
    # Introspection

    def stats(self) -> dict:
        """Host-side serving stats (TTFT percentiles, sustained decode,
        lifecycle counts, prefix-cache effectiveness).

        ``block_utilization`` is PHYSICAL: a page five streams share is
        one page of HBM and counts once (the refcounted allocator's
        ``utilization()`` — the same rule behind the ``serve.block_util``
        gauge)."""
        out = {
            "health": self._health.value,
            "requests": self._next_rid,
            "running": self._n_running(),
            "waiting": len(self.scheduler),
            "ticks": self._tick_no,
            "decode_tokens": self._decode_tokens,
            "decode_s": round(self._decode_s, 4),
            "block_utilization": round(self.allocator.utilization(), 4),
            "shed": self._n_shed,
            "expired": self._n_expired,
            "cancelled": self._n_cancelled,
            "recoveries": self._n_recoveries,
            "preempted": self._n_preempted,
            "preemptions_swap": self._n_preempt_swap,
            "preemptions_replay": self._n_preempt_replay,
            "swapped_pages": self.allocator.num_swapped,
            "role": self.role,
            "migrations_out": self._n_migrated_out,
            "migrations_in": self._n_migrated_in,
        }
        if self.prefix is not None:
            out["prefix_cached_pages"] = len(self.prefix)
            out["prefix_hits"] = self.prefix.hits
            out["prefix_hit_tokens"] = self.prefix.hit_tokens
            out["prefix_evictions"] = self.prefix.evictions
            out["cow_copies"] = self._n_cow
        if self._auditor is not None:
            out["audit_checked"] = self._auditor.checked
            out["audit_divergences"] = self._auditor.divergences
            out["audit_pending"] = self._auditor.backlog()
            out["audit_dropped"] = self._auditor.dropped
            out["audit_aborted"] = self._auditor.aborted
        if self._diverging:
            out["diverging"] = True
        if self.model_pool is not None:
            out["models"] = self.model_pool.stats()
            out["forks"] = self._n_forks
        elif self._n_forks:
            out["forks"] = self._n_forks
        if self._journal is not None:
            out["journal"] = self._journal.stats()
        if self._decode_s > 0:
            out["decode_tokens_per_s"] = round(
                self._decode_tokens / self._decode_s, 1
            )
        # Latency percentiles come from the per-engine telemetry
        # histograms (the ad-hoc bounded lists they replaced could not
        # be shared with the trace/export layer): exact counts, ~33%
        # bucket resolution, O(1) state however long the engine lives.
        if self._h_ttft.count:
            out["ttft_p50_s"] = round(self._h_ttft.percentile(50), 4)
            out["ttft_p95_s"] = round(self._h_ttft.percentile(95), 4)
        if self._h_tpot.count:
            out["tpot_p50_s"] = round(self._h_tpot.percentile(50), 6)
            out["tpot_p95_s"] = round(self._h_tpot.percentile(95), 6)
        if self._h_queue_wait.count:
            out["queue_wait_p95_s"] = round(
                self._h_queue_wait.percentile(95), 4
            )
        if self._h_outage.count:
            out["preempt_outage_p95_s"] = round(
                self._h_outage.percentile(95), 4
            )
        return out
