"""Device-side paged KV cache: page pools + the prompt scatter.

The pool is the model family's contiguous cache with the sequence axis cut
into pages: ``{"k","v"}: (L, num_blocks, block_size, Hkv, Dh)``.  Shapes
and dtype are probed from the family's own ``init_cache`` via
``jax.eval_shape`` — zero model coupling, so any family implementing the
cache protocol (llama, gpt2, future ones) pages identically.

Four device programs live here:

* :func:`init_paged_cache` — allocate the zeroed pool.
* :func:`write_prompt` — scatter a *contiguous* prefill cache (what the
  family's unchanged ``forward_cached`` produced for the padded prompt)
  into a slot's pages.  Pad positions (``>= length``) and positions past
  the table are steered into the trash page.  Jitted per prompt bucket;
  the pool is donated so the scatter updates in place on TPU.  (The
  engine's chunked prefill writes through ``forward_paged``'s own
  scatter instead — same steering rule,
  :func:`~torchdistx_tpu.ops.attention.paged_write_index` — so prompt
  KV lands page by page as each chunk computes; ``write_prompt`` remains
  the one-shot contiguous path.)
* :func:`copy_pages` — duplicate one physical page across every layer of
  both pools: the **copy-on-write** primitive of the prefix cache.  A
  stream about to write into a page whose refcount is > 1 (shared with
  the prefix index or another stream) gets its own copy first, so shared
  history is immutable.
* :func:`swap_out_pages` / :func:`swap_in_pages` — the **swap-to-host**
  preemption primitive (QoS): a preempted stream's pages gather to a
  host buffer (read-only — a failed copy damages nothing) and later
  scatter back into freshly-allocated pages.  Indices pad to
  power-of-two buckets so compiles stay bounded; pad rows steer into
  the trash page, safe by construction.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import perf as _perf
from .blocks import TRASH_BLOCK
from .lifecycle import MigrationIncompatible

__all__ = [
    "copy_pages",
    "fresh_pool",
    "init_paged_cache",
    "pool_geometry",
    "swap_in_pages",
    "swap_out_pages",
    "write_prompt",
]


def init_paged_cache(model, cfg, num_blocks: int, block_size: int):
    """Zeroed page pool ``{"k","v"}: (L, NB, bs, Hkv, Dh)`` for ``model``.

    Dims/dtype come from ``jax.eval_shape(model.init_cache, ...)`` — no
    allocation happens during the probe.
    """
    proto = jax.eval_shape(lambda: model.init_cache(cfg, 1, 1))

    def page(leaf):
        n_layers, _, _, heads, head_dim = leaf.shape
        return jnp.zeros(
            (n_layers, num_blocks, block_size, heads, head_dim),
            dtype=leaf.dtype,
        )

    try:
        return jax.tree.map(page, proto)
    except Exception as err:
        # The pool allocation is the single biggest HBM bite the serving
        # stack takes; a RESOURCE_EXHAUSTED here must carry the ledger
        # (what already holds the device) into the flight record.
        if _perf.is_oom(err):
            _perf.oom_dump(
                "device_oom", site="cache.init_paged_cache",
                num_blocks=num_blocks, block_size=block_size,
                error=f"{type(err).__name__}: {err}",
            )
        raise


def fresh_pool(paged):
    """A zeroed pool with ``paged``'s shapes/dtypes — without re-probing
    the model.

    Built from shape/dtype metadata only, so it works even when
    ``paged``'s buffers were consumed by a failed donated call
    (``is_deleted()`` leaves still carry their aval).  This is the
    recovery supervisor's rebuild primitive: the engine re-prefills
    every live request into the fresh pool, so zeroed is the correct
    initial state, exactly as at engine construction.
    """
    try:
        return jax.tree.map(lambda x: jnp.zeros(x.shape, x.dtype), paged)
    except Exception as err:
        if _perf.is_oom(err):
            # Recovery could not even re-carve the pool: the one OOM
            # that ends the engine — dump what held the memory.
            _perf.oom_dump(
                "device_oom", site="cache.fresh_pool",
                error=f"{type(err).__name__}: {err}",
            )
        raise


@partial(jax.jit, static_argnames=("block_size",), donate_argnums=(0,))
def write_prompt(paged, contiguous, table, length, *, block_size: int):
    """Scatter a slot's prefill KV into its pages.

    ``paged``: the pool (donated); ``contiguous``: ``{"k","v"}:
    (L, 1, P_pad, H, D)`` from the family's ``forward_cached`` prefill;
    ``table (M,)`` int32 page table (padded with trash); ``length`` the
    real prompt length (traced — one compile per ``P_pad`` bucket).
    """
    from ..ops.attention import paged_write_index

    p_pad = jax.tree.leaves(contiguous)[0].shape[2]
    pos = jnp.arange(p_pad)
    # The ONE steering rule (see paged_write_index), with the prompt's
    # shared table broadcast per position; pad positions go to trash.
    blk, off = paged_write_index(
        jnp.broadcast_to(table[None], (p_pad, table.shape[0])),
        pos, block_size,
    )
    blk = jnp.where(pos < length, blk, TRASH_BLOCK)

    def scatter(pool, cont):
        # pool (L, NB, bs, H, D); cont[:, 0] (L, P, H, D): rows land at
        # (layer, blk[p], off[p]).
        return pool.at[:, blk, off].set(cont[:, 0])

    return jax.tree.map(scatter, paged, contiguous)


def pool_geometry(paged) -> tuple:
    """Hashable per-leaf page geometry of a pool (or of a
    :func:`swap_out_pages` host buffer): the pytree structure plus each
    leaf's ``(L, block_size, Hkv, Dh, dtype)`` — everything about a page
    EXCEPT how many the pool holds.  Two pools with equal geometry can
    exchange page snapshots bit-for-bit; anything else cannot, whatever
    the byte counts happen to be.  The cross-engine migration path
    compares these before any scatter (see :func:`swap_in_pages`)."""
    leaves, treedef = jax.tree.flatten(paged)
    return (
        str(treedef),
        tuple(
            (x.shape[0],) + tuple(x.shape[2:]) + (str(x.dtype),)
            for x in leaves
        ),
    )


def _page_bucket(n: int) -> int:
    """Swap-transfer pad width: next power of two — one gather and one
    scatter compile per bucket, not per page count."""
    b = 1
    while b < n:
        b *= 2
    return b


@jax.jit
def _gather_pages(paged, idx):
    return jax.tree.map(lambda pool: pool[:, idx], paged)


@partial(jax.jit, donate_argnums=(0,))
def _scatter_pages(paged, host, idx):
    return jax.tree.map(
        lambda pool, h: pool.at[:, idx].set(h), paged, host
    )


def swap_out_pages(paged, pages):
    """Copy physical ``pages`` (every layer, both pools) to host.

    Read-only: the pool is untouched, so a failure mid-copy leaves the
    device state undamaged (the engine falls back to drop-and-replay).
    Returns a host pytree ``{"k","v"}: (L, len(pages), bs, Hkv, Dh)``
    of numpy arrays, rows in ``pages`` order."""
    n = len(pages)
    idx = np.full((_page_bucket(n),), TRASH_BLOCK, np.int32)
    idx[:n] = pages
    gathered = _gather_pages(paged, jnp.asarray(idx))
    return jax.tree.map(lambda x: np.asarray(x[:, :n]), gathered)


def swap_in_pages(paged, host, pages):
    """Scatter a :func:`swap_out_pages` buffer back into freshly
    allocated ``pages`` (the pool is donated — in place on device).
    ``len(pages)`` must equal the buffer's page count; pad rows (zeros)
    land in the trash page.

    The buffer's page geometry is validated against the pool BEFORE the
    scatter.  Same-pool swap round trips match trivially; a CROSS-pool
    import (stream migration) with a different layer count, page size,
    head shape, or dtype raises a typed, retryable
    :class:`.lifecycle.MigrationIncompatible` — never a silent
    broadcast/cast into the destination pool (and never a shape error
    surfacing from inside a donated call that already consumed it)."""
    if pool_geometry(paged) != pool_geometry(host):
        raise MigrationIncompatible(
            "page snapshot does not fit this pool: snapshot geometry "
            f"{pool_geometry(host)!r} != pool geometry "
            f"{pool_geometry(paged)!r}; fall back to a key-pinned replay"
        )
    n = len(pages)
    n_rows = jax.tree.leaves(host)[0].shape[1]
    if n != n_rows:
        raise MigrationIncompatible(
            f"page snapshot holds {n_rows} page(s) but {n} destination "
            "page(s) were allocated"
        )
    bucket = _page_bucket(n)
    idx = np.full((bucket,), TRASH_BLOCK, np.int32)
    idx[:n] = pages

    def pad(h):
        out = np.zeros((h.shape[0], bucket) + h.shape[2:], h.dtype)
        out[:, :n] = h
        return out

    return _scatter_pages(
        paged, jax.tree.map(pad, host), jnp.asarray(idx)
    )


@partial(jax.jit, donate_argnums=(0,))
def copy_pages(paged, src, dst):
    """Copy physical page ``src`` onto ``dst`` in every layer of both
    pools (the prefix cache's copy-on-write).  ``src``/``dst`` are
    traced scalars — one compile serves every copy.  The pool is donated:
    the copy happens in place on device, no host round-trip."""

    def cp(pool):
        row = jax.lax.dynamic_index_in_dim(pool, src, axis=1, keepdims=False)
        return jax.lax.dynamic_update_index_in_dim(pool, row, dst, axis=1)

    return jax.tree.map(cp, paged)
