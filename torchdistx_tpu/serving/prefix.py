"""Prefix cache: a refcounted index of full KV pages by token content.

Real serving traffic is dominated by shared prefixes — the same system
prompt in front of millions of requests, the same long document quizzed
repeatedly.  The paged cache (:mod:`.cache`) already stores KV at page
granularity; this module adds the vLLM-style observation (Kwon et al.,
SOSP '23) that a page's KV content is a pure function of **every token
up to and including its own** — so a page can be named by the chained
hash of its token history and *shared* between requests instead of
recomputed.

The index maps ``chained page hash → physical page``:

* ``h_i = H(h_{i-1} || tokens[i*bs : (i+1)*bs])`` — chaining makes the
  hash cover the page's full history, so two prompts that diverge
  anywhere before a page can never collide into sharing it;
* only **full** pages are indexed — a partially-filled page's KV would
  change as more tokens arrive, invalidating its name;
* the index holds ONE allocator reference per indexed page
  (:meth:`~.blocks.BlockAllocator.share`); every request that maps a
  cached page holds its own.  A page whose only reference is the
  index's is an *unreferenced cached prefix* — reclaimable;
* eviction is **LRU under allocator pressure** (:meth:`PrefixIndex.evict`):
  the engine reclaims least-recently-matched pages only when an
  admission or copy-on-write needs pages the free list cannot supply,
  so a populated cache can never cause an admission stall that an empty
  cache would not.

Writes into shared pages are the engine's problem (copy-on-write before
the write — see ``Engine`` in :mod:`.engine`); the index only promises
that everything it maps is refcounted and content-addressed.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import List, Optional

from .blocks import BlockAllocator

__all__ = ["PrefixIndex", "page_hashes"]


def page_hashes(
    tokens, block_size: int, namespace: bytes = b""
) -> List[bytes]:
    """Chained content hashes of every FULL page of ``tokens``.

    ``tokens`` is any int sequence; result ``i`` names the page holding
    ``tokens[i*bs:(i+1)*bs]`` *and* its entire history (the chain).  A
    trailing partial page gets no hash — its KV is still mutable.

    ``namespace`` seeds the chain.  A page's KV is a function of the
    tokens AND the model that computed it: on a multi-model engine
    (:mod:`.modelpool`) the same prompt under two models must never
    share pages, so the engine seeds the chain with the model tag and
    the first hash already diverges.  The default (empty) namespace is
    the engine's own model — single-model hashes are unchanged.
    """
    import numpy as np

    tok = np.ascontiguousarray(np.asarray(tokens, dtype=np.int32))
    out: List[bytes] = []
    prev = namespace
    for i in range(len(tok) // block_size):
        h = hashlib.blake2b(digest_size=16)
        h.update(prev)
        h.update(tok[i * block_size : (i + 1) * block_size].tobytes())
        prev = h.digest()
        out.append(prev)
    return out


class PrefixIndex:
    """LRU map ``chained page hash → physical page``, refcounted through
    the :class:`~.blocks.BlockAllocator`.

    Host-side only; O(pages) per operation, no device work.  The engine
    owns the device side (mapping matched pages into block tables,
    copy-on-write, and the actual eviction trigger).
    """

    def __init__(self, block_size: int):
        self.block_size = block_size
        # hash -> page, in LRU order (least-recently-matched first).
        self._pages: "OrderedDict[bytes, int]" = OrderedDict()
        self.hits = 0
        self.hit_tokens = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._pages)

    def match(self, hashes: List[bytes]) -> List[int]:
        """Pages of the longest indexed prefix of ``hashes`` (possibly
        empty).  Chained hashes make the walk prefix-closed: the first
        miss ends the match.  Matched entries are LRU-touched; the
        caller must :meth:`~.blocks.BlockAllocator.share` the result
        before relying on it."""
        out: List[int] = []
        for h in hashes:
            page = self._pages.get(h)
            if page is None:
                break
            self._pages.move_to_end(h)
            out.append(page)
        return out

    def probe(self, hashes: List[bytes]) -> int:
        """Length (in pages) of the longest indexed prefix — no LRU
        touch, no refcounts taken.  For estimates (admission TTFT)."""
        n = 0
        for h in hashes:
            if h not in self._pages:
                break
            n += 1
        return n

    def register(
        self, hashes: List[bytes], pages: List[int], allocator: BlockAllocator
    ) -> int:
        """Index ``pages[i]`` under ``hashes[i]``, taking one allocator
        reference per newly-indexed page.  A hash already present keeps
        its existing page (two requests racing the same prompt must
        converge on one copy, not leak two).  Returns pages added."""
        added = 0
        for h, page in zip(hashes, pages):
            if h in self._pages:
                self._pages.move_to_end(h)
                continue
            allocator.share([page])
            self._pages[h] = page
            added += 1
        return added

    def evict(self, n: int, allocator: BlockAllocator) -> int:
        """Free up to ``n`` *unreferenced* cached pages (refcount 1 — the
        index's own), least-recently-matched first.  Pages still mapped
        by live requests are skipped, not stalled on.  Returns pages
        actually freed."""
        if n <= 0:
            return 0
        freed = 0
        for h, page in list(self._pages.items()):
            if freed >= n:
                break
            if allocator.refcount(page) != 1:
                continue  # a live request still maps it
            allocator.free([page])
            del self._pages[h]
            freed += 1
        self.evictions += freed
        return freed

    def release(self, allocator: BlockAllocator) -> None:
        """Drop every index reference (engine close/drain): cached pages
        not mapped by a request return to the free list."""
        for page in self._pages.values():
            allocator.free([page])
        self._pages.clear()

    def clear(self) -> None:
        """Forget everything WITHOUT touching the allocator — the
        recovery path, where ``allocator.reset()`` already reclaimed the
        map and the pool content is gone."""
        self._pages.clear()

    def check(self, allocator: BlockAllocator) -> Optional[str]:
        """Refcount-drift check (chaos soak): every indexed page must be
        in use with at least the index's own reference.  Returns a
        description of the first violation, or None."""
        for h, page in self._pages.items():
            rc = allocator.refcount(page)
            if rc < 1:
                return f"indexed page {page} has refcount {rc} (stale index)"
        return None
