"""The deferred-init op tape: a bidirectional op graph with mutation semantics.

Rebuild of the reference's recorder (/root/reference/src/cc/torchdistx/
deferred_init.cc:102-710): ``Op`` (recorded call + deep-copied args +
thread-local state), ``OpNode`` (chronological ``op_nr``, dependency edges,
output-storage sets for aliasing, external-tensor version guards),
``TensorRecord`` (per-fake side data naming the producing (node, index)), and
the materializer's call-stack builder (last-in-place-op horizon search +
transitive-closure collection + chronological sort, deferred_init.cc:529-621).

Differences from the reference, by design:

* Mutation tracking uses operator *schemas* (``alias_info.is_write``) instead
  of the reference's name heuristics — the schema is ground truth here.
* Aliasing is tracked through the fakes' **meta shadow storages** (meta
  tensors have real storage identity but no data), which is exactly the
  role the reference's output-storage sets play (deferred_init.cc:416-428).
* Replay caching is per-node (``Op::materialize`` runs once,
  deferred_init.cc:255-271).  Caches mutate in place on in-place replays,
  exactly like the reference's cached outputs; see materialize.py for the
  union-replay discipline that keeps multi-target materialization
  order-consistent.
"""

from __future__ import annotations

import copy
import itertools
import threading
import weakref
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import torch
import torch.utils._pytree as pytree

from . import _native
from . import telemetry as _telemetry

_tls = threading.local()

# Counters bound once at import (counter() lookup takes a registry lock;
# record_op is the hot path).  Counter.add is one lock round-trip — ~2% of
# a recorded op's cost — and exact under the concurrent recorders the
# materializer's build pool can drive.
_T_OPS = _telemetry.counter("tape.ops_recorded")
_T_MUTATIONS = _telemetry.counter("tape.mutation_ops")
_T_VIEWS = _telemetry.counter("tape.view_ops")
# High-water mark, not current depth: tape stacks are thread-local, so a
# last-writer-wins "current" gauge is meaningless once the materializer's
# build pool records on several threads at once.  The peak is well-defined
# process-wide and is the number that matters (unexpectedly deep nesting).
_T_DEPTH_PEAK = _telemetry.gauge("tape.depth_peak")


def _note_depth(depth: int) -> None:
    peak = _T_DEPTH_PEAK.value
    if peak is None or depth > peak:
        _T_DEPTH_PEAK.set(depth)

# Process-wide chronological op counter (the reference's is thread-local,
# deferred_init.cc:671).  Global so that op_nr is unique across tapes: a
# module may be assembled from several deferred_init calls, and replay
# caches / PRNG streams are keyed by op_nr.
_op_counter = itertools.count()

# The JAX materializer derives RNG streams from (tape ordinal, *relative*
# op number ``op_nr - node.base_nr``) — never from absolute op_nrs, which
# depend on how many tapes ran earlier in the process.  Relative numbering
# makes the same architecture materialize to the same values in any process
# AND keeps the emitted HLO byte-stable (→ compilation-cache hits); see
# materialize.py's RNG note.


class _PyOutputRef:
    """Marker replacing a fake-tensor argument inside a recorded arg stack.

    Analog of the reference's dependency ``OpOutputDescriptor``
    (deferred_init.cc:106-154): names the producing node + output index, and
    holds the node strongly (keep-alive, like TensorRecord's view refs).

    The native core defines the same type in C (src/cc/tdx_core/stack.cc);
    ``OutputRef`` below binds to whichever is live so isinstance checks see
    one class everywhere.
    """

    __slots__ = ("node", "index")

    def __init__(self, node: "OpNode", index: int):
        self.node = node
        self.index = index

    def __repr__(self):
        return f"OutputRef(op_nr={self.node.op_nr}, index={self.index})"


_stack_mod = _native.stack_ops()
OutputRef = (
    _stack_mod.OutputRef
    if _stack_mod is not None and hasattr(_stack_mod, "OutputRef")
    else _PyOutputRef
)


@dataclass
class ExternalTensorGuard:
    """Version guard for a real (non-fake) tensor captured by the tape.

    Analog of deferred_init.cc:394,480-489/639-666: replaying an op whose
    external input has since been mutated would silently produce different
    values, so record its version counter and verify at replay.
    """

    tensor: torch.Tensor
    version: int

    def check(self) -> None:
        if self.tensor.is_inference():
            raise RuntimeError(
                "Cannot materialize: a recorded operation captured an "
                "inference-mode tensor."
            )
        if self.tensor._version != self.version:
            raise RuntimeError(
                "Cannot materialize: an external tensor captured by a "
                "recorded operation was mutated after recording "
                f"(version {self.tensor._version} != {self.version})."
            )


@dataclass
class TensorRecord:
    """Per-fake-tensor side data: who produced it (deferred_init.cc:106-154)."""

    node: "OpNode"
    index: int


class Op:
    """A recorded operation: callable + deep-copied boxed arguments + TLS.

    Analog of ``Op`` (deferred_init.cc:102-300).  Args are deep-copied at
    record time (copyStack, deferred_init.cc:69-100) with fake tensors
    replaced by :class:`OutputRef` edges and real tensors guarded.  Replay
    runs once and caches outputs (deferred_init.cc:255-271) under the grad
    mode captured at record time (the ThreadLocalState analog,
    deferred_init.cc:211-215).
    """

    __slots__ = (
        "name",
        "func",
        "args",
        "kwargs",
        "grad_enabled",
        "guards",
        "replayed",
        "outputs",
    )

    def __init__(self, name, func, args, kwargs, grad_enabled, guards):
        self.name = name
        self.func = func
        self.args = args
        self.kwargs = kwargs
        self.grad_enabled = grad_enabled
        self.guards: List[ExternalTensorGuard] = guards
        self.replayed = False
        self.outputs: Optional[List[Any]] = None


class OpNode:
    """Graph node — analog of ``OpNode`` (deferred_init.cc:311-710)."""

    __slots__ = (
        "op_nr",
        "op",
        "dependents",
        "out_storages",
        "out_metas",
        "write_storages",
        "pinned_storages",
        "mutated_args",
        "num_outputs",
        "materialized_pyobjs",
        "native_graph",
        "base_nr",
        "__weakref__",
    )

    def __init__(self, op_nr: int, op: Op):
        self.op_nr = op_nr
        self.op = op
        # Dependency edges live in op.args/kwargs as OutputRef markers (which
        # hold producer nodes strongly) — the analog of deferred_init.cc:390's
        # dependency descriptors, without a duplicate edge list.
        # Back-edges to later ops touching any of this node's storages — the
        # analog of the reference's `dependents_` (deferred_init.cc:397).
        # Strong refs (the GC collects cycles) which also provides the
        # view-record keep-alive the reference implements separately
        # (ensureViewsKeptAlive, deferred_init.cc:430-461): a later in-place
        # op on a view stays reachable from the base's producing node even if
        # the view object is dropped.
        self.dependents: List["OpNode"] = []
        self.out_storages: List[int] = []
        # Meta shadows of the fake outputs: shape/stride/offset/dtype ground
        # truth for the functional (JAX) replay engine's strided
        # gather/scatter resolution of views and in-place writes.
        self.out_metas: List[Optional[torch.Tensor]] = []
        # Positional-arg indices the op writes (schema alias_info) — which
        # layouts the functional engine scatters results through.
        self.mutated_args: List[int] = []
        self.write_storages: List[int] = []
        # Keep the meta storage objects alive: storage keys are raw
        # StorageImpl addresses, and a freed address could be reused by an
        # unrelated tensor, creating false alias edges.  The reference pins
        # refcounted c10::Storage objects the same way
        # (deferred_init.cc:387,416-428).
        self.pinned_storages: List[Any] = []
        self.num_outputs = 0
        # Python-identity cache: materializing the same output twice returns
        # the same object (the reference's pyobj reuse, _C/deferred_init.cc:79-93).
        self.materialized_pyobjs: Dict[int, Any] = {}
        # Native-core graph this node is registered in (None = Python path).
        # Shared strong handle: the graph must outlive every node that may
        # be materialized through it, long after the tape is popped.
        self.native_graph = None
        # First op_nr of this node's tape — RNG streams key on the
        # tape-relative number ``op_nr - base_nr`` (see module docstring).
        self.base_nr = 0

    def __repr__(self):
        return f"OpNode({self.op_nr}: {self.op.name})"


class Tape:
    """The active recording — owns the op counter and the alias index.

    The reference keeps a thread-local ``op_nr`` counter
    (deferred_init.cc:671) and discovers in-place dependents by walking
    per-node weak back-edges (getLastInPlaceOpNode, deferred_init.cc:540-578).
    Here the tape keeps a storage→[(op_nr, node)] writer index used at record
    time to install those back-edges; materialization then navigates the node
    graph alone, so it works long after the tape is gone.
    """

    def __init__(self):
        # storage key -> list of (op_nr, weakref to node) that WROTE it.
        # Maintained only on the Python path: with a native Recorder the
        # writer index lives in C++ (and is exported on downgrade).
        self.writers: Dict[int, List[Tuple[int, weakref.ref]]] = {}
        self.base_nr: Optional[int] = None  # first recorded op_nr
        # Native recorder: the writer index, dep/dependent edges, and
        # call-stack traversal in C++ (src/cc/tdx_core/stack.cc Recorder).
        # Per-tape: storage keys are raw addresses whose lifetime is only
        # pinned within a tape, so a process-global graph could see reused
        # addresses as false aliases.
        s = _native.stack_ops()
        self.native_graph = (
            s.Recorder() if s is not None and hasattr(s, "Recorder") else None
        )

    def disable_native(self) -> None:
        """Hand the graph back to the Python path (e.g. a cross-tape
        dependency appeared — its producer lives in another tape's graph, so
        this graph's traversals would be incomplete).  The recorder installs
        its dependent edges into the Python nodes and exports its writer
        index so the Python ``note_write`` keeps linking correctly."""
        if self.native_graph is not None:
            exported = self.native_graph.downgrade()
            for key, nodes in exported.items():
                entries = self.writers.setdefault(key, [])
                entries.extend(
                    (n.op_nr, weakref.ref(n)) for n in nodes
                )
            self.native_graph = None

    def note_write(self, storage_key: int, node: OpNode) -> None:
        entries = self.writers.setdefault(storage_key, [])
        # Link every earlier toucher of this storage to the new writer —
        # materialization (possibly long after this tape is gone) navigates
        # the node graph alone, like the reference's dependents_ walk
        # (deferred_init.cc:540-578).
        for _, ref in entries:
            prev = ref()
            if prev is not None and prev is not node:
                prev.dependents.append(node)
        entries.append((node.op_nr, weakref.ref(node)))


def current_tape() -> Optional[Tape]:
    return getattr(_tls, "tape", None)


def push_tape() -> Tape:
    tape = Tape()
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(tape)
    _tls.tape = tape
    _note_depth(len(stack))
    return tape


def pop_tape() -> None:
    stack = _tls.stack
    stack.pop()
    _tls.tape = stack[-1] if stack else None


def _torch_allocatable(device: torch.device) -> bool:
    if device.type in ("cpu", "meta"):
        return True
    if device.type == "cuda":
        return torch.cuda.is_available()
    if device.type == "mps":
        return torch.backends.mps.is_available()
    return False


def _storage_key(meta: torch.Tensor) -> int:
    return meta.untyped_storage()._cdata


def _mutated_arg_indices(func) -> List[int]:
    """Schema-arg indices the op writes to, from the schema alias info.

    Indices address ``schema.arguments`` — kwarg-only args (out-variant
    buffers like ``aminmax.out``'s min/max) get indices past ``len(args)``
    and are resolved by :func:`arg_at_schema_pos`.
    """
    out = []
    try:
        schema = func._schema
    except AttributeError:
        return out
    for i, arg in enumerate(schema.arguments):
        if arg.alias_info is not None and arg.alias_info.is_write:
            out.append(i)
    return out


def arg_at_schema_pos(func, args, kwargs, pos):
    """The value bound to schema argument ``pos``, positional or kwarg-only."""
    if pos < len(args):
        return args[pos]
    try:
        name = func._schema.arguments[pos].name
    except (AttributeError, IndexError):
        return None
    return kwargs.get(name)


# Per-func cache of (name string, mutated schema-arg indices, is-view):
# schemas are immutable, and str(OpOverload) + the alias_info walk cost
# ~25ms of a GPT-2-XL record (1743 ops) when recomputed per op.
_SCHEMA_CACHE: Dict[Any, Tuple[str, Tuple[int, ...], bool]] = {}


def _is_view_schema(func, mutated: Tuple[int, ...]) -> bool:
    # Same ground truth as materialize._is_view_node: nothing written and
    # every return aliases an input.
    if mutated:
        return False
    try:
        returns = func._schema.returns
    except AttributeError:
        return False
    return bool(returns) and all(r.alias_info is not None for r in returns)


def _schema_info(func) -> Tuple[str, Tuple[int, ...], bool]:
    info = _SCHEMA_CACHE.get(func)
    if info is None:
        mutated = tuple(_mutated_arg_indices(func))
        info = (str(func), mutated, _is_view_schema(func, mutated))
        _SCHEMA_CACHE[func] = info
    return info


# Lazily-bound canonical record protocol (deferred_init/fake import this
# module at their top level, so the reverse imports must wait until first
# record) — bound once, not per op: record_op is the hot path.
_PROTO = None


def _record_protocol():
    global _PROTO
    if _PROTO is None:
        from .deferred_init import _SLOT
        from .fake import FakeTensor, _convert_tensors, _StrictFallback

        _PROTO = (_SLOT, FakeTensor, _convert_tensors, _StrictFallback)
    return _PROTO


def record_op(
    tape: Tape,
    func,
    args: tuple,
    kwargs: dict,
    fake_outputs: list,
) -> OpNode:
    """Record one op — analog of ``recordOp`` (deferred_init.cc:673-710).

    ``fake_outputs`` are the fake tensors the op produced (or mutated).
    Fake args become dependency edges and are *dropped* from the preserved
    stack (replaced by :class:`OutputRef`), breaking reference cycles the
    same way attachDependencies does (deferred_init.cc:463-495).  Real
    tensors are kept with version guards; all other leaves are deep-copied
    (copyStack, deferred_init.cc:69-100).

    Hot path: argument preservation, the writer index, and dependency/
    dependent bookkeeping run in the native core
    (src/cc/tdx_core/stack.cc: ``record_preserve`` + ``Recorder.note_op``);
    the Python implementation below is the executable spec and the fallback
    (``TDX_DISABLE_NATIVE=1``, exotic containers, cross-tape edges).
    """
    _SLOT, FakeTensor, _convert_tensors, _StrictFallback = _record_protocol()

    def is_fake(a):
        return isinstance(a, FakeTensor)

    guards: List[ExternalTensorGuard] = []
    dep_nodes: List[OpNode] = []

    def preserve(a):
        if is_fake(a):
            rec = a._slots.get(_SLOT)
            if rec is None:
                raise RuntimeError(
                    "Cannot record an operation on a fake tensor that was "
                    "created outside of a deferred-init context."
                )
            dep_nodes.append(rec.node)
            return OutputRef(rec.node, rec.index)
        if isinstance(a, torch.Tensor):
            guards.append(ExternalTensorGuard(a, a._version))
            return a
        if isinstance(a, (int, float, bool, str, bytes, complex, type(None),
                          torch.dtype, torch.device, torch.layout,
                          torch.memory_format, torch.Generator)):
            return a
        # Containers are flattened by tree_map; anything else must be
        # deep-copyable — the immutability validation analog
        # (deferred_init.cc:227-253).
        try:
            return copy.deepcopy(a)
        except Exception as e:  # pragma: no cover
            raise RuntimeError(
                f"Cannot record op '{func}': argument of type "
                f"{type(a).__name__} is not preservable."
            ) from e

    # Native fast path: the whole preserve walk (container recursion, fake→
    # OutputRef substitution, guard snapshots, immutable-domain validation)
    # in C; full-domain pytree walk (which also deep-copies unknown
    # preservable leaves) when validation signals out.
    s = _stack_mod
    p_args = None
    if s is not None and hasattr(s, "record_preserve"):
        try:
            p_args, p_kwargs, dep_nodes, guards = s.record_preserve(
                tuple(args), dict(kwargs), FakeTensor, _SLOT,
                ExternalTensorGuard,
            )
        except s.Fallback:
            p_args = None
    if p_args is None:
        guards.clear()
        dep_nodes.clear()
        try:
            p_args, p_kwargs = _convert_tensors(
                (tuple(args), dict(kwargs)), preserve, strict=True
            )
        except _StrictFallback:
            # The aborted native walk already ran `preserve` on earlier
            # tensor leaves; drop those side effects before the full retry
            # or every guard / dependency edge would be recorded twice.
            guards.clear()
            dep_nodes.clear()
            p_args, p_kwargs = pytree.tree_map(
                preserve, (tuple(args), dict(kwargs))
            )

    name, mutated, is_view = _schema_info(func)
    _T_OPS.add()
    if mutated:
        _T_MUTATIONS.add()
    elif is_view:
        _T_VIEWS.add()
    op = Op(
        name=name,
        func=func,
        args=p_args,
        kwargs=p_kwargs,
        grad_enabled=torch.is_grad_enabled(),
        guards=guards,
    )
    node = OpNode(next(_op_counter), op)
    if tape.base_nr is None:
        tape.base_nr = node.op_nr
    node.base_nr = tape.base_nr
    node.num_outputs = len(fake_outputs)

    # Output storages for aliasing checks (recordStorages,
    # deferred_init.cc:416-428) via the meta shadows.
    for out in fake_outputs:
        if out is not None:
            node.out_storages.append(_storage_key(out._meta))
            node.out_metas.append(out._meta)
            node.pinned_storages.append(out._meta.untyped_storage())
        else:
            node.out_metas.append(None)

    # Storages the op WROTE: schema-mutated args + all outputs (an output
    # freshly created or aliasing a mutated arg both count as written).
    node.mutated_args = list(mutated)
    for i in node.mutated_args:
        a = arg_at_schema_pos(func, args, kwargs, i)
        if is_fake(a):
            node.write_storages.append(_storage_key(a._meta))
            node.pinned_storages.append(a._meta.untyped_storage())
    node.write_storages.extend(node.out_storages)
    write_keys = list(set(node.write_storages))

    # Writer index + dependent edges: native recorder when live (one C call
    # per op; cross-tape deps signal False with no side effects), Python
    # otherwise.
    g = tape.native_graph
    if g is not None:
        if g.note_op(node.op_nr, node, dep_nodes, write_keys):
            node.native_graph = g
        else:
            # Cross-tape dependency: the producer lives in another tape's
            # graph, so this graph's traversals would be incomplete.
            tape.disable_native()
            g = None
    if g is None:
        for key in write_keys:
            tape.note_write(key, node)

    # Point each fake output's record at this node (deferred_init.cc:683-710).
    for idx, out in enumerate(fake_outputs):
        if out is not None:
            out._slots[_SLOT] = TensorRecord(node, idx)
    return node


def build_call_stack(target: OpNode) -> List[OpNode]:
    """Build the chronological replay schedule for ``target``.

    Analog of ``OpNode::materialize``'s buildCallStack
    (deferred_init.cc:529-621): find the last in-place op touching any
    storage aliased with the target's outputs (the *horizon*,
    getLastInPlaceOpNode deferred_init.cc:540-578), then collect the
    transitive dependency closure plus in-place dependents within the
    horizon (collectCallStack, deferred_init.cc:580-621), sorted by
    ``op_nr``.  Self-contained on the node graph — no live tape needed.

    Uses the native core's traversal when this node was recorded into one
    (identical semantics; tests/test_native_tape.py asserts equality).
    """
    g = target.native_graph
    if g is not None:
        return g.call_stack(target.op_nr)
    horizon = target.op_nr
    for d in target.dependents:
        if d.op_nr > horizon:
            horizon = d.op_nr
    result: Dict[int, OpNode] = {}
    work: List[OpNode] = [target]
    while work:
        node = work.pop()
        if node.op_nr in result:
            continue
        result[node.op_nr] = node
        for ref in pytree.tree_iter((node.op.args, node.op.kwargs)):
            if isinstance(ref, OutputRef):
                work.append(ref.node)
        for d in node.dependents:
            if d.op_nr <= horizon:
                work.append(d)
    return [result[nr] for nr in sorted(result)]


def replay_node(node: OpNode) -> List[Any]:
    """Replay one node for real — analog of ``Op::materialize``
    (deferred_init.cc:255-271).  Idempotent: runs once and caches outputs."""
    op = node.op
    if op.replayed:
        return op.outputs  # type: ignore[return-value]
    for guard in op.guards:
        guard.check()

    def resolve(a):
        if isinstance(a, OutputRef):
            outs = replay_node(a.node)
            return outs[a.index]
        return a

    r_args, r_kwargs = pytree.tree_map(resolve, (op.args, op.kwargs))
    recorded_device = r_kwargs.get("device")
    if recorded_device is not None:
        override = getattr(_tls, "device_override", None)
        if override is not None:
            # Replayed factory ops carry their recorded (claimed) device; a
            # replay-time override redirects them (e.g. a fake "tpu:0" claim
            # replayed on host CPU before transfer, or test-time redirection).
            r_kwargs["device"] = override
        elif not _torch_allocatable(torch.device(recorded_device)):
            # Claimed devices torch has no backend for (tpu/xla, or cuda on a
            # CUDA-less host) replay on host CPU by default; the JAX
            # materializer is the native route onto the actual device.
            r_kwargs["device"] = torch.device("cpu")
    with torch.set_grad_enabled(op.grad_enabled):
        out = op.func(*r_args, **r_kwargs)
    if isinstance(out, (tuple, list)):
        outputs = list(out)
    else:
        outputs = [out]
    op.outputs = outputs
    op.replayed = True
    return outputs
