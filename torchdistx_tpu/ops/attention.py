"""Attention ops: reference implementation + implementation dispatcher.

The reference framework (/root/reference) contains no attention code at all —
its models come from torchvision/HF (BASELINE configs).  This framework ships
its own TPU-native model stack (:mod:`torchdistx_tpu.models`), so attention is
a first-class op with three interchangeable implementations:

* ``"jnp"``     — pure jax.numpy reference (runs anywhere, XLA-fused);
* ``"pallas"``  — fused flash-attention Pallas TPU kernel
  (:mod:`torchdistx_tpu.ops.pallas.flash_attention`): O(seq) memory, tiled
  for the MXU, online softmax;
* ``"ring"``    — ring attention over a sequence-parallel mesh axis
  (:mod:`torchdistx_tpu.parallel.ring_attention`): blockwise attention with
  K/V rotating over ICI via ``ppermute``, for sequences too long for one
  chip's HBM.

``attention()`` picks automatically: ring iff a sequence-parallel mesh axis
is given, else pallas on TPU, else jnp.
"""

from __future__ import annotations

import functools
from typing import Optional

__all__ = [
    "attention",
    "cached_attention",
    "mha_reference",
    "paged_attention",
    "paged_write_index",
]


def _neg_inf(dtype):
    import jax.numpy as jnp

    return jnp.finfo(dtype).min


def mha_reference(q, k, v, *, causal: bool = True, segment_ids=None):
    """Reference multi-head attention (GQA-aware) in plain jax.numpy.

    Shapes: q ``(B, Sq, Hq, D)``; k/v ``(B, Sk, Hkv, D)`` with
    ``Hq % Hkv == 0`` (grouped-query attention).  Returns ``(B, Sq, Hq, D)``.
    Softmax is computed in float32 regardless of input dtype (bfloat16-safe).
    """
    import jax.numpy as jnp

    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    groups = hq // hkv
    qg = q.reshape(b, sq, hkv, groups, d)
    scale = 1.0 / (d**0.5)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32) * scale
    if causal:
        # Positions are global: with sequence parallelism the caller passes
        # pre-offset index vectors via segment_ids=None + explicit masks in
        # ring_attention; here q and k start at 0.
        qi = jnp.arange(sq)[:, None]
        ki = jnp.arange(sk)[None, :]
        mask = qi >= ki
        logits = jnp.where(mask[None, None, None], logits, _neg_inf(jnp.float32))
    if segment_ids is not None:
        q_seg, k_seg = segment_ids
        mask = q_seg[:, None, None, :, None] == k_seg[:, None, None, None, :]
        logits = jnp.where(mask, logits, _neg_inf(jnp.float32))
    probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v)
    return out.reshape(b, sq, hq, d)


def _attend_cached(q, k_cache, v_cache, valid):
    """Shared decode-attention math: GQA einsum + f32 softmax over a cache.

    ``valid`` broadcasts against the f32 logits ``(B, T, Hkv, G, Sk)``.
    One definition for the contiguous (:func:`cached_attention`) and paged
    (:func:`paged_attention`) cache layouts — identical contraction and
    masking ops, so serving logits cannot drift from the generate path.
    """
    import jax.numpy as jnp

    b, t, hq, d = q.shape
    hkv = k_cache.shape[2]
    groups = hq // hkv
    qg = q.reshape(b, t, hkv, groups, d)
    scale = 1.0 / (d**0.5)
    logits = (
        jnp.einsum("bqhgd,bkhd->bqhgk", qg, k_cache).astype(jnp.float32)
        * scale
    )
    logits = jnp.where(valid, logits, _neg_inf(jnp.float32))
    probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bqhgk,bkhd->bqhgd", probs.astype(v_cache.dtype), v_cache)
    return out.reshape(b, t, hq, d)


def cached_attention(q, k_cache, v_cache, pos):
    """Decode-time attention against a static-shape KV cache.

    q ``(B, T, Hq, D)`` holds queries for positions ``pos .. pos+T-1``;
    k/v caches ``(B, Smax, Hkv, D)`` are valid up to ``pos+T``.  Key ``j``
    attends to query ``i`` iff ``j <= pos + i`` (global causal mask over the
    cache; invalid tail masked out).  Static shapes → one compiled decode
    step regardless of position.
    """
    import jax.numpy as jnp

    t = q.shape[1]
    smax = k_cache.shape[1]
    valid = jnp.arange(smax)[None, :] <= (pos + jnp.arange(t))[:, None]
    return _attend_cached(
        q, k_cache, v_cache, valid[None, :, None, None, :]
    )


def paged_write_index(block_tables, positions, block_size):
    """Page/offset each token writes to: ``(blk, off)``, int32, shaped
    like ``positions``.

    ``positions`` may be ``(B,)`` — each slot's ONE decode token — or
    ``(B, T)`` — a chunked-prefill block of ``T`` suffix tokens per
    slot, positions ``start_b .. start_b+T-1`` (the chunked ``write_prompt``
    scatter rides this same rule).

    The ONE definition of the paged cache's write-steering rule, shared
    by every family's ``forward_paged`` (llama, gpt2) and the prefill
    scatter (``serving.cache.write_prompt``, table broadcast per
    position) — it is safety-critical for cache isolation, so it must
    not fork per call site:
    a position that has run past its table (``pos//bs >= M``)
    steers into page 0, the trash page the serving allocator never hands
    out (:data:`torchdistx_tpu.serving.blocks.TRASH_BLOCK`), so a
    retired-but-still-batched slot (or a chunk's padding tail) can never
    scribble on a live slot's pages.
    """
    import jax.numpy as jnp

    m = block_tables.shape[1]
    blk_no = positions // block_size
    if positions.ndim == 1:
        blk = jnp.take_along_axis(
            block_tables, jnp.clip(blk_no, 0, m - 1)[:, None], axis=1
        )[:, 0]
    else:  # (B, T): T gathers per slot from its own table row
        blk = jnp.take_along_axis(
            block_tables, jnp.clip(blk_no, 0, m - 1), axis=1
        )
    blk = jnp.where(blk_no < m, blk, 0)
    return blk, positions % block_size


def paged_attention(q, k_pages, v_pages, block_tables, positions):
    """Decode-time attention against a block/paged KV cache (serving path).

    q ``(B, T, Hq, D)`` holds slot ``b``'s queries for positions
    ``positions[b] .. positions[b]+T-1`` — ``T == 1`` is a decode step;
    ``T > 1`` is a chunked-prefill block attending the slot's cached
    prefix (shared pages included) plus itself, the partial-prefix
    attention of the prefix cache.  ``k_pages``/``v_pages``
    ``(NB, bs, Hkv, D)`` are the one-layer page pools; ``block_tables``
    ``(B, M)`` int32 maps slot ``b``'s logical block ``j`` to its page.
    Gathers each slot's pages into a contiguous ``(B, M*bs, Hkv, D)`` view
    and reuses :func:`_attend_cached` with the per-slot causal mask
    ``key j <= positions[b] + i`` — pages beyond a slot's history (and the
    shared trash page other slots scribble on) mask to exactly-zero
    probability, so values match the contiguous-cache path bit-for-bit.

    The gather reads ``M*bs`` positions per slot; size ``M`` (the engine's
    ``max_model_len``) to the longest admissible request, NOT the model's
    ``max_seq_len`` — that width, not the pool size, is the decode-step
    HBM traffic.
    """
    import jax.numpy as jnp

    b, t = q.shape[0], q.shape[1]
    nb, bs, hkv, d = k_pages.shape
    m = block_tables.shape[1]
    k = jnp.take(k_pages, block_tables, axis=0).reshape(b, m * bs, hkv, d)
    v = jnp.take(v_pages, block_tables, axis=0).reshape(b, m * bs, hkv, d)
    valid = (
        jnp.arange(m * bs)[None, None, :]
        <= (positions[:, None] + jnp.arange(t)[None, :])[:, :, None]
    )
    return _attend_cached(q, k, v, valid[:, :, None, None, :])


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    impl: str = "auto",
    mesh=None,
    seq_axis: Optional[str] = None,
    pre_permuted: bool = False,
):
    """Dispatching attention entry point used by the model stack.

    ``impl``: ``"auto" | "jnp" | "pallas" | "ring" | "ring_zigzag"``.
    ``auto`` = ring iff ``seq_axis`` is set (sequence/context parallelism);
    else the Pallas flash kernel on TPU — single-chip directly, under a
    mesh via its shard_map wrapper (batch over dp/fsdp, heads over tp; see
    :func:`~torchdistx_tpu.ops.pallas.flash_attention.flash_attention_sharded`)
    whenever the shapes divide over the mesh; else jnp (XLA-fused,
    partitions anywhere).  ``ring_zigzag`` is the load-balanced causal ring
    schedule (see :mod:`torchdistx_tpu.parallel.ring_attention`).

    Callers already *inside* a shard_map (the pipeline stage body) must not
    select ``"pallas"`` with a mesh — the model forwards pin ``"jnp"``
    under ``pp_axis``.
    """
    impl = _select_impl(impl, mesh, seq_axis, q.shape, k.shape)
    if impl in ("ring", "ring_zigzag"):
        from ..parallel.ring_attention import ring_attention

        if mesh is None or seq_axis is None:
            raise ValueError("ring attention needs mesh= and seq_axis=")
        return ring_attention(
            q, k, v, mesh=mesh, axis=seq_axis, causal=causal,
            schedule="zigzag" if impl == "ring_zigzag" else "contiguous",
            pre_permuted=pre_permuted,
        )
    if pre_permuted:
        raise ValueError("pre_permuted is only meaningful with ring_zigzag")
    if impl == "pallas":
        from .pallas.flash_attention import (
            flash_attention,
            flash_attention_sharded,
            shardable,
        )

        if mesh is not None and shardable(mesh, q.shape, k.shape):
            return flash_attention_sharded(q, k, v, causal=causal, mesh=mesh)
        # mesh=None, or an explicit "pallas" opt-in whose shapes don't divide
        # over the mesh: the bare kernel (replicated per chip under a mesh —
        # the long-documented escape hatch for replicated heads/batch).
        return flash_attention(q, k, v, causal=causal)
    if impl != "jnp":
        raise ValueError(
            f"unknown attention impl: {impl!r} "
            "(expected auto|jnp|pallas|ring|ring_zigzag)"
        )
    return mha_reference(q, k, v, causal=causal)


# Mesh axes the shard_map wrapper understands: dp/fsdp shard batch, tp
# shards heads, and activations are replicated over ep/pp at the point
# attention runs (expert dispatch and pipeline staging have their own
# shard_maps elsewhere).  A mesh with any OTHER nontrivial axis (custom
# names like "data"/"model") falls back to jnp — a bare Mosaic call can't
# partition over axes we don't recognize.
_KNOWN_AXES = frozenset({"dp", "fsdp", "tp", "ep", "pp"})


def _select_impl(impl, mesh, seq_axis, q_shape, kv_shape) -> str:
    """Resolve ``impl="auto"`` (factored out for direct testing)."""
    if impl != "auto":
        return impl
    if seq_axis is not None:
        return "ring"
    if not _on_tpu():
        return "jnp"
    if mesh is None:
        return "pallas"
    if any(
        size > 1 and name not in _KNOWN_AXES
        for name, size in mesh.shape.items()
    ):
        return "jnp"
    from .pallas.flash_attention import shardable

    # Under a mesh the kernel runs through its shard_map wrapper; shapes
    # that don't divide over the mesh (odd batch vs dp, GQA heads vs tp)
    # fall back to XLA's fused jnp path, which partitions anything.
    return "pallas" if shardable(mesh, q_shape, kv_shape) else "jnp"


def resolve_stage_attn_impl(attn_impl: str) -> str:
    """Pin the attention impl for code already inside a pipeline stage.

    Stage bodies run inside the pipeline's shard_map; the flash kernel's
    own shard_map wrapper cannot nest there, so ``"auto"`` pins to
    ``"jnp"`` and an explicit ``"pallas"`` is refused.  Shared by every
    model family's ``forward`` (llama/gpt2/moe).
    """
    if attn_impl == "pallas":
        raise ValueError(
            "attn_impl='pallas' cannot run inside a pipeline stage; "
            "use 'auto' or 'jnp'"
        )
    return "jnp" if attn_impl == "auto" else attn_impl
