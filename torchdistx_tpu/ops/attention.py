"""Attention ops: reference implementation + implementation dispatcher.

The reference framework (/root/reference) contains no attention code at all —
its models come from torchvision/HF (BASELINE configs).  This framework ships
its own TPU-native model stack (:mod:`torchdistx_tpu.models`), so attention is
a first-class op with three interchangeable implementations:

* ``"jnp"``     — pure jax.numpy reference (runs anywhere, XLA-fused);
* ``"pallas"``  — fused flash-attention Pallas TPU kernel
  (:mod:`torchdistx_tpu.ops.pallas.flash_attention`): O(seq) memory, tiled
  for the MXU, online softmax;
* ``"ring"``    — ring attention over a sequence-parallel mesh axis
  (:mod:`torchdistx_tpu.parallel.ring_attention`): blockwise attention with
  K/V rotating over ICI via ``ppermute``, for sequences too long for one
  chip's HBM.

``attention()`` picks automatically: ring iff a sequence-parallel mesh axis
is given, else pallas on TPU, else jnp.
"""

from __future__ import annotations

import functools
from typing import Optional

__all__ = ["attention", "mha_reference"]


def _neg_inf(dtype):
    import jax.numpy as jnp

    return jnp.finfo(dtype).min


def mha_reference(q, k, v, *, causal: bool = True, segment_ids=None):
    """Reference multi-head attention (GQA-aware) in plain jax.numpy.

    Shapes: q ``(B, Sq, Hq, D)``; k/v ``(B, Sk, Hkv, D)`` with
    ``Hq % Hkv == 0`` (grouped-query attention).  Returns ``(B, Sq, Hq, D)``.
    Softmax is computed in float32 regardless of input dtype (bfloat16-safe).
    """
    import jax.numpy as jnp

    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    groups = hq // hkv
    qg = q.reshape(b, sq, hkv, groups, d)
    scale = 1.0 / (d**0.5)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32) * scale
    if causal:
        # Positions are global: with sequence parallelism the caller passes
        # pre-offset index vectors via segment_ids=None + explicit masks in
        # ring_attention; here q and k start at 0.
        qi = jnp.arange(sq)[:, None]
        ki = jnp.arange(sk)[None, :]
        mask = qi >= ki
        logits = jnp.where(mask[None, None, None], logits, _neg_inf(jnp.float32))
    if segment_ids is not None:
        q_seg, k_seg = segment_ids
        mask = q_seg[:, None, None, :, None] == k_seg[:, None, None, None, :]
        logits = jnp.where(mask, logits, _neg_inf(jnp.float32))
    probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v)
    return out.reshape(b, sq, hq, d)


def cached_attention(q, k_cache, v_cache, pos):
    """Decode-time attention against a static-shape KV cache.

    q ``(B, T, Hq, D)`` holds queries for positions ``pos .. pos+T-1``;
    k/v caches ``(B, Smax, Hkv, D)`` are valid up to ``pos+T``.  Key ``j``
    attends to query ``i`` iff ``j <= pos + i`` (global causal mask over the
    cache; invalid tail masked out).  Static shapes → one compiled decode
    step regardless of position.
    """
    import jax.numpy as jnp

    b, t, hq, d = q.shape
    smax, hkv = k_cache.shape[1], k_cache.shape[2]
    groups = hq // hkv
    qg = q.reshape(b, t, hkv, groups, d)
    scale = 1.0 / (d**0.5)
    logits = (
        jnp.einsum("bqhgd,bkhd->bqhgk", qg, k_cache).astype(jnp.float32)
        * scale
    )
    valid = jnp.arange(smax)[None, :] <= (pos + jnp.arange(t))[:, None]
    logits = jnp.where(
        valid[None, :, None, None, :], logits, _neg_inf(jnp.float32)
    )
    probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bqhgk,bkhd->bqhgd", probs.astype(v_cache.dtype), v_cache)
    return out.reshape(b, t, hq, d)


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    impl: str = "auto",
    mesh=None,
    seq_axis: Optional[str] = None,
    pre_permuted: bool = False,
):
    """Dispatching attention entry point used by the model stack.

    ``impl``: ``"auto" | "jnp" | "pallas" | "ring" | "ring_zigzag"``.
    ``auto`` = ring iff ``seq_axis`` is set (sequence/context parallelism);
    else pallas on TPU when ``mesh`` is None (single-chip); else jnp
    (XLA-fused, partitions correctly under a mesh).  ``ring_zigzag`` is the
    load-balanced causal ring schedule (see
    :mod:`torchdistx_tpu.parallel.ring_attention`).
    """
    if impl == "auto":
        if seq_axis is not None:
            impl = "ring"
        elif mesh is None and _on_tpu():
            # Only auto-select the Pallas kernel outside a mesh: a Mosaic
            # pallas_call carries no SPMD partitioning rules, so inside a
            # sharded jit program it would fail to partition (or silently
            # replicate full attention per chip).  Under a mesh, XLA's fused
            # jnp path partitions correctly; pass impl="pallas" explicitly to
            # opt in (e.g. single-axis data parallelism where heads/batch are
            # replicated per chip).
            impl = "pallas"
        else:
            impl = "jnp"
    if impl in ("ring", "ring_zigzag"):
        from ..parallel.ring_attention import ring_attention

        if mesh is None or seq_axis is None:
            raise ValueError("ring attention needs mesh= and seq_axis=")
        return ring_attention(
            q, k, v, mesh=mesh, axis=seq_axis, causal=causal,
            schedule="zigzag" if impl == "ring_zigzag" else "contiguous",
            pre_permuted=pre_permuted,
        )
    if pre_permuted:
        raise ValueError("pre_permuted is only meaningful with ring_zigzag")
    if impl == "pallas":
        from .pallas.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    if impl != "jnp":
        raise ValueError(
            f"unknown attention impl: {impl!r} "
            "(expected auto|jnp|pallas|ring|ring_zigzag)"
        )
    return mha_reference(q, k, v, causal=causal)
