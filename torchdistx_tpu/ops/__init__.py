from .aten_jax import LOWERINGS, UnsupportedOpError, lowering  # noqa: F401
