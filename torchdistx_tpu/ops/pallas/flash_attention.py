"""Fused flash attention — Pallas TPU kernel.

Single-chip attention for the model stack (:mod:`torchdistx_tpu.models`):
Q is tiled into blocks that stream through VMEM while the full K/V rows for
the (kv-)head sit in VMEM; logits/softmax run in float32 on the VPU and both
matmuls hit the MXU via ``jnp.dot(..., preferred_element_type=f32)``.  GQA is
handled in the index maps — each Q-head grid step fetches its kv-head's K/V
block (no materialized head expansion, no extra HBM traffic).

The public entry is differentiable via ``jax.custom_vjp``: the forward runs
the Pallas kernel (saving the f32 log-sum-exp), the backward uses the
standard flash-attention gradient identities computed with XLA (dv = pᵀ·do,
ds = p∘(do·vᵀ − rowsum(do∘o)), dq = ds·k, dk = dsᵀ·q) — exact, recompute-
based, nothing saved but q/k/v/out/lse.

``interpret=True`` runs the same kernel through the Pallas interpreter so CPU
CI (the virtual-mesh test rig, SURVEY.md §4) covers the kernel logic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["flash_attention"]

_NEG_INF = float("-inf")


def _pick_block(s: int, preferred: int = 256) -> int:
    if s <= preferred:
        return s
    b = preferred
    while s % b:
        b //= 2
    return max(b, 1)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, bq):
    import jax.experimental.pallas as pl

    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)  # (bq, d)
    k = k_ref[0, 0].astype(jnp.float32)  # (S, d)
    v = v_ref[0, 0]  # (S, d)
    s = k.shape[0]
    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if causal:
        qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, s), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (bq, s), 1)
        logits = jnp.where(qpos >= kpos, logits, _NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        (p / l).astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0, 0] = o.astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l))[:, 0]


def _fa_forward(q, k, v, *, causal: bool, interpret: bool):
    """q: (B, Hq, S, D); k/v: (B, Hkv, S, D) → (out, lse)."""
    import jax.experimental.pallas as pl

    b, hq, s, d = q.shape
    hkv = k.shape[1]
    groups = hq // hkv
    bq = _pick_block(s)
    scale = 1.0 / (d**0.5)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, hq, s // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi // groups, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi // groups, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq), lambda bi, hi, qi: (bi, hi, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, hq, s), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _expand_kv(t, groups):
    # (B, Hkv, S, D) -> (B, Hq, S, D) for the XLA backward.
    return jnp.repeat(t, groups, axis=1) if groups > 1 else t


def _fa_backward_xla(q, k, v, out, lse, do, *, causal, scale):
    """Exact flash-attention gradients, recomputed in XLA (f32).

    Chunked over Q blocks with a ``lax.scan`` accumulating dk/dv, so peak
    memory is O(bq·S) logits per head — the same order as the forward
    kernel — never the full (S, S) attention matrix.
    """
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    groups = hq // hkv
    kx = _expand_kv(k, groups).astype(jnp.float32)
    vx = _expand_kv(v, groups).astype(jnp.float32)
    bq = _pick_block(s)
    nblk = s // bq

    def chunk(t):  # (B, H, S, ...) -> (nblk, B, H, bq, ...)
        return jnp.moveaxis(
            t.reshape(t.shape[:2] + (nblk, bq) + t.shape[3:]), 2, 0
        )

    q_c = chunk(q.astype(jnp.float32))
    do_c = chunk(do.astype(jnp.float32))
    delta_c = chunk(jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                            axis=-1, keepdims=True))
    lse_c = chunk(lse[..., None])
    kpos = jnp.arange(s)

    def step(carry, blk):
        dk_acc, dv_acc, i = carry
        qi, doi, di, li = blk
        logits = jnp.einsum("bhqd,bhkd->bhqk", qi, kx) * scale
        if causal:
            qpos = i * bq + jnp.arange(bq)
            logits = jnp.where(
                (qpos[:, None] >= kpos[None, :])[None, None], logits, _NEG_INF
            )
        p = jnp.exp(logits - li)  # rows sum to 1
        dv_acc = dv_acc + jnp.einsum("bhqk,bhqd->bhkd", p, doi)
        dp = jnp.einsum("bhqd,bhkd->bhqk", doi, vx)
        ds = p * (dp - di) * scale
        dqi = jnp.einsum("bhqk,bhkd->bhqd", ds, kx)
        dk_acc = dk_acc + jnp.einsum("bhqk,bhqd->bhkd", ds, qi)
        return (dk_acc, dv_acc, i + 1), dqi

    zeros = jnp.zeros((b, hq, s, d), dtype=jnp.float32)
    (dk, dv, _), dq_c = jax.lax.scan(
        step, (zeros, zeros, jnp.zeros((), jnp.int32)),
        (q_c, do_c, delta_c, lse_c),
    )
    dq = jnp.moveaxis(dq_c, 0, 2).reshape(b, hq, s, d)
    if groups > 1:
        dk = dk.reshape(b, hkv, groups, s, d).sum(axis=2)
        dv = dv.reshape(b, hkv, groups, s, d).sum(axis=2)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fa(q, k, v, causal, interpret):
    out, _ = _fa_forward(q, k, v, causal=causal, interpret=interpret)
    return out


def _fa_fwd(q, k, v, causal, interpret):
    out, lse = _fa_forward(q, k, v, causal=causal, interpret=interpret)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, interpret, res, do):
    q, k, v, out, lse = res
    scale = 1.0 / (q.shape[-1] ** 0.5)
    return _fa_backward_xla(q, k, v, out, lse, do, causal=causal, scale=scale)


_fa.defvjp(_fa_fwd, _fa_bwd)


def flash_attention(
    q, k, v, *, causal: bool = True, interpret: Optional[bool] = None
):
    """Fused attention.  Layout matches the model stack: ``(B, S, H, D)``.

    ``interpret``: force the Pallas interpreter (None = auto: interpret on
    non-TPU backends so the kernel is testable on the CPU mesh rig).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Kernel layout is (B, H, S, D).
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _fa(qt, kt, vt, causal, interpret)
    return out.transpose(0, 2, 1, 3)
