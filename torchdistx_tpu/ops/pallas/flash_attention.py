"""Fused flash attention — Pallas TPU kernels (forward + backward).

Single-chip attention for the model stack (:mod:`torchdistx_tpu.models`).
Both Q **and** K/V are tiled: the kv dimension is a grid axis streamed
through VMEM with online-softmax accumulators held in VMEM scratch, so
per-step VMEM is O(bq·d + bkv·d) regardless of sequence length — the
long-context regime (S ≥ 16k) the kernel exists for.  Matmuls keep their
storage dtype (bf16 → full MXU rate) and accumulate in f32 via
``preferred_element_type``; logits/softmax/rescale math runs in float32 on
the VPU.  GQA is handled in the index maps — each
Q-head grid step fetches its kv-head's K/V block (no materialized head
expansion, no extra HBM traffic).

Perf notes (v5e, S=16k, d=128): blocks default to 1024 — large blocks
amortize per-grid-step overhead and quadrupled throughput over 256-blocks;
softmax runs in the log2 domain (``exp2`` is the native transcendental,
log2 e folds into the softmax scale); only padded kv cols and
causal-diagonal blocks are masked (padded q rows cancel structurally).
Together: fwd+bwd 61→22 ms, attention MFU 0.16→0.44.

The backward is two Pallas kernels using the standard flash-attention
gradient identities (dv = pᵀ·do, ds = p∘(do·vᵀ − rowsum(do∘o)),
dq = ds·k, dk = dsᵀ·q), each streaming its reduction axis through a grid
dimension with VMEM scratch accumulators:

* dq kernel: grid ``(B, Hq, nq, nkv)`` — accumulates dq over kv blocks;
* dk/dv kernel: grid ``(B, Hkv, nkv, groups·nq)`` — accumulates dk/dv over
  (gqa-group, q-block) pairs, summing the GQA group reduction in-kernel.

Sequence lengths are padded to the TPU tile grain (128, or 8 below one
block); padded keys/queries are masked in-kernel, so any length is accepted.
The log-sum-exp/delta tensors are carried as ``(B, H, S_pad, 1)`` so their
``(1, 1, bq, 1)`` blocks satisfy Mosaic's (8, 128)-or-equal tiling rule on
the last two block dims (the round-1 ``(1, 1, bq)`` spec did not compile on
real TPU).

``interpret=True`` runs the same kernels through the Pallas interpreter so
CPU CI (the virtual-mesh test rig, SURVEY.md §4) covers the kernel logic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["flash_attention", "flash_attention_sharded", "shardable"]

# Finite "minus infinity": keeps the online-softmax recurrences NaN-free for
# rows whose valid keys haven't streamed in yet (exp(-1e30 − m) underflows to
# exactly 0; -inf would produce inf−inf = NaN in the rescale term).
_MASK = -1e30


def _pad_len(s: int) -> int:
    """Sequence padded to the TPU tile grain."""
    if s >= 128:
        return -(-s // 128) * 128
    return -(-s // 8) * 8


# Block-size overrides (None = measured-best default).  Module-level
# knobs so the bench/tuning harness (scripts/flash_sweep.py) can sweep
# them.
#
# NOTE (advisor r4): these globals are read at TRACE time and are not part
# of any jit cache key — a sweep that mutates them under a caller's cached
# ``jax.jit`` keeps executing the previously-traced blocks.  Sweeps must
# call ``jax.clear_caches()`` after each override change (the bench
# harness does).
#
# With the mask-free interior bodies, SQUARE blocks measure best both
# directions at S=16k d=128 on v5e (adjacent same-window runs:
# fwd+bwd 23.5 ms at the round-4 (512,2048)/(512,1024) defaults →
# 21.9 ms with fwd 1024² → 20.6 ms with bwd 1024² as well): at bq == bkv
# exactly one kv step per q block pays the masked body, and the square
# shape balances the dq/dkv accumulator footprints.
_BWD_BLOCK_Q = None
_BWD_BLOCK_KV = None
_BWD_BLOCK_Q_DEFAULT = 1024
_BWD_BLOCK_KV_DEFAULT = 1024
# Sequences up to this length take the fused one-kernel backward with the
# whole kv extent as a single block (VMEM bound: the (bq, s_pad) f32
# p/ds buffers — 8 MB at bq 1024, s 2048).  Beyond it, the streamed
# two-kernel backward.
_FUSED_BWD_MAX_KV = 2048
# Known-good f32 working-set budget for one (bq, bkv) p/ds pair in the
# fused backward (1024² — the S=1024 training case); bq 1024 × bkv 2048
# overflows VMEM server-side.  The fused path halves bq down to 128 to
# stay under this, and falls back to the streamed two-kernel backward
# when even bq=128 cannot fit (bkv = s_pad > 8192).
_FUSED_BWD_VMEM_CAP = 1024 * 1024 * 4
_FWD_BLOCK_Q = None
_FWD_BLOCK_KV = None
_FWD_BLOCK_Q_DEFAULT = 1024
_FWD_BLOCK_KV_DEFAULT = 1024


def _compiler_params(pltpu, **kw):
    """``pltpu.CompilerParams``, falling back to the pre-rename
    ``TPUCompilerParams`` (jax < 0.6) — same kwargs either way."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def _pick_block(s_pad: int, override, default) -> int:
    for cand in (override, default):
        if cand and s_pad % cand == 0:
            return cand
    return _block_for(s_pad)


def _block_for(s_pad: int, preferred: int = 1024) -> int:
    # Large blocks amortize per-grid-step overhead (DMA issue, softmax VPU
    # setup): at S=16k, d=128, blocks of 1024 run the fwd+bwd pair 2.5×
    # faster than 256 (27ms vs 68ms, v5e).  2048 exceeds VMEM with
    # double-buffered q/k/v/o + f32 scratch.
    for b in (preferred, 512, 256, 128):
        if s_pad % b == 0:
            return b
    return s_pad  # s_pad < 128: single block (equality escape in Mosaic)


# exp(x) = exp2(x·log2 e): exp2 is the native TPU transcendental, and the
# log2 e factor folds into the softmax scale (fwd) or a single multiply
# (bwd), shaving VPU work from the hottest loop.
_LOG2E = 1.4426950408889634


def _iota(shape, axis):
    return jax.lax.broadcasted_iota(jnp.int32, shape, axis)


def _diag_clamp(causal: bool, bq: int, bkv: int, clamp):
    """Index transform for the *streamed* block axis of a causal grid.

    Blocks strictly on the skipped side of the diagonal are never computed
    (the kernels' ``run`` predicate ``q_start + bq - 1 >= k_start``);
    clamping their index to the diagonal makes consecutive grid steps
    fetch the same block, and Mosaic elides the repeated HBM→VMEM copy —
    at 16k that is half the streamed-side traffic.  ``clamp`` is
    ``jnp.minimum`` for a streamed kv axis (skip blocks past the last
    running kv block of the fixed q row) and ``jnp.maximum`` for a
    streamed q axis (skip blocks before the first running q block of the
    fixed kv row); both reduce to min/max(streamed, fixed) when
    ``bq == bkv``.
    """
    if not causal:
        return lambda streamed, fixed: streamed
    if clamp is jnp.minimum:
        return lambda ki, qi: jnp.minimum(ki, (qi * bq + bq - 1) // bkv)
    return lambda qi, ki: jnp.maximum(qi, (ki * bkv) // bq)


# ---------------------------------------------------------------------------
# Forward


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale, causal, bq, bkv, s,
):
    import jax.experimental.pallas as pl

    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    q_start = qi * bq
    k_start = ki * bkv

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _MASK)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: skip kv blocks entirely above the diagonal.
    run = (q_start + bq - 1 >= k_start) if causal else True
    # Masking is needed only where correctness demands it: the one kv
    # step whose block intersects the causal diagonal (bq ≤ bkv ⇒ at most
    # one per q block) or carries padded cols.  Everything below runs the
    # mask-free body — the iota/compare/select passes are ~1/3 of the
    # per-step VPU element work and ~90% of steps don't need them.  The
    # two bodies are scalar-branched with pl.when (a real Mosaic branch;
    # a lax.cond variant measured slower).
    needs_mask = _needs_mask(causal, q_start, k_start, bkv, s)

    def _body(apply_mask):
        # Matmul inputs keep their storage dtype (bf16 on TPU → full MXU
        # rate) with f32 accumulation; only softmax math runs f32 on the
        # VPU.  An earlier revision upcast to f32 *before* the dots, which
        # quarters MXU throughput.  Softmax runs in the log2 domain (scale
        # folds in log2 e; exp2 is the native transcendental).
        q = q_ref[0, 0]  # (bq, d)
        logits = (
            jax.lax.dot_general(
                q, k_ref[0, 0], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * (scale * _LOG2E)
        )
        if apply_mask:
            # Mask only what correctness needs: padded kv cols (they must
            # not enter l), the causal triangle when the block touches
            # the diagonal.  Padded q ROWS need no mask: their logits are
            # finite (zero-padded q) and their outputs are sliced off.
            kpos = k_start + _iota((bq, bkv), 1)
            keep = kpos < s
            if causal:
                keep &= (q_start + _iota((bq, bkv), 0)) >= kpos
            logits = jnp.where(keep, logits, _MASK)

        # Row statistics computed on (bq, 1) slices: the scratch tiles are
        # physically (bq, 128) (f32 tiling grain), but running the
        # max/exp/rescale math lane-replicated would add bq·128 exps per
        # step — a ~50% increase over the bq·bkv softmax exps themselves.
        #
        # Rejected variants, measured at S=16k (v5e): in-body kv
        # sub-splitting with a combined max (no MXU/VPU overlap — Mosaic
        # barriers every exp2 behind all qk matmuls), per-sub online
        # updates (extra acc rescales), lax.cond-gated masking
        # (predication costs more than the iota/where it saves, 10.6 →
        # 13.7 ms).  The win that stuck is the scalar-branched mask-free
        # interior body (see pl.when below).
        m_prev = m_ref[...][:, :1]  # (bq, 1)
        l_prev = l_ref[...][:, :1]
        row_max = jnp.max(logits, axis=-1, keepdims=True)  # (bq, 1)
        m_next = jnp.maximum(m_prev, row_max)
        alpha = jnp.exp2(m_prev - m_next)  # (bq, 1)
        p = jnp.exp2(logits - m_next)  # (bq, bkv)
        l_next = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        l_ref[...] = jnp.broadcast_to(l_next, l_ref.shape)
        m_ref[...] = jnp.broadcast_to(m_next, m_ref.shape)
        acc_ref[...] = acc_ref[...] * alpha + pv

    @pl.when(run & needs_mask)
    def _body_masked():
        _body(True)

    @pl.when(run & jnp.logical_not(needs_mask))
    def _body_plain():
        _body(False)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[...][:, :1]  # (bq, 1)
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked (padded) rows
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        # m is tracked in the log2 domain; lse stays natural-log (the
        # backward converts once per row block).
        lse_ref[0, 0] = m_ref[...][:, :1] / _LOG2E + jnp.log(l_safe)


def _fa_forward_padded(q, k, v, s, *, causal: bool, interpret: bool):
    """q: (B, Hq, S_pad, D); k/v: (B, Hkv, S_pad, D); ``s`` = valid length.

    Returns ``(out, lse)`` with ``out`` matching q's shape and ``lse``
    ``(B, Hq, S_pad)`` float32.
    """
    import jax.experimental.pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    b, hq, s_pad, d = q.shape
    hkv = k.shape[1]
    groups = hq // hkv
    bq = _pick_block(s_pad, _FWD_BLOCK_Q, _FWD_BLOCK_Q_DEFAULT)
    bkv = _pick_block(s_pad, _FWD_BLOCK_KV, _FWD_BLOCK_KV_DEFAULT)
    nq, nk = s_pad // bq, s_pad // bkv
    scale = 1.0 / (d**0.5)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bkv=bkv, s=s
    )

    kv_clamp = _diag_clamp(causal, bq, bkv, jnp.minimum)

    def kv_index(bi, hi, qi, ki, g=groups):
        return (bi, hi // g, kv_clamp(ki, qi), 0)

    out, lse = pl.pallas_call(
        kernel,
        grid=(b, hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bkv, d), kv_index),
            pl.BlockSpec((1, 1, bkv, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec(
                (1, 1, bq, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, hq, s_pad, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Backward


def _recompute_p(
    q, k, lse, q_start, k_start, *, scale, causal, bq, bkv, s, s_pad,
    apply_mask=True,
):
    """Recompute the softmax block from the saved (natural-log) lse.

    Masking needed: the causal triangle on diagonal blocks (interior
    blocks lie fully below it), and — non-causal with padding only — the
    padded kv cols, whose p = exp(-lse) can overflow f32 for very negative
    lse and then poison dq with inf·0 = NaN.  (Causal padding is safe: for
    real rows every padded col sits above the diagonal; padded q-row /
    kv-col contributions otherwise cancel against zero-padded do/k/v, and
    padded dk/dv rows are sliced off by the caller.)

    ``apply_mask=False`` skips the iota/compare/select passes — callers
    branch on the same block-level condition the forward uses (at most one
    kv block per q block intersects the diagonal).
    """
    logits = (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * (scale * _LOG2E)
    )
    p = jnp.exp2(logits - lse * _LOG2E)
    if not apply_mask:
        return p
    if causal:
        kpos = k_start + _iota((bq, bkv), 1)
        keep = (q_start + _iota((bq, bkv), 0)) >= kpos
        p = jnp.where(keep, p, 0.0)
    elif s_pad > s:
        kpos = k_start + _iota((bq, bkv), 1)
        p = jnp.where(kpos < s, p, 0.0)
    return p


def _needs_mask(causal, q_start, k_start, bkv, s):
    """Block-level mask condition shared by fwd and bwd kernels: the kv
    block crosses the causal diagonal for this q block, or carries padded
    cols.  (Worst causal pair: first q row vs last kv col.)"""
    needs = k_start + bkv > s
    if causal:
        needs |= k_start + bkv - 1 > q_start
    return needs


def _p_ds(
    q, k, v, do, lse, delta, q_start, k_start,
    *, scale, causal, bq, bkv, s, s_pad, apply_mask,
):
    """The shared backward block chain: recomputed softmax ``p`` and the
    logit gradient ``ds = p ∘ (do·vᵀ − Δ)·scale`` (cast to the matmul
    dtype).  Every backward kernel (dq, dk/dv, fused) consumes exactly
    these two — one definition so a change to the gradient identities
    cannot silently diverge between the long-context and training paths.
    """
    p = _recompute_p(
        q, k, lse, q_start, k_start,
        scale=scale, causal=causal, bq=bq, bkv=bkv, s=s, s_pad=s_pad,
        apply_mask=apply_mask,
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = (p * (dp - delta) * scale).astype(q.dtype)
    return p, ds


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref,
    *, scale, causal, bq, bkv, s, s_pad,
):
    import jax.experimental.pallas as pl

    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    q_start = qi * bq
    k_start = ki * bkv

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = (q_start + bq - 1 >= k_start) if causal else True
    needs_mask = _needs_mask(causal, q_start, k_start, bkv, s)

    def _body(apply_mask):
        # bf16 matmul inputs + f32 accumulation (see _fwd_kernel note).
        _, ds = _p_ds(
            q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], do_ref[0, 0],
            lse_ref[0, 0], delta_ref[0, 0], q_start, k_start,
            scale=scale, causal=causal, bq=bq, bkv=bkv, s=s, s_pad=s_pad,
            apply_mask=apply_mask,
        )
        acc_ref[...] += jax.lax.dot_general(
            ds, k_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(run & needs_mask)
    def _body_masked():
        _body(True)

    @pl.when(run & jnp.logical_not(needs_mask))
    def _body_plain():
        _body(False)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, scale, causal, bq, bkv, s, s_pad, nq,
):
    import jax.experimental.pallas as pl

    ki = pl.program_id(2)
    idx = pl.program_id(3)  # (gqa group, q block) pairs
    n_idx = pl.num_programs(3)
    qi = idx % nq
    q_start = qi * bq
    k_start = ki * bkv

    @pl.when(idx == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = (q_start + bq - 1 >= k_start) if causal else True
    needs_mask = _needs_mask(causal, q_start, k_start, bkv, s)

    def _body(apply_mask):
        # bf16 matmul inputs + f32 accumulation (see _fwd_kernel note).
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        p, ds = _p_ds(
            q, k_ref[0, 0], v_ref[0, 0], do, lse_ref[0, 0],
            delta_ref[0, 0], q_start, k_start,
            scale=scale, causal=causal, bq=bq, bkv=bkv, s=s, s_pad=s_pad,
            apply_mask=apply_mask,
        )
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(run & needs_mask)
    def _body_masked():
        _body(True)

    @pl.when(run & jnp.logical_not(needs_mask))
    def _body_plain():
        _body(False)

    @pl.when(idx == n_idx - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _dqkv_fused_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dk_ref,
    dv_ref, dk_acc, dv_acc,
    *, scale, causal, bq, bkv, s, s_pad, nq,
):
    """Single-kv-block backward (``nk == 1`` — the training regime, where
    S fits one kv block): dq, dk, dv in ONE kernel.

    With the whole kv extent resident, dq needs no cross-step
    accumulation (each q block's dq is complete after its own grid step),
    so the classic dq/dkv grid-order conflict disappears.  One kernel
    halves the per-layer pallas-call count AND computes the p/dp
    recompute once instead of twice (5 block matmuls instead of 7, half
    the bwd exp2s) — the two-kernel split at S=1024/d=64 measured ~0.64
    ms per call with ~0.09 ms of ideal matmul work, i.e. per-call
    overhead and duplicated softmax dominated the training backward.
    """
    import jax.experimental.pallas as pl

    idx = pl.program_id(2)  # (gqa group, q block) pairs
    n_idx = pl.num_programs(2)
    qi = idx % nq
    q_start = qi * bq
    k_start = 0

    @pl.when(idx == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = (q_start + bq - 1 >= k_start) if causal else True
    needs_mask = _needs_mask(causal, q_start, k_start, bkv, s)

    def _body(apply_mask):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        do = do_ref[0, 0]
        p, ds = _p_ds(
            q, k, v_ref[0, 0], do, lse_ref[0, 0], delta_ref[0, 0],
            q_start, k_start,
            scale=scale, causal=causal, bq=bq, bkv=bkv, s=s, s_pad=s_pad,
            apply_mask=apply_mask,
        )
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dq_ref[0, 0] = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dq_ref.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(run & needs_mask)
    def _body_masked():
        _body(True)

    @pl.when(run & jnp.logical_not(needs_mask))
    def _body_plain():
        _body(False)

    # Above-diagonal q blocks never run the body: their dq block is pure
    # padding-free zeros.
    @pl.when(jnp.logical_not(run))
    def _zero_dq():
        dq_ref[0, 0] = jnp.zeros_like(dq_ref[0, 0])

    @pl.when(idx == n_idx - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _fa_backward_fused_nk1(q, k, v, out, lse, do, s, *, causal, interpret):
    """One-kernel backward for ``s_pad <= bkv`` (single kv block)."""
    import jax.experimental.pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    b, hq, s_pad, d = q.shape
    hkv = k.shape[1]
    groups = hq // hkv
    bq = _pick_block(s_pad, _BWD_BLOCK_Q, _BWD_BLOCK_Q_DEFAULT)
    bkv = s_pad  # single block
    # Cap the (bq, bkv) f32 p/ds working set (_FUSED_BWD_VMEM_CAP) by
    # halving bq; below 128 rows the MXU tiles go partial, so once bq
    # bottoms out there the single-block premise itself has failed —
    # stream kv through a grid axis instead of holding it whole.
    while bq > 128 and bq * bkv * 4 > _FUSED_BWD_VMEM_CAP:
        bq //= 2
    if bq * bkv * 4 > _FUSED_BWD_VMEM_CAP:
        # Don't hand the streamed path our whittled bq: its kv blocks are
        # _block_for-sized, not the whole extent, so its own default q
        # block (the known-good 1024² working set) fits the cap fine —
        # a 128-row handoff would just run 8× more dq grid iterations.
        return _fa_backward_streamed(
            q, k, v, out, lse, do, s, causal=causal, interpret=interpret,
            bkv=_block_for(s_pad),
        )
    nq = s_pad // bq
    scale = 1.0 / (d**0.5)

    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32),
        axis=-1, keepdims=True,
    )

    gq_q_spec = pl.BlockSpec(
        (1, 1, bq, d),
        lambda bi, hkvi, idx, g=groups, n=nq: (
            bi, hkvi * g + idx // n, idx % n, 0
        ),
    )
    gq_row_spec = pl.BlockSpec(
        (1, 1, bq, 1),
        lambda bi, hkvi, idx, g=groups, n=nq: (
            bi, hkvi * g + idx // n, idx % n, 0
        ),
    )
    kv_spec = pl.BlockSpec(
        (1, 1, bkv, d), lambda bi, hkvi, idx: (bi, hkvi, 0, 0)
    )
    dq, dk, dv = pl.pallas_call(
        functools.partial(
            _dqkv_fused_kernel, scale=scale, causal=causal, bq=bq,
            bkv=bkv, s=s, s_pad=s_pad, nq=nq,
        ),
        grid=(b, hkv, groups * nq),
        in_specs=[
            gq_q_spec, kv_spec, kv_spec, gq_q_spec, gq_row_spec,
            gq_row_spec,
        ],
        out_specs=[gq_q_spec, kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bkv, d), jnp.float32),
            pltpu.VMEM((bkv, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _fa_backward(q, k, v, out, lse, do, s, *, causal, interpret):
    s_pad = q.shape[2]
    # Whole kv extent in one block → fused one-kernel path.  An explicit
    # smaller kv-block override (sweeps/tests) forces the streamed pair.
    if (_BWD_BLOCK_KV is None or _BWD_BLOCK_KV >= s_pad) and (
        s_pad <= _FUSED_BWD_MAX_KV
        or s_pad == _pick_block(s_pad, _BWD_BLOCK_KV, _BWD_BLOCK_KV_DEFAULT)
    ):
        return _fa_backward_fused_nk1(
            q, k, v, out, lse, do, s, causal=causal, interpret=interpret
        )
    return _fa_backward_streamed(
        q, k, v, out, lse, do, s, causal=causal, interpret=interpret
    )


def _fa_backward_streamed(
    q, k, v, out, lse, do, s, *, causal, interpret, bq=None, bkv=None
):
    """The streamed two-kernel backward (dq kernel + dk/dv kernel), kv as
    a grid axis.  ``bq``/``bkv`` are normally derived from the sweep
    overrides; the fused path passes explicit VMEM-safe blocks when it
    falls back here."""
    import jax.experimental.pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    b, hq, s_pad, d = q.shape
    hkv = k.shape[1]
    groups = hq // hkv
    if bq is None:
        bq = _pick_block(s_pad, _BWD_BLOCK_Q, _BWD_BLOCK_Q_DEFAULT)
    if bkv is None:
        bkv = _pick_block(s_pad, _BWD_BLOCK_KV, _BWD_BLOCK_KV_DEFAULT)
    nq, nk = s_pad // bq, s_pad // bkv
    scale = 1.0 / (d**0.5)

    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32),
        axis=-1, keepdims=True,
    )  # (B, Hq, S_pad, 1)

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kv_clamp = _diag_clamp(causal, bq, bkv, jnp.minimum)
    kv_spec = pl.BlockSpec(
        (1, 1, bkv, d),
        lambda bi, hi, qi, ki, g=groups: (bi, hi // g, kv_clamp(ki, qi), 0),
    )
    row_spec = pl.BlockSpec(
        (1, 1, bq, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
    )

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, bq=bq, bkv=bkv, s=s,
            s_pad=s_pad,
        ),
        grid=(b, hq, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: grid over kv blocks with the (group, q-block) reduction as the
    # innermost axis — the GQA head-group sum happens in the accumulator.
    _q_block = _diag_clamp(causal, bq, bkv, jnp.maximum)
    gq_q_spec = pl.BlockSpec(
        (1, 1, bq, d),
        lambda bi, hkvi, ki, idx, g=groups, n=nq: (
            bi, hkvi * g + idx // n, _q_block(idx % n, ki), 0
        ),
    )
    gq_row_spec = pl.BlockSpec(
        (1, 1, bq, 1),
        lambda bi, hkvi, ki, idx, g=groups, n=nq: (
            bi, hkvi * g + idx // n, _q_block(idx % n, ki), 0
        ),
    )
    kv_out_spec = pl.BlockSpec(
        (1, 1, bkv, d), lambda bi, hkvi, ki, idx: (bi, hkvi, ki, 0)
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, bq=bq, bkv=bkv, s=s,
            s_pad=s_pad, nq=nq,
        ),
        grid=(b, hkv, nk, groups * nq),
        in_specs=[
            gq_q_spec, kv_out_spec, kv_out_spec, gq_q_spec,
            gq_row_spec, gq_row_spec,
        ],
        out_specs=[kv_out_spec, kv_out_spec],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bkv, d), jnp.float32),
            pltpu.VMEM((bkv, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Differentiable entry (operates on padded (B, H, S_pad, D) layout)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fa(q, k, v, s, causal, interpret):
    out, _ = _fa_forward_padded(q, k, v, s, causal=causal, interpret=interpret)
    return out


def _fa_fwd(q, k, v, s, causal, interpret):
    out, lse = _fa_forward_padded(
        q, k, v, s, causal=causal, interpret=interpret
    )
    return out, (q, k, v, out, lse)


def _fa_bwd(s, causal, interpret, res, do):
    q, k, v, out, lse = res
    return _fa_backward(
        q, k, v, out, lse, do, s, causal=causal, interpret=interpret
    )


_fa.defvjp(_fa_fwd, _fa_bwd)


def flash_attention(
    q, k, v, *, causal: bool = True, interpret: Optional[bool] = None
):
    """Fused attention.  Layout matches the model stack: ``(B, S, H, D)``.

    Any sequence length is accepted (padded to the TPU tile grain and masked
    in-kernel).  ``interpret``: force the Pallas interpreter (None = auto:
    interpret on non-TPU backends so the kernel is testable on the CPU mesh
    rig).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, hq, d = q.shape
    s_pad = _pad_len(s)
    # Kernel layout is (B, H, S, D).
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if s_pad != s:
        pad = ((0, 0), (0, 0), (0, s_pad - s), (0, 0))
        qt, kt, vt = (jnp.pad(t, pad) for t in (qt, kt, vt))
    out = _fa(qt, kt, vt, s, causal, interpret)
    if s_pad != s:
        out = out[:, :, :s, :]
    return out.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# SPMD wrapper: the kernel under a mesh.
#
# A pallas_call is a Mosaic custom call with no SPMD partitioning rules, so
# inside a sharded jit program XLA cannot partition it (round-2's dispatcher
# therefore fell back to O(S²) jnp attention for every multi-chip train
# step).  Attention is embarrassingly parallel over batch and head, so the
# TPU-native fix is shard_map: run the kernel per-device on its local
# (batch-shard, head-shard) block — no collectives, sequence replicated —
# while dp/fsdp shard batch and tp shards heads exactly as the Megatron
# projections already laid them out (contiguous head chunks align q-head
# groups with their kv heads under GQA).


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map

        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def _mesh_split(mesh, batch_axes, head_axis):
    """Nontrivial (size>1) batch axes and head axis present in ``mesh``."""
    batch = tuple(
        a for a in batch_axes if a in mesh.shape and mesh.shape[a] > 1
    )
    head = (
        head_axis
        if head_axis in mesh.shape and mesh.shape[head_axis] > 1
        else None
    )
    return batch, head


def shardable(
    mesh, q_shape, kv_shape, *,
    batch_axes=("dp", "fsdp"), head_axis="tp",
) -> bool:
    """Whether the kernel can run under ``mesh`` via :func:`flash_attention_sharded`:
    the dp/fsdp product must divide batch and tp must divide both head
    counts (whole GQA groups per shard)."""
    batch, head = _mesh_split(mesh, batch_axes, head_axis)
    b, _, hq, _ = q_shape
    hkv = kv_shape[2]
    nb = 1
    for a in batch:
        nb *= mesh.shape[a]
    tp = mesh.shape[head] if head else 1
    return b % nb == 0 and hq % tp == 0 and hkv % tp == 0


def flash_attention_sharded(
    q, k, v, *,
    causal: bool = True,
    mesh,
    batch_axes=("dp", "fsdp"),
    head_axis: str = "tp",
    interpret: Optional[bool] = None,
):
    """:func:`flash_attention` under a mesh: batch sharded over
    ``batch_axes``, heads over ``head_axis``, sequence replicated.

    Layout ``(B, S, H, D)`` as everywhere in the model stack.  Must not be
    called inside another shard_map over the same axes (the pipeline stage
    body) — the dispatcher routes those to jnp attention.
    """
    from jax.sharding import PartitionSpec as P

    if not shardable(
        mesh, q.shape, k.shape, batch_axes=batch_axes, head_axis=head_axis
    ):
        raise ValueError(
            f"flash_attention_sharded: q {q.shape} / kv {k.shape} not "
            f"divisible over mesh {dict(mesh.shape)} "
            f"(batch_axes={batch_axes}, head_axis={head_axis!r})"
        )
    batch, head = _mesh_split(mesh, batch_axes, head_axis)
    if not batch and head is None:
        return flash_attention(q, k, v, causal=causal, interpret=interpret)
    spec = P(batch if batch else None, None, head, None)

    def local(ql, kl, vl):
        return flash_attention(ql, kl, vl, causal=causal, interpret=interpret)

    return _shard_map(local, mesh, (spec, spec, spec), spec)(q, k, v)
