"""aten → JAX lowering registry for tape replay.

The torch-backend materializer replays recorded aten ops natively; the
TPU-native materializer (:mod:`torchdistx_tpu.materialize`) instead lowers
each recorded *compute* op to JAX so the whole init subgraph runs inside one
``jit`` with sharded outputs on a mesh.  View/aliasing ops never reach this
registry — the functional-replay engine resolves them through strided
gather/scatter on flat storage buffers (see materialize.py), which is the
functional translation of the reference's mutable-storage replay
(/root/reference/src/cc/torchdistx/deferred_init.cc:505-666).

RNG lowering note: torch's in-place RNG ops (``uniform_``, ``normal_``) draw
from the global Philox stream; here each op draws from
``fold_in`` streams (name-keyed or tape-relative, see materialize.py) —
deterministic, materialization-order
independent, and shard-consistent under SPMD (every shard of a param sees the
same key and XLA partitions the generation).  Statistical, not bitwise,
parity with torch eager init — by design.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import torch

from ..utils.dtypes import jnp_dtype_of

LOWERINGS: Dict[str, Callable] = {}


class UnsupportedOpError(RuntimeError):
    """Raised when a recorded op has no JAX lowering (caller falls back to
    torch replay + device_put)."""


def lowering(*names: str):
    def deco(fn):
        for name in names:
            LOWERINGS[name] = fn
        return fn

    return deco


def _jnp():
    import jax.numpy as jnp

    return jnp


def _dtype_or(kwargs, default):
    dt = kwargs.get("dtype")
    if dt is None:
        return default
    return jnp_dtype_of(dt) if isinstance(dt, torch.dtype) else dt


# ---------------------------------------------------------------------------
# Factories.  `ctx` provides: ctx.key (per-op PRNG key), ctx.out_meta(i)
# (the recorded meta tensor of output i: shape/dtype ground truth).


@lowering("aten.empty.memory_format", "aten.empty_strided.default",
          "aten.zeros.default", "aten.empty.default")
def _zeros(ctx, size, *args, **kwargs):
    # `empty` deliberately lowers to zeros: XLA has no uninitialized
    # allocation, and deterministic zeros keep replay reproducible.  A
    # recorded `empty` that a model READS without first writing would show
    # torch-eager garbage but zeros here — a documented divergence (such a
    # read is a bug in the model's init anyway).
    jnp = _jnp()
    dtype = _dtype_or(kwargs, jnp_dtype_of(ctx.out_meta(0).dtype))
    return jnp.zeros(tuple(size), dtype=dtype)


@lowering("aten.empty_like.default", "aten.zeros_like.default",
          "aten.new_empty.default", "aten.new_zeros.default")
def _zeros_like(ctx, x, *args, **kwargs):
    jnp = _jnp()
    meta = ctx.out_meta(0)
    return jnp.zeros(tuple(meta.shape), dtype=jnp_dtype_of(meta.dtype))


@lowering("aten.ones.default")
def _ones(ctx, size, **kwargs):
    jnp = _jnp()
    dtype = _dtype_or(kwargs, jnp_dtype_of(ctx.out_meta(0).dtype))
    return jnp.ones(tuple(size), dtype=dtype)


@lowering("aten.ones_like.default", "aten.new_ones.default")
def _ones_like(ctx, x, *args, **kwargs):
    jnp = _jnp()
    meta = ctx.out_meta(0)
    return jnp.ones(tuple(meta.shape), dtype=jnp_dtype_of(meta.dtype))


@lowering("aten.full.default")
def _full(ctx, size, fill_value, **kwargs):
    jnp = _jnp()
    dtype = _dtype_or(kwargs, jnp_dtype_of(ctx.out_meta(0).dtype))
    return jnp.full(tuple(size), fill_value, dtype=dtype)


@lowering("aten.full_like.default", "aten.new_full.default")
def _full_like(ctx, x, fill_value, **kwargs):
    jnp = _jnp()
    meta = ctx.out_meta(0)
    return jnp.full(tuple(meta.shape), fill_value, dtype=jnp_dtype_of(meta.dtype))


@lowering("aten.arange.default", "aten.arange.start", "aten.arange.start_step")
def _arange(ctx, *args, **kwargs):
    jnp = _jnp()
    meta = ctx.out_meta(0)
    start, end, step = 0, None, 1
    if len(args) == 1:
        (end,) = args
    elif len(args) == 2:
        start, end = args
    else:
        start, end, step = args[:3]
    return jnp.arange(start, end, step, dtype=jnp_dtype_of(meta.dtype))


@lowering("aten.eye.default", "aten.eye.m")
def _eye(ctx, n, m=None, **kwargs):
    jnp = _jnp()
    meta = ctx.out_meta(0)
    return jnp.eye(n, m, dtype=jnp_dtype_of(meta.dtype))


@lowering("aten.scalar_tensor.default")
def _scalar_tensor(ctx, value, **kwargs):
    jnp = _jnp()
    dtype = _dtype_or(kwargs, jnp_dtype_of(ctx.out_meta(0).dtype))
    return jnp.asarray(value, dtype=dtype)


# ---------------------------------------------------------------------------
# RNG ops (in-place on torch, pure here).
#
# The in-place fills (`uniform_`, `normal_`) draw into a FLAT buffer padded
# to the next power-of-two length ("bucket") and keep the first ``numel``
# values.  Two reasons:
#
# * shape-diverse models (a resnet has ~25 unique conv shapes) collapse onto
#   ~log₂(max numel) distinct RNG kernel shapes, so XLA compiles a handful of
#   generators instead of one per shape;
# * the grouped materializer's fill fast path (materialize.py) draws the same
#   buckets vmapped over whole parameter *populations* — threefry keys are
#   vmap-invariant, so the batched draw is bitwise equal to this per-op
#   replay, keeping materialize_tensor_jax == materialize_module_jax.


# Fills above this size draw EXACT lengths: they are excluded from pooling
# (materialize._plan_fill_bins imports this bound) because large params are
# few and shape-repeated, so padding would waste RNG compute and transient
# HBM for no kernel-shape dedup.
FILL_POOL_MAX = 1 << 20


def fill_bucket(numel: int) -> int:
    """Padded draw length for a fill of ``numel`` elements.

    Power-of-4 steps while small (padding is free, fewer distinct kernel
    shapes: 128, 512, 2048, 8192, 32768), power-of-2 up to the pooling
    bound (waste ≤2×), and exact above it (no pooling there — see
    FILL_POOL_MAX)."""
    if numel > FILL_POOL_MAX:
        return numel
    b = 128
    while b < numel:
        b <<= 2 if b < 16384 else 1
    return b


@lowering("aten.uniform_.default")
def _uniform_(ctx, x, from_=0.0, to=1.0, **kwargs):
    import jax

    flat = jax.random.uniform(
        ctx.key, (fill_bucket(x.size),), dtype=x.dtype,
        minval=from_, maxval=to,
    )
    return flat[: x.size].reshape(x.shape)


@lowering("aten.normal_.default")
def _normal_(ctx, x, mean=0.0, std=1.0, **kwargs):
    import jax

    flat = jax.random.normal(ctx.key, (fill_bucket(x.size),), dtype=x.dtype)
    return (flat * std + mean)[: x.size].reshape(x.shape)


@lowering("aten.randn.default")
def _randn(ctx, size, **kwargs):
    import jax

    dtype = _dtype_or(kwargs, jnp_dtype_of(ctx.out_meta(0).dtype))
    return jax.random.normal(ctx.key, tuple(size), dtype=dtype)


@lowering("aten.rand.default")
def _rand(ctx, size, **kwargs):
    import jax

    dtype = _dtype_or(kwargs, jnp_dtype_of(ctx.out_meta(0).dtype))
    return jax.random.uniform(ctx.key, tuple(size), dtype=dtype)


@lowering("aten.randint.default", "aten.randint.low")
def _randint(ctx, *args, **kwargs):
    import jax

    meta = ctx.out_meta(0)
    if len(args) == 2:
        low, (high, size) = 0, args
    else:
        low, high, size = args[:3]
    return jax.random.randint(
        ctx.key, tuple(size), low, high, dtype=jnp_dtype_of(meta.dtype)
    )


@lowering("aten.randperm.default")
def _randperm(ctx, n, **kwargs):
    import jax

    meta = ctx.out_meta(0)
    return jax.random.permutation(ctx.key, n).astype(jnp_dtype_of(meta.dtype))


@lowering("aten.bernoulli_.float")
def _bernoulli_(ctx, x, p=0.5, **kwargs):
    import jax

    return jax.random.bernoulli(ctx.key, p, x.shape).astype(x.dtype)


# ---------------------------------------------------------------------------
# Elementwise / in-place arithmetic (in-place variants are pure here; the
# engine scatters results back through the written tensor's layout).


def _binop(fn):
    def lowered(ctx, a, b, *, alpha=None, **kwargs):
        jnp = _jnp()
        if alpha is not None and alpha != 1:
            b = b * alpha
        out = fn(a, b)
        meta = ctx.out_meta(0)
        return out.astype(jnp_dtype_of(meta.dtype))

    return lowered


for _names, _fn in [
    (("aten.add.Tensor", "aten.add_.Tensor", "aten.add.Scalar",
      "aten.add_.Scalar"), lambda a, b: a + b),
    (("aten.sub.Tensor", "aten.sub_.Tensor", "aten.sub.Scalar",
      "aten.sub_.Scalar"), lambda a, b: a - b),
    (("aten.mul.Tensor", "aten.mul_.Tensor", "aten.mul.Scalar",
      "aten.mul_.Scalar"), lambda a, b: a * b),
    (("aten.div.Tensor", "aten.div_.Tensor", "aten.div.Scalar",
      "aten.div_.Scalar"), lambda a, b: a / b),
    # pow.Scalar is scalar-base ** tensor-exponent (HF Llama's RoPE
    # inv_freq: theta ** -(arange(0, d, 2)/d)).
    (("aten.pow.Tensor_Scalar", "aten.pow_.Scalar", "aten.pow.Scalar",
      "aten.pow.Tensor_Tensor"), lambda a, b: a**b),
]:
    LOWERINGS.update({n: _binop(_fn) for n in _names})


def _unop(fn):
    def lowered(ctx, x, *args, **kwargs):
        return fn(_jnp(), x, *args, **kwargs)

    return lowered


LOWERINGS.update(
    {
        "aten.zero_.default": _unop(lambda jnp, x: jnp.zeros_like(x)),
        "aten.fill_.Scalar": _unop(lambda jnp, x, v: jnp.full_like(x, v)),
        "aten.fill_.Tensor": _unop(lambda jnp, x, v: jnp.full_like(x, v)),
        "aten.neg.default": _unop(lambda jnp, x: -x),
        "aten.neg_.default": _unop(lambda jnp, x: -x),
        "aten.sqrt.default": _unop(lambda jnp, x: jnp.sqrt(x)),
        "aten.sqrt_.default": _unop(lambda jnp, x: jnp.sqrt(x)),
        "aten.rsqrt.default": _unop(lambda jnp, x: 1 / jnp.sqrt(x)),
        "aten.abs.default": _unop(lambda jnp, x: jnp.abs(x)),
        "aten.exp.default": _unop(lambda jnp, x: jnp.exp(x)),
        "aten.exp_.default": _unop(lambda jnp, x: jnp.exp(x)),
        "aten.log.default": _unop(lambda jnp, x: jnp.log(x)),
        "aten.tanh.default": _unop(lambda jnp, x: jnp.tanh(x)),
        "aten.sigmoid.default": _unop(lambda jnp, x: 1 / (1 + jnp.exp(-x))),
        "aten.tril.default": _unop(lambda jnp, x, k=0: jnp.tril(x, k)),
        "aten.tril_.default": _unop(lambda jnp, x, k=0: jnp.tril(x, k)),
        "aten.triu.default": _unop(lambda jnp, x, k=0: jnp.triu(x, k)),
        "aten.triu_.default": _unop(lambda jnp, x, k=0: jnp.triu(x, k)),
        "aten.reciprocal.default": _unop(lambda jnp, x: 1 / x),
    }
)


@lowering("aten.erfinv.default", "aten.erfinv_.default")
def _erfinv(ctx, x, **kwargs):
    from jax.scipy.special import erfinv

    return erfinv(x)


@lowering("aten.clamp.default", "aten.clamp_.default")
def _clamp(ctx, x, min=None, max=None, **kwargs):
    jnp = _jnp()
    return jnp.clip(x, min, max)


@lowering("aten.clamp_min.default", "aten.clamp_min_.default")
def _clamp_min(ctx, x, min, **kwargs):
    return _jnp().clip(x, min, None)


@lowering("aten.clamp_max.default", "aten.clamp_max_.default")
def _clamp_max(ctx, x, max, **kwargs):
    return _jnp().clip(x, None, max)


@lowering("aten.aminmax.default", "aten.aminmax.out")
def _aminmax(ctx, x, *, dim=None, keepdim=False, **kwargs):
    # out-variant: the min/max buffers arrive in kwargs; the replay engine
    # scatters each return into its own schema-aliased buffer.
    jnp = _jnp()
    axis = None if dim is None else dim
    return (
        jnp.amin(x, axis=axis, keepdims=keepdim),
        jnp.amax(x, axis=axis, keepdims=keepdim),
    )


@lowering("aten.copy_.default")
def _copy_(ctx, dst, src, non_blocking=False, **kwargs):
    jnp = _jnp()
    return jnp.broadcast_to(src, dst.shape).astype(dst.dtype)


@lowering("aten._to_copy.default", "aten.to.dtype", "aten.clone.default")
def _to_copy(ctx, x, **kwargs):
    meta = ctx.out_meta(0)
    return x.astype(jnp_dtype_of(meta.dtype))


@lowering("aten.cat.default")
def _cat(ctx, tensors, dim=0, **kwargs):
    return _jnp().concatenate(tensors, axis=dim)


@lowering("aten.stack.default")
def _stack(ctx, tensors, dim=0, **kwargs):
    return _jnp().stack(tensors, axis=dim)


@lowering("aten.mm.default", "aten.matmul.default", "aten.bmm.default")
def _mm(ctx, a, b, **kwargs):
    return a @ b


@lowering("aten.addmm.default")
def _addmm(ctx, bias, a, b, *, beta=1, alpha=1, **kwargs):
    return beta * bias + alpha * (a @ b)


@lowering("aten.outer.default")
def _outer(ctx, a, b, **kwargs):
    return _jnp().outer(a, b)


@lowering("aten.linalg_qr.default")
def _qr(ctx, x, mode="reduced", **kwargs):
    jnp = _jnp()
    q, r = jnp.linalg.qr(x, mode=mode)
    return [q, r]


@lowering("aten.sign.default")
def _sign(ctx, x, **kwargs):
    return _jnp().sign(x)


@lowering("aten.diag.default", "aten.diagonal.default")
def _diag(ctx, x, *args, **kwargs):
    return _jnp().diagonal(x, *args) if x.ndim > 1 else _jnp().diag(x)


@lowering("aten.repeat.default")
def _repeat(ctx, x, repeats, **kwargs):
    return _jnp().tile(x, tuple(repeats))


@lowering("aten._unsafe_view.default")
def _unsafe_view(ctx, x, size, **kwargs):
    # reshape-of-non-contiguous lowers to clone + _unsafe_view; unlike
    # aten.view it carries NO alias info (the clone is the only reader),
    # so it reaches the lowerings as a functional op rather than the
    # engine's layout-only view path.  Found by tests/test_tape_fuzz.py.
    return _jnp().reshape(x, tuple(size))
