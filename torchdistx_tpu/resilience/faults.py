"""Deterministic fault injection: ``TDX_FAULT="site:step:kind[,...]"``.

Proving crash/retry/skip paths with real process games (kill -9 at "about
the right time", flaky network mocks) makes resilience tests the least
reliable tests in a suite.  Instead, named *sites* in the training stack
ask this registry "do I fail now?" — the answer is a pure function of
the ``TDX_FAULT`` spec and the step number, so every CI run exercises
exactly the same failure at exactly the same step.

Grammar (comma-separated specs)::

    TDX_FAULT="site:step:kind[,site:step:kind...]"

Sites (where the stack asks):

* ``ckpt.save``  — inside ``Checkpointer.save``, before orbax runs (so a
  retry re-enters the site and succeeds once the spec is consumed).
* ``data.next``  — in ``fit()`` before pulling the next batch.
* ``step.exec``  — in ``fit()`` before executing the step.
* ``serve.admit`` — in the serving engine's admission phase, before any
  request is popped or any page allocated (step = admission attempt;
  ``nan`` skips the admission tick).
* ``serve.prefill`` — before the engine dispatches one request's
  prefill (step = prefill attempt).  ``io``/``nan`` return the request
  (and the rest of the admission batch) to the FIFO head; the next tick
  retries in order.
* ``serve.step``  — before the serving engine dispatches a decode chunk
  (step = decode-chunk number).  ``nan`` here means "this chunk is
  poisoned": the engine skips it cleanly and re-runs next tick.
* ``serve.recover`` — before one replay attempt of the engine's
  crash-recovery supervisor (step = replay attempt).  ``io``/``nan``
  fail that replay, consuming the request's recovery budget — the path
  that proves budgets exhaust into typed errors instead of hangs.
* ``serve.swap`` — before one swap-to-host page gather of the QoS
  preemption path (step = swap attempt).  ``io``/``nan`` fail the swap
  — the gather is read-only, so device state is untouched and the
  preemption falls back to drop-and-replay, still token-identical.
* ``serve.migrate_out`` — before one cross-engine stream-migration
  export (step = export attempt).  ``io``/``nan`` fail the export
  BEFORE the page gather: the source stream keeps running untouched —
  a failed export must never strand or double-serve a live stream.
* ``serve.migrate_in`` — mid-import of a migrated stream, after the
  destination allocated its pages but before the scatter (step = import
  attempt).  ``io``/``nan`` fail the import: the partial page set is
  freed on the destination (no leak) and the stream falls back to a
  cold key-pinned replay — no double-serve, token-identical either way.
* ``serve.materialize`` — before the model pool materializes one
  registered model's weights (step = materialize attempt).  ``io``/
  ``nan`` fail that attempt: the model stays a skeleton (no partial
  weights, no ledger row) and the next tick with demand retries;
  ``crash`` is the kill-mid-materialize drill — the process dies with
  nothing registered, so recovery starts from the skeleton.
* ``journal.append`` — before one request-journal record append (step
  = append attempt).  ``io`` fails that append: the engine counts
  ``journal.append_errors`` and keeps serving — durability is
  best-effort once the disk itself fails; ``crash`` dies before the
  record lands (the torn-tail / lost-record drill).
* ``journal.fsync`` — before one journal fsync (step = fsync attempt).
  ``io`` degrades the journal to ``fsync=async`` with a
  ``journal.fsync_degraded`` counter — a slow or failing disk must
  never block the tick.
* ``journal.recover`` — before one cold-restart journal scan (step =
  recover attempt).  ``io`` fails that recovery loudly — nothing is
  half-resumed; the caller retries or escalates.

Kinds (what happens):

* ``io``      — raise :class:`InjectedFault` (an ``OSError``: retryable
  under the default :class:`~torchdistx_tpu.resilience.retry.RetryPolicy`).
* ``fatal``   — raise :class:`FatalInjectedFault` (a ``RuntimeError``:
  NOT retryable; proves fatal errors propagate).
* ``crash``   — ``os._exit(CRASH_EXIT_CODE)``: a hard kill, no ``finally``
  blocks, no atexit — the SIGKILL/power-loss simulation.
* ``sigterm`` — ``os.kill(os.getpid(), SIGTERM)``: a real signal through
  the real handler — the preemption simulation.
* ``nan``     — needs caller cooperation (returned, not raised).  At
  ``step.exec``, ``fit()`` poisons the step's loss (via the reserved
  ``_tdx_nan`` batch key understood by ``make_train_step``) so the
  jit-side non-finite guard trips; at ``serve.step`` the serving engine
  treats the decode chunk as poisoned and skips it.
* ``corrupt`` — needs caller cooperation (returned, not raised).  At
  ``serve.step`` the engine runs the decode chunk normally, then flips
  ONE committed token (first decoding slot, first token of the chunk,
  XOR 1) on the host — a **silent** determinism break: nothing raises,
  nothing retries, the stream stays plausible.  The only thing that
  can catch it is the audit plane (the shadow auditor's digest
  comparison — docs/observability.md, "Audit plane"), which is exactly
  what this kind exists to prove.  At other cooperation-checking sites
  it is treated like ``nan`` (the attempt is poisoned and skipped).

``step`` is the 1-based global step number.  Each spec fires ONCE (the
first time its site+step matches), so a retried site succeeds on the
next attempt; every firing bumps the ``faults.fired`` counter — and,
when telemetry is recording, emits a ``fault.fired`` event carrying
``site``/``step``/``kind``, so a flight dump names the fault sites an
incident replay must re-arm to reproduce the run.
"""

from __future__ import annotations

import os
import signal
import threading
from dataclasses import dataclass, field
from typing import List, Optional

from .. import telemetry as _telemetry

__all__ = [
    "CRASH_EXIT_CODE",
    "FatalInjectedFault",
    "FaultSpec",
    "InjectedFault",
    "active",
    "fire",
    "parse_faults",
    "reset",
]

ENV_VAR = "TDX_FAULT"
CRASH_EXIT_CODE = 13
SITES = frozenset(
    {
        "ckpt.save",
        "data.next",
        "step.exec",
        "serve.admit",
        "serve.prefill",
        "serve.step",
        "serve.recover",
        "serve.swap",
        "serve.migrate_out",
        "serve.migrate_in",
        "serve.materialize",
        "journal.append",
        "journal.fsync",
        "journal.recover",
    }
)
KINDS = frozenset({"io", "fatal", "crash", "sigterm", "nan", "corrupt"})

_T_FIRED = _telemetry.counter("faults.fired")


class InjectedFault(OSError):
    """A transient injected failure (retryable by default policies)."""


class FatalInjectedFault(RuntimeError):
    """An injected failure no policy should retry."""


@dataclass
class FaultSpec:
    site: str
    step: int
    kind: str
    fired: bool = field(default=False, compare=False)


def parse_faults(text: str) -> List[FaultSpec]:
    """Parse a ``TDX_FAULT`` value; raises ``ValueError`` on bad grammar
    (a mistyped injection silently doing nothing would "pass" CI)."""
    specs: List[FaultSpec] = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        pieces = part.split(":")
        if len(pieces) != 3:
            raise ValueError(
                f"TDX_FAULT spec {part!r}: expected 'site:step:kind'"
            )
        site, step_s, kind = (p.strip() for p in pieces)
        if site not in SITES:
            raise ValueError(
                f"TDX_FAULT spec {part!r}: unknown site {site!r} "
                f"(sites: {sorted(SITES)})"
            )
        if kind not in KINDS:
            raise ValueError(
                f"TDX_FAULT spec {part!r}: unknown kind {kind!r} "
                f"(kinds: {sorted(KINDS)})"
            )
        try:
            step = int(step_s)
        except ValueError:
            raise ValueError(
                f"TDX_FAULT spec {part!r}: step {step_s!r} is not an int"
            ) from None
        if step < 1:
            raise ValueError(
                f"TDX_FAULT spec {part!r}: step must be >= 1 (1-based)"
            )
        specs.append(FaultSpec(site, step, kind))
    return specs


class _Registry:
    """Process singleton, lazily seeded from ``TDX_FAULT``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._specs: Optional[List[FaultSpec]] = None

    def _ensure(self) -> List[FaultSpec]:
        if self._specs is None:
            with self._lock:
                if self._specs is None:
                    text = os.environ.get(ENV_VAR, "")
                    self._specs = parse_faults(text) if text else []
        return self._specs

    def reset(self, text: Optional[str] = None) -> None:
        """Reload from ``text`` (tests) or from the environment."""
        with self._lock:
            self._specs = parse_faults(text) if text is not None else None

    def active(self) -> bool:
        return bool(self._ensure())

    def check(self, site: str, step: int) -> Optional[str]:
        """Consume and return the kind of the first unfired matching
        spec, or None.  Does not act on the kind."""
        specs = self._ensure()
        if not specs:  # fast path: registry empty in production
            return None
        with self._lock:
            for spec in specs:
                if not spec.fired and spec.site == site and spec.step == step:
                    spec.fired = True
                    _T_FIRED.add()
                    return spec.kind
        return None


_registry = _Registry()

reset = _registry.reset
active = _registry.active


def fire(site: str, step: int) -> Optional[str]:
    """Ask the registry whether to fail at ``site`` for ``step`` — and
    act: raise for ``io``/``fatal``, hard-exit for ``crash``, signal for
    ``sigterm``.  Kinds that need caller cooperation (``nan``) are
    returned; None means "no fault here".
    """
    kind = _registry.check(site, step)
    if kind is None:
        return None
    # Recorded BEFORE acting (a crash kind never returns): the trace —
    # and any flight dump cut from it — names the injected fault, so an
    # incident replay can re-arm the exact same schedule.
    _telemetry.event("fault.fired", site=site, step=step, kind=kind)
    if kind == "io":
        raise InjectedFault(f"injected io fault at {site}:{step}")
    if kind == "fatal":
        raise FatalInjectedFault(f"injected fatal fault at {site}:{step}")
    if kind == "crash":
        os._exit(CRASH_EXIT_CODE)
    if kind == "sigterm":
        # A REAL signal through the real handler chain: the preemption
        # path under test is the production path, not a mock of it.
        os.kill(os.getpid(), signal.SIGTERM)
        return None
    return kind
