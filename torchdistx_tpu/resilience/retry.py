"""Retry transient failures: exponential backoff + jitter, capped.

Checkpoint IO (orbax over GCS/NFS) and data loading fail transiently all
the time on long runs; one hiccup must not kill hours of training.  A
:class:`RetryPolicy` classifies exceptions into retryable/fatal, sleeps
an exponentially growing, jittered delay between attempts, and gives up
after ``max_attempts`` tries or a wall-clock ``deadline_s`` — raising
:class:`RetriesExhausted` chained to the last underlying error so the
root cause stays in the traceback.

Classification is three-layered, most-specific first: an explicit
boolean ``retryable`` attribute on the exception is authoritative (the
:class:`~torchdistx_tpu.serving.lifecycle.RequestError` contract — the
raiser knows better than any heuristic, so the serving fleet router,
checkpoint IO, and data IO all share this one classification path);
then an ``isinstance`` check against ``retryable`` (default ``OSError``,
which covers ``ConnectionError`` and ``TimeoutError``); then a *name*
match against ``retryable_names`` for backend exception types this
package must not import (grpc/GCS/orbax transport errors surface with
names like ``Unavailable`` or ``DeadlineExceeded`` but live in optional
dependencies).  The attribute layer is what keeps a serving
``DeadlineExceeded`` (``retryable=False``) from colliding with grpc's
transient status of the same name.

Every granted retry can bump a telemetry counter supplied by the call
site (``ckpt.retries``, ``data.retries``), so recovery is visible in
traces instead of silently absorbed.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, FrozenSet, Optional, Tuple, Type

__all__ = ["RetriesExhausted", "RetryPolicy", "DEFAULT_RETRYABLE_NAMES"]

# Transport-layer exception *names* treated as transient (grpc status
# classes, GCS/orbax wrappers) — matched when the type isn't importable
# here.  Deliberately conservative: nothing that can mean corrupt data.
DEFAULT_RETRYABLE_NAMES: FrozenSet[str] = frozenset(
    {
        "Aborted",
        "DeadlineExceeded",
        "InternalServerError",
        "ResourceExhausted",
        "RetryError",
        "ServiceUnavailable",
        "TooManyRequests",
        "Unavailable",
    }
)


class RetriesExhausted(RuntimeError):
    """All attempts failed; ``__cause__`` is the last underlying error."""


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + jitter with attempt and deadline caps.

    ``delay(k)`` for the k-th granted retry (0-based) is
    ``min(max_delay_s, base_delay_s * 2**k)`` scaled by a uniform random
    factor in ``[1 - jitter, 1]`` (decorrelates clients hammering the
    same recovering endpoint).  ``deadline_s`` bounds the *total* wall
    clock across attempts: a retry whose sleep would cross the deadline
    is not granted.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.1
    max_delay_s: float = 30.0
    deadline_s: Optional[float] = None
    jitter: float = 0.5
    retryable: Tuple[Type[BaseException], ...] = (OSError,)
    retryable_names: FrozenSet[str] = field(
        default=DEFAULT_RETRYABLE_NAMES
    )

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")

    def is_retryable(self, exc: BaseException) -> bool:
        # An explicit boolean `retryable` attribute wins outright: the
        # raiser's own classification (the RequestError contract) must
        # not be overridden by an isinstance or name coincidence.
        flag = getattr(exc, "retryable", None)
        if isinstance(flag, bool):
            return flag
        if isinstance(exc, self.retryable):
            return True
        return type(exc).__name__ in self.retryable_names

    def delay(self, attempt: int) -> float:
        """Sleep before the ``attempt``-th retry (0-based), jittered."""
        base = min(self.max_delay_s, self.base_delay_s * (2.0 ** attempt))
        return base * (1.0 - self.jitter * random.random())

    def call(
        self,
        fn: Callable,
        *args,
        counter=None,
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
        site: str = "",
        **kwargs,
    ):
        """Run ``fn(*args, **kwargs)``, retrying retryable failures.

        ``counter`` (a ``telemetry.Counter``) is bumped once per granted
        retry; ``on_retry(attempt, exc)`` is called just before the
        sleep.  Non-retryable exceptions propagate unchanged on the
        first failure.
        """
        deadline = (
            time.monotonic() + self.deadline_s
            if self.deadline_s is not None
            else None
        )
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except Exception as exc:
                if not self.is_retryable(exc):
                    raise
                attempt += 1
                if attempt >= self.max_attempts:
                    raise RetriesExhausted(
                        f"{site or getattr(fn, '__name__', 'call')}: "
                        f"{attempt} attempt(s) failed; last: {exc!r}"
                    ) from exc
                pause = self.delay(attempt - 1)
                if deadline is not None and (
                    time.monotonic() + pause > deadline
                ):
                    raise RetriesExhausted(
                        f"{site or getattr(fn, '__name__', 'call')}: "
                        f"deadline {self.deadline_s}s exceeded after "
                        f"{attempt} attempt(s); last: {exc!r}"
                    ) from exc
                if counter is not None:
                    counter.add()
                if on_retry is not None:
                    on_retry(attempt, exc)
                time.sleep(pause)
