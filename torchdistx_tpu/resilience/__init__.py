"""Resilience: preemption-safe training, retrying IO, non-finite guards,
and deterministic fault injection.

Long runs on preemptible TPU fleets fail in exactly four boring ways —
the scheduler reclaims the VM (SIGTERM), checkpoint/data IO hiccups
(transient orbax/GCS errors), a step produces non-finite loss/grads, and
"it crashed and must resume where it left off".  This package makes each
of those a first-class, *observable* path:

* :mod:`~torchdistx_tpu.resilience.retry` — :class:`RetryPolicy`:
  exponential backoff + jitter with attempt/deadline caps and
  retryable-exception classification, applied to checkpoint IO and the
  ``fit()`` data iterator (``ckpt.retries`` / ``data.retries`` counters).
* :mod:`~torchdistx_tpu.resilience.preemption` — SIGTERM/SIGINT handlers
  that set a flag checked at every step boundary; on preemption ``fit()``
  checkpoints the current step, flushes telemetry, and returns resumably
  (multihost: the flag is agreed via
  :func:`torchdistx_tpu.parallel.distributed.any_flag`).
* :mod:`~torchdistx_tpu.resilience.guard` — jit-side finiteness check
  over loss+grads with skip-step semantics (prior state returned
  unchanged, ``train.skipped_steps`` bumped) and host-side escalation
  (:class:`NonFiniteError` after K consecutive skips).
* :mod:`~torchdistx_tpu.resilience.faults` — deterministic fault
  injection (``TDX_FAULT="site:step:kind"``) so tests and CI prove the
  crash/retry/skip paths without flaky process games.

The same machinery extends into the serving stack
(:mod:`torchdistx_tpu.serving.lifecycle`): the preemption flag drives
the engine's graceful drain, the fault registry covers the
``serve.admit``/``serve.prefill``/``serve.step``/``serve.recover``
sites, and a crash-recovery supervisor replays in-flight requests
token-identically after failed device calls — request-lifecycle
robustness (deadlines, cancellation, overload shedding) rides on top.

Like :mod:`~torchdistx_tpu.telemetry`, the package is dependency-free at
module level (stdlib only; jax imports live inside the functions that
need them), so it is importable in the torch-only environment.

See ``docs/resilience.md`` for semantics and knobs.
"""

from .faults import (  # noqa: F401
    CRASH_EXIT_CODE,
    FaultSpec,
    InjectedFault,
    parse_faults,
)
from .guard import NonFiniteError, SkipTracker, select_tree, tree_allfinite  # noqa: F401
from .retry import RetriesExhausted, RetryPolicy  # noqa: F401
from . import faults, guard, preemption, retry  # noqa: F401

__all__ = [
    "CRASH_EXIT_CODE",
    "FaultSpec",
    "InjectedFault",
    "NonFiniteError",
    "RetriesExhausted",
    "RetryPolicy",
    "SkipTracker",
    "faults",
    "guard",
    "parse_faults",
    "preemption",
    "retry",
    "select_tree",
    "tree_allfinite",
]
