"""Non-finite step guard: skip poisoned steps, escalate when they persist.

One NaN/Inf gradient silently corrupts optimizer moments forever — every
later step inherits the poison.  The guard splits into a jit-side check
and a host-side policy:

* **jit-side** (:func:`tree_allfinite` + :func:`select_tree`, wired into
  ``make_train_step``): an all-reduced finiteness check over loss and
  every gradient leaf — ``isfinite(x).all()`` over sharded arrays, so
  the SPMD partitioner inserts the cross-device reduction — selecting
  the PRIOR state when the step is poisoned.  Skipped steps leave
  params, optimizer moments, and the step counter bit-identical to
  before the step; the metrics dict carries ``nonfinite`` so the host
  can see it.
* **host-side** (:class:`SkipTracker`, used by ``fit()``): bumps the
  ``train.skipped_steps`` counter per skip and raises
  :class:`NonFiniteError` after ``max_consecutive`` skips in a row — a
  persistently diverging run must fail loudly (lower the LR, inspect
  the data), not spin forever skipping.

The module is import-light (jax only inside functions) to keep the
resilience package importable in the torch-only environment.
"""

from __future__ import annotations

from typing import Any

from .. import telemetry as _telemetry

__all__ = ["NonFiniteError", "SkipTracker", "select_tree", "tree_allfinite"]

_T_SKIPPED = _telemetry.counter("train.skipped_steps")


class NonFiniteError(RuntimeError):
    """Raised after ``max_consecutive`` non-finite steps in a row."""

    def __init__(self, step: int, consecutive: int):
        self.step = step
        self.consecutive = consecutive
        super().__init__(
            f"{consecutive} consecutive non-finite training step(s), "
            f"last at step {step}: loss/grads contain NaN or Inf and "
            "skipping is not recovering — stopping so the run can be "
            "restarted from the last checkpoint with different "
            "hyperparameters."
        )


def tree_allfinite(*trees: Any):
    """Scalar bool: every inexact-dtype leaf of every tree is finite.

    Traced under jit this lowers to per-leaf ``isfinite().all()``
    reductions; on sharded leaves XLA all-reduces across devices, so
    every shard agrees on the verdict (the "all-reduced finiteness
    check").  Integer/bool leaves are skipped — they cannot be
    non-finite.
    """
    import jax
    import jax.numpy as jnp

    ok = jnp.asarray(True)
    for tree in trees:
        for leaf in jax.tree.leaves(tree):
            arr = jnp.asarray(leaf)
            if jnp.issubdtype(arr.dtype, jnp.inexact):
                ok = ok & jnp.isfinite(arr).all()
    return ok


def select_tree(ok, new: Any, old: Any) -> Any:
    """``new`` where ``ok`` else ``old``, leafwise (skip-step select).

    Both trees must share structure (they are the post- and pre-step
    TrainState).  ``jnp.where`` with a scalar predicate compiles to a
    select per leaf — no host sync, donation-compatible.
    """
    import jax
    import jax.numpy as jnp

    return jax.tree.map(lambda n, o: jnp.where(ok, n, o), new, old)


class SkipTracker:
    """Host-side escalation policy over the per-step ``nonfinite`` flag.

    ``observe(skipped, step)`` bumps ``train.skipped_steps`` and raises
    :class:`NonFiniteError` once ``max_consecutive`` skips arrive with
    no finite step in between.  ``max_consecutive <= 0`` disables
    escalation (skips are still counted).
    """

    def __init__(self, max_consecutive: int = 8):
        self.max_consecutive = max_consecutive
        self.consecutive = 0
        self.total = 0

    def observe(self, skipped: bool, step: int) -> None:
        if not skipped:
            self.consecutive = 0
            return
        self.total += 1
        self.consecutive += 1
        _T_SKIPPED.add()
        if 0 < self.max_consecutive <= self.consecutive:
            raise NonFiniteError(step, self.consecutive)
