"""Preemption flag: SIGTERM/SIGINT → a boolean checked at step boundaries.

Preemptible TPU VMs get SIGTERM with a grace window; Ctrl-C is the
interactive equivalent.  A signal handler must not checkpoint (it can
interrupt arbitrary code, including orbax mid-write) — it only sets a
flag here, and the flag's consumers act at their own safe boundaries:
the training loop (:func:`torchdistx_tpu.parallel.fit`) checks it at
each step boundary, where state is consistent, saves a final
checkpoint, flushes telemetry, and returns resumably; the serving
engine (:class:`torchdistx_tpu.serving.Engine`) checks it at each tick
and moves through its graceful-drain state machine — admission closed,
in-flight requests finished within the drain deadline, the remainder
failed with a retryable typed error.  Both clear the flag once acted
on (a platform that is really going down keeps signalling).

Semantics:

* :func:`install` is idempotent, chains to previously installed
  handlers, and degrades gracefully off the main thread (signal
  handlers can only be installed there; callers in worker threads get
  ``False`` and rely on :func:`request`).
* The FIRST signal sets the flag.  A SECOND signal of the same kind
  escalates to the previous handler — so a double Ctrl-C still raises
  ``KeyboardInterrupt`` and a double SIGTERM still runs the outer
  framework's handler; graceful draining never traps the operator.
* :func:`request` sets the flag programmatically — for tests and for
  cluster preemption-notice APIs (GCE metadata watcher, k8s preStop)
  that learn about preemption without a signal.

Multihost note: the flag is HOST-LOCAL (the scheduler may signal hosts
at different times).  ``fit()`` agrees on it across hosts with
:func:`torchdistx_tpu.parallel.distributed.any_flag` before acting, so
every host checkpoints the same step.

Each signal received bumps the ``preempt.signals`` telemetry counter.
"""

from __future__ import annotations

import signal
import threading
from typing import Dict, Iterable

from .. import telemetry as _telemetry

__all__ = [
    "clear",
    "install",
    "installed",
    "request",
    "requested",
    "uninstall",
]

_T_SIGNALS = _telemetry.counter("preempt.signals")

_flag = threading.Event()
_lock = threading.Lock()
_prev_handlers: Dict[int, object] = {}


def _handler(signum, frame):
    if _flag.is_set():
        # Second signal: escalate to whoever was installed before us
        # (default SIGINT raises KeyboardInterrupt; SIG_DFL for SIGTERM
        # means the caller really wants out — re-raise via the default).
        prev = _prev_handlers.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            signal.signal(signum, signal.SIG_DFL)
            signal.raise_signal(signum)
        return
    _flag.set()
    _T_SIGNALS.add()


def install(
    signals: Iterable[int] = (signal.SIGTERM, signal.SIGINT),
) -> bool:
    """Install the flag-setting handlers.  Idempotent; returns False
    (without raising) off the main thread, where handlers cannot be
    installed — callers there use :func:`request` instead."""
    with _lock:
        try:
            for sig in signals:
                if sig in _prev_handlers:
                    continue  # already ours
                _prev_handlers[sig] = signal.signal(sig, _handler)
        except ValueError:  # not the main thread
            return False
        return True


def uninstall() -> None:
    """Restore the previously installed handlers.

    A previous handler that ``signal.signal`` cannot re-install (it
    returned None for a C-installed handler) is replaced by ``SIG_DFL``
    — leaving OUR handler silently installed while the bookkeeping says
    otherwise would make a later :func:`install` record ``_handler`` as
    its own "previous" handler and recurse on escalation.  Off the main
    thread (``ValueError``) nothing can be restored: the entry is kept
    so :func:`installed` stays truthful.
    """
    with _lock:
        for sig, prev in list(_prev_handlers.items()):
            try:
                signal.signal(sig, prev)
            except ValueError:  # not the main thread: nothing restorable
                continue
            except TypeError:
                try:
                    signal.signal(sig, signal.SIG_DFL)
                except (ValueError, OSError):
                    continue
            del _prev_handlers[sig]


def installed() -> bool:
    return bool(_prev_handlers)


def requested() -> bool:
    """True once a preemption signal (or :func:`request`) arrived."""
    return _flag.is_set()


def request() -> None:
    """Set the flag programmatically (tests, preemption-notice APIs)."""
    _flag.set()


def clear() -> None:
    """Reset the flag (tests; a new run in the same process)."""
    _flag.clear()
