"""Persistent XLA compilation cache for init programs.

Materialization cost is dominated by XLA compile time (the init program
itself executes in milliseconds); the grouped materializer deliberately emits
HLO that is stable across processes — the RNG base key and per-node stream
identities enter as traced inputs rather than baked constants (see _tape.py's
tape-relative numbering) — precisely so JAX's persistent compilation cache
can hit on re-runs.  A training job that restarts (preemption, resharding,
hyperparameter sweeps) re-materializes the same architecture and pays only
trace + cache-lookup time.

Enabled on first materialization unless the user configured a cache dir
themselves (their setting wins) or disabled it via
``TDX_NO_COMPILATION_CACHE=1``.  The default location honors
``JAX_COMPILATION_CACHE_DIR`` and falls back to
``~/.cache/torchdistx_tpu/xla_cache``.
"""

from __future__ import annotations

import os
import threading

from .. import telemetry as _telemetry

# 1 once the persistent cache is configured, 0 when skipped (CPU backend,
# TDX_NO_COMPILATION_CACHE, setup failure); unset until first
# materialization.  A user-configured jax cache dir also reads 1 — the
# cache is on, just not ours to manage.  The exec-tier hit/miss counters
# live in materialize (materialize.exec_cache_*): JAX does not expose
# per-compile persistent-cache hit events to instrument here.
_T_ENABLED = _telemetry.gauge("compilation_cache.enabled")
# Swallowed cache-management failures (setup, threshold save/restore).
# The cache is a pure optimization — errors must never fail the caller —
# but silent degradation (every compile suddenly cold) must still be
# visible in traces, so every swallowed exception counts here.
_T_ERRORS = _telemetry.counter("compile_cache.errors")

_lock = threading.Lock()
_done = False
# cache_everything refcount state (guarded by _lock).
_ce_depth = 0
_ce_saved: list = []


def ensure_compilation_cache() -> None:
    global _done
    if _done:
        return
    with _lock:
        if _done:
            return
        _done = True
        # Arm the compile observatory with the cache: both exist because
        # compile time dominates materialization cost, and every entry
        # point that configures one should see the other's metrics
        # (docs/observability.md, "Perf plane").
        from ..telemetry import perf as _perf

        _perf.install_monitoring()
        _T_ENABLED.set(0)
        if os.environ.get("TDX_NO_COMPILATION_CACHE"):
            return
        try:
            import jax

            if jax.config.jax_compilation_cache_dir:
                _T_ENABLED.set(1)
                return  # user configured their own — leave it alone
            if jax.default_backend() == "cpu":
                # CPU executables are AOT-compiled against the build host's
                # exact machine features; reloading them elsewhere warns (or
                # SIGILLs).  The cache's value is on accelerators, where
                # executables are device-kind-portable.
                return
            cache_dir = os.environ.get(
                "JAX_COMPILATION_CACHE_DIR"
            ) or os.path.expanduser("~/.cache/torchdistx_tpu/xla_cache")
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            _T_ENABLED.set(1)
        except Exception:
            # Cache is a pure optimization — never fail materialization
            # over it (read-only HOME, old jax flag names, ...).
            _T_ERRORS.add()


class cache_everything:
    """Scope JAX's persistent-cache admission thresholds to one region.

    Init programs are individually cheap to compile (~100ms per unique
    signature) — below JAX's default min-compile-time admission bar — but
    numerous, so the materializer wants them all cached.  Applying the
    thresholds process-globally would also serialize every tiny throwaway
    jit and every multi-hundred-MB train-step executable the *user*
    compiles; scoping keeps the aggressive admission local to
    materialization.

    The thresholds are process-global jax.config state, so the save/restore
    is refcounted under the module lock: overlapping regions (concurrent
    materializations) share the OUTERMOST save and restore once, instead of
    racing each other into a corrupted restore.  Compiles issued by
    unrelated threads while any region is open are still admitted under the
    aggressive thresholds — inherent to global config, harmless (extra cache
    entries).
    """

    _FLAGS = (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    )

    def __enter__(self):
        global _ce_depth, _ce_saved
        with _lock:
            _ce_depth += 1
            if _ce_depth == 1:
                _ce_saved = []
                try:
                    import jax

                    for name, value in self._FLAGS:
                        _ce_saved.append((name, getattr(jax.config, name)))
                        jax.config.update(name, value)
                except Exception:
                    # Partial failure (e.g. a flag renamed in a newer jax):
                    # roll back what WAS applied rather than leaving the
                    # aggressive thresholds process-global.
                    _T_ERRORS.add()
                    try:
                        import jax

                        for name, value in _ce_saved:
                            jax.config.update(name, value)
                    except Exception:
                        _T_ERRORS.add()
                    _ce_saved = []
        return self

    def __exit__(self, *exc):
        global _ce_depth, _ce_saved
        with _lock:
            _ce_depth -= 1
            if _ce_depth == 0:
                try:
                    import jax

                    for name, value in _ce_saved:
                        jax.config.update(name, value)
                except Exception:
                    _T_ERRORS.add()
                _ce_saved = []
        return False
