"""Persistent XLA compilation cache for init programs.

Materialization cost is dominated by XLA compile time (the init program
itself executes in milliseconds); the grouped materializer deliberately emits
HLO that is stable across processes — the RNG base key and per-node stream
identities enter as traced inputs rather than baked constants (see _tape.py's
tape-relative numbering) — precisely so JAX's persistent compilation cache
can hit on re-runs.  A training job that restarts (preemption, resharding,
hyperparameter sweeps) re-materializes the same architecture and pays only
trace + cache-lookup time.

Enabled on first materialization unless the user configured a cache dir
themselves (their setting wins) or disabled it via
``TDX_NO_COMPILATION_CACHE=1``.  The default location honors
``JAX_COMPILATION_CACHE_DIR`` and falls back to
``~/.cache/torchdistx_tpu/xla_cache``.
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_done = False


def ensure_compilation_cache() -> None:
    global _done
    if _done:
        return
    with _lock:
        if _done:
            return
        _done = True
        if os.environ.get("TDX_NO_COMPILATION_CACHE"):
            return
        try:
            import jax

            if jax.config.jax_compilation_cache_dir:
                return  # user configured their own — leave it alone
            if jax.default_backend() == "cpu":
                # CPU executables are AOT-compiled against the build host's
                # exact machine features; reloading them elsewhere warns (or
                # SIGILLs).  The cache's value is on accelerators, where
                # executables are device-kind-portable.
                return
            cache_dir = os.environ.get(
                "JAX_COMPILATION_CACHE_DIR"
            ) or os.path.expanduser("~/.cache/torchdistx_tpu/xla_cache")
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        except Exception:
            # Cache is a pure optimization — never fail materialization
            # over it (read-only HOME, old jax flag names, ...).
            pass


class cache_everything:
    """Scope JAX's persistent-cache admission thresholds to one region.

    Init programs are individually cheap to compile (~100ms per unique
    signature) — below JAX's default min-compile-time admission bar — but
    numerous, so the materializer wants them all cached.  Applying the
    thresholds process-globally would also serialize every tiny throwaway
    jit and every multi-hundred-MB train-step executable the *user*
    compiles; scoping keeps the aggressive admission local to
    materialization.
    """

    _FLAGS = (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    )

    def __enter__(self):
        self._saved = []
        try:
            import jax

            for name, value in self._FLAGS:
                self._saved.append((name, getattr(jax.config, name)))
                jax.config.update(name, value)
        except Exception:
            self._saved = []
        return self

    def __exit__(self, *exc):
        try:
            import jax

            for name, value in self._saved:
                jax.config.update(name, value)
        except Exception:
            pass
        return False
