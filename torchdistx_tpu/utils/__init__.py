from .dtypes import jnp_dtype_of, torch_dtype_of  # noqa: F401
