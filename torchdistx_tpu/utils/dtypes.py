"""torch ↔ jax dtype mapping."""

from __future__ import annotations

import functools

import torch


@functools.cache
def _tables():
    import jax.numpy as jnp
    import numpy as np

    t2j = {
        torch.float32: jnp.float32,
        torch.float64: jnp.float64,
        torch.float16: jnp.float16,
        torch.bfloat16: jnp.bfloat16,
        torch.int8: jnp.int8,
        torch.int16: jnp.int16,
        torch.int32: jnp.int32,
        torch.int64: jnp.int64,
        torch.uint8: jnp.uint8,
        torch.bool: jnp.bool_,
        torch.complex64: jnp.complex64,
        torch.complex128: jnp.complex128,
    }
    j2t = {np.dtype(j): t for t, j in t2j.items()}
    return t2j, j2t


def jnp_dtype_of(torch_dtype: torch.dtype):
    t2j, _ = _tables()
    try:
        return t2j[torch_dtype]
    except KeyError:
        raise TypeError(f"No JAX dtype for {torch_dtype}") from None


def torch_dtype_of(jnp_dtype) -> torch.dtype:
    import numpy as np

    _, j2t = _tables()
    try:
        return j2t[np.dtype(jnp_dtype)]
    except KeyError:
        raise TypeError(f"No torch dtype for {jnp_dtype}") from None
