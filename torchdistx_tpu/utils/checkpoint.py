"""Checkpoint/resume for sharded training state (orbax).

The reference checkpoints only the SlowMo optimizer state through
``state_dict``/``load_state_dict`` + ``torch.save`` (slowmo_optimizer.py:
156-189, round-trip tested at test_slowmo_fsdp.py:283-300).  Here the whole
:class:`~torchdistx_tpu.parallel.train_step.TrainState` is one pytree of
(possibly sharded) ``jax.Array``s, so checkpointing is orbax over the tree:
each host writes its own shards (OCDBT), and restore places shards directly
onto the mesh via an abstract target — no full-tensor host round-trip, the
same discipline as sharded materialization.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from .. import telemetry as _telemetry
from ..resilience import faults as _faults
from ..resilience.retry import RetryPolicy

__all__ = ["save_state", "restore_state", "latest_step", "Checkpointer"]

# Granted retries of checkpoint IO (save dispatch + restore), visible in
# traces so flaky storage degrades loudly instead of silently.
_T_CKPT_RETRIES = _telemetry.counter("ckpt.retries")


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def save_state(path: str | os.PathLike, state: Any, *, force: bool = False):
    """Write ``state`` (any pytree of arrays) to ``path``."""
    ckptr = _ocp().StandardCheckpointer()
    ckptr.save(os.fspath(path), state, force=force)
    ckptr.wait_until_finished()


def restore_state(
    path: str | os.PathLike,
    *,
    target: Optional[Any] = None,
    shardings: Optional[Any] = None,
):
    """Restore a pytree from ``path``.

    ``target``: abstract pytree (``jax.ShapeDtypeStruct`` leaves) or a
    concrete example; with ``shardings`` (matching pytree of
    ``NamedSharding``), restored arrays are placed directly as shards on
    the mesh.
    """
    import jax

    ckptr = _ocp().StandardCheckpointer()
    if target is not None and shardings is not None:
        abstract = jax.tree.map(
            lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
            target,
            shardings,
        )
        return ckptr.restore(os.fspath(path), abstract)
    if target is not None:
        abstract = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), target
        )
        return ckptr.restore(os.fspath(path), abstract)
    return ckptr.restore(os.fspath(path))


class Checkpointer:
    """Step-numbered checkpoint manager for a training run.

    ``Checkpointer(dir).save(step, state)`` keeps the ``max_to_keep`` most
    recent steps; ``restore_latest(target=...)`` resumes.

    ``retry`` (a :class:`~torchdistx_tpu.resilience.retry.RetryPolicy`)
    makes save dispatch and restore survive transient IO errors —
    attempts beyond the first bump the ``ckpt.retries`` counter.  Saves
    are safe to re-enter: orbax writes into a temporary step directory
    and commits atomically, so a failed attempt leaves no committed
    step behind.
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        *,
        max_to_keep: int = 3,
        retry: Optional[RetryPolicy] = None,
    ):
        ocp = _ocp()
        self._retry = retry
        self._mgr = ocp.CheckpointManager(
            os.fspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def _call(self, fn, *, site: str):
        if self._retry is None:
            return fn()
        return self._retry.call(fn, counter=_T_CKPT_RETRIES, site=site)

    def save(self, step: int, state: Any, *, wait: bool = True) -> None:
        """Write a checkpoint for ``step``.

        ``wait=False`` returns as soon as the save is dispatched (orbax
        persists in the background; device buffers are snapshotted first,
        so training may mutate/donate the state immediately) — the
        standard overlap of checkpoint IO with subsequent steps.  Call
        :meth:`wait_until_finished` before relying on the files: a pending
        save is NOT finalized by ``restore``/``restore_latest`` (they only
        see committed steps), only by the next ``save`` or an explicit
        wait.
        """
        ocp = _ocp()

        def _save():
            _faults.fire("ckpt.save", step)
            self._mgr.save(step, args=ocp.args.StandardSave(state))

        self._call(_save, site=f"ckpt.save[{step}]")
        if wait:
            self._mgr.wait_until_finished()

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_latest(self, *, target: Any = None, shardings: Any = None):
        import jax

        ocp = _ocp()
        step = self._mgr.latest_step()
        if step is None:
            return None, None
        if target is not None and shardings is not None:
            abstract = jax.tree.map(
                lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
                target,
                shardings,
            )
            args = ocp.args.StandardRestore(abstract)
        elif target is not None:
            abstract = jax.tree.map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), target
            )
            args = ocp.args.StandardRestore(abstract)
        else:
            args = None
        restored = self._call(
            lambda: self._mgr.restore(step, args=args),
            site=f"ckpt.restore[{step}]",
        )
        return step, restored


def latest_step(directory: str | os.PathLike) -> Optional[int]:
    """Latest committed step under ``directory``, or None.

    A pure read: querying a run that never checkpointed must not create
    its directory (CheckpointManager's default options would, as a side
    effect), so a missing directory short-circuits and the manager is
    built with ``create=False``.
    """
    if not os.path.isdir(os.fspath(directory)):
        return None
    ocp = _ocp()
    mgr = ocp.CheckpointManager(
        os.fspath(directory),
        options=ocp.CheckpointManagerOptions(create=False),
    )
    return mgr.latest_step()
