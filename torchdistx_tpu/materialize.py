"""TPU-native materialization: replay the deferred-init tape as JAX arrays.

This is the reason this framework exists (SURVEY.md §7, BASELINE.md): take a
module whose parameters are fake + recorded, and instantiate them **directly
as (sharded) ``jax.Array`` leaves on a TPU mesh** — shard-then-materialize
with no full-tensor host round-trip.  The reference stops at replaying onto
real torch devices (deferred_init.cc:505-666); the TPU-native path compiles
the whole init subgraph into a single ``jit`` whose ``out_shardings`` place
every parameter shard on its device over ICI, letting XLA's SPMD partitioner
generate per-shard init (including partitioned RNG) without ever building the
full tensor anywhere.

Mutation/view semantics on an immutable substrate
-------------------------------------------------
The reference replays in-place/view-heavy init code onto *mutable storage*.
Functionally, each recorded meta **storage** becomes a flat value in an
environment; tensors are strided windows onto those values:

* reading a tensor = strided gather from its storage buffer
  (fast path: contiguous whole-storage view = reshape);
* an in-place op = pure compute + strided scatter back through the written
  tensor's layout;
* a view op = no compute at all — its outputs are just layouts, resolved at
  read time (this subsumes the reference's view keep-alive and aliasing
  machinery, deferred_init.cc:416-461).

Replay order is the same chronological call-stack the torch path uses
(_tape.build_call_stack ≈ deferred_init.cc:529-621), so write-after-write and
read-after-write through any alias resolve exactly as recorded.

RNG: every node draws from
``fold_in(fold_in(key(seed), tape_ordinal), tape_relative_op_nr)`` where
``tape_ordinal`` numbers the distinct tapes reachable from the target(s) in
first-appearance order and the relative op_nr is ``op_nr - base_nr`` (first
op of the node's tape).  Properties: deterministic, independent of
materialization order, reproducible across processes *and* across tapes in
one process (absolute op counters never leak in), collision-free when
separately recorded submodules are merged into one module (distinct
ordinals), equal between :func:`materialize_tensor_jax` and
:func:`materialize_module_jax` for the ordinary single-tape module, and
identical across hosts — so multi-host sharded materialization is
consistent by construction (the NCCL-broadcast-init analog: no broadcast
needed at all).

Ops with no JAX lowering fall back to torch replay + ``jax.device_put`` with
the planned sharding (per-tensor, so host RAM stays bounded by the largest
parameter, not the model).
"""

from __future__ import annotations

import functools
import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

import torch
import torch.nn as nn
import torch.utils._pytree as pytree

from . import _tape
from . import telemetry as _telemetry
from .telemetry import perf as _perf
from ._tape import OpNode, OutputRef
from .deferred_init import _get_record, is_deferred
from .fake import FakeTensor
from .ops.aten_jax import LOWERINGS, UnsupportedOpError
from .utils.compilation_cache import ensure_compilation_cache
from .utils.dtypes import jnp_dtype_of

__all__ = [
    "materialize_tensor_jax",
    "materialize_module_jax",
]


def _is_view_node(node: OpNode) -> bool:
    """Pure view op: outputs alias inputs, nothing is written.

    Ground truth is the op schema (the reference infers the same from output
    storages aliasing argument storages, deferred_init.cc:416-461)."""
    if node.mutated_args:
        return False
    try:
        returns = node.op.func._schema.returns
    except AttributeError:
        return False
    return bool(returns) and all(r.alias_info is not None for r in returns)


class _MetaWindow:
    """Layout of one tensor over its flat storage buffer."""

    __slots__ = (
        "storage_key",
        "shape",
        "strides",
        "offset",
        "dtype",
        "numel",
        "storage_elems",
    )

    def __init__(self, meta: torch.Tensor):
        storage = meta.untyped_storage()
        self.storage_key = storage._cdata
        self.shape = tuple(meta.shape)
        self.strides = tuple(meta.stride())
        self.offset = meta.storage_offset()
        self.dtype = meta.dtype
        self.numel = meta.numel()
        self.storage_elems = storage.size() // max(meta.element_size(), 1)

    def is_whole_contiguous(self, buffer_len: int) -> bool:
        if self.offset != 0 or self.numel != buffer_len:
            return False
        expected = 1
        for size, stride in zip(reversed(self.shape), reversed(self.strides)):
            if size != 1 and stride != expected:
                return False
            expected *= size
        return True

    def flat_indices(self):
        import jax.numpy as jnp

        idx = jnp.asarray(self.offset)
        for size, stride in zip(self.shape, self.strides):
            idx = idx[..., None] + jnp.arange(size) * stride
        return idx


class _FunctionalReplay:
    """Replays tape nodes as pure JAX computation over storage buffers.

    ``key_lookup``/``ext_lookup`` parametrize the replay for template reuse
    (the grouped strategy): per-node PRNG keys and external tensor values come
    in as traced arguments instead of being baked into the trace, so one
    compiled program serves every structurally identical call stack.
    """

    def __init__(
        self,
        base_key,
        *,
        check_guards: bool = True,
        key_lookup=None,
        ext_lookup=None,
    ):
        self.base_key = base_key
        self.check_guards = check_guards
        self.key_lookup = key_lookup
        self.ext_lookup = ext_lookup
        # storage key -> (flat jnp value, element count)
        self.storages: Dict[int, Any] = {}
        self.replayed: set = set()
        # Tape base_nr -> ordinal, assigned in replay (chronological) order;
        # recording order is deterministic for a given program, so ordinals
        # are process-stable.  See key_for.
        self.tape_ordinals: Dict[int, int] = {}

    def key_for(self, node: OpNode):
        import jax

        if self.key_lookup is not None:
            return self.key_lookup(node)
        # Stream identity = (tape ordinal, tape-relative op_nr):
        # reproducible across processes and across tapes in one process —
        # absolute op_nrs depend on how many tapes preceded this one and
        # never enter a key — and collision-free when a call stack spans
        # several tapes (each gets a distinct ordinal).  Matches the module
        # path for single-tape modules (module docstring, RNG note).
        ordinal = self.tape_ordinals.setdefault(
            node.base_nr, len(self.tape_ordinals)
        )
        return jax.random.fold_in(
            jax.random.fold_in(self.base_key, ordinal),
            node.op_nr - node.base_nr,
        )

    # -- engine plumbing ----------------------------------------------------

    def read(self, window: _MetaWindow):
        buf = self.storages[window.storage_key]
        if window.is_whole_contiguous(buf.shape[0]):
            return buf.reshape(window.shape)
        return buf[window.flat_indices()]

    def write(self, window: _MetaWindow, value):
        import jax.numpy as jnp

        value = jnp.broadcast_to(value, window.shape).astype(
            jnp_dtype_of(window.dtype)
        )
        buf = self.storages.get(window.storage_key)
        if buf is None:
            # Fresh storage: a flat buffer covering the whole allocation.
            buf = jnp.zeros(
                (window.storage_elems,), dtype=jnp_dtype_of(window.dtype)
            )
        if window.is_whole_contiguous(buf.shape[0]):
            self.storages[window.storage_key] = value.reshape(-1)
        else:
            self.storages[window.storage_key] = buf.at[
                window.flat_indices()
            ].set(value)

    def value_of_output(self, node: OpNode, index: int):
        meta = node.out_metas[index]
        return self.read(_MetaWindow(meta))

    # -- node replay --------------------------------------------------------

    def run_call_stack(self, target: OpNode) -> None:
        for node in _tape.build_call_stack(target):
            self.run_node(node)

    def run_node(self, node: OpNode) -> None:
        import jax
        import jax.numpy as jnp

        if node.op_nr in self.replayed:
            return
        self.replayed.add(node.op_nr)
        if self.check_guards:
            for guard in node.op.guards:
                guard.check()

        if _is_view_node(node):
            # Views are layouts, not computation; ensure the base storage
            # exists (it must, via dependencies) and move on.
            return

        def resolve(a):
            if isinstance(a, OutputRef):
                meta = a.node.out_metas[a.index]
                return self.read(_MetaWindow(meta))
            if isinstance(a, torch.Tensor):
                if self.ext_lookup is not None:
                    return self.ext_lookup(a)
                return jnp.asarray(a.detach().cpu().numpy())
            return a

        op = node.op
        args, kwargs = pytree.tree_map(resolve, (op.args, op.kwargs))
        name = _packet_name(op.func)
        fn = LOWERINGS.get(name)
        if fn is None:
            raise UnsupportedOpError(
                f"No JAX lowering for '{name}' (recorded as {op.name})."
            )

        ctx = _LowerCtx(self, node)
        out = fn(ctx, *args, **_strip_factory_kwargs(kwargs))
        outs = out if isinstance(out, (list, tuple)) else [out]

        if node.mutated_args:
            # In-place: scatter each mutated arg's OWN result back through
            # that tensor's layout (writes are visible through every alias).
            # The arg→output pairing comes from the schema alias sets; a
            # blanket outs[0] would corrupt the second buffer of a
            # two-mutation op such as aminmax.out.
            out_of = _mutation_output_map(op.func, node.mutated_args, len(outs))
            for pos in node.mutated_args:
                ref = _tape.arg_at_schema_pos(op.func, op.args, op.kwargs, pos)
                if isinstance(ref, OutputRef):
                    meta = ref.node.out_metas[ref.index]
                    self.write(_MetaWindow(meta), outs[out_of[pos]])
        # Fresh outputs define their storages.
        for i, meta in enumerate(node.out_metas):
            if meta is None or i >= len(outs):
                continue
            window = _MetaWindow(meta)
            if window.storage_key not in self.storages:
                self.write(window, outs[i])


class _LowerCtx:
    """Per-node context handed to lowerings: PRNG key + output metadata."""

    __slots__ = ("engine", "node")

    def __init__(self, engine: _FunctionalReplay, node: OpNode):
        self.engine = engine
        self.node = node

    @property
    def key(self):
        return self.engine.key_for(self.node)

    def out_meta(self, index: int) -> torch.Tensor:
        return self.node.out_metas[index]


@functools.lru_cache(maxsize=4096)
def _packet_name(func) -> str:
    # e.g. "aten.uniform_.default" — OpOverload objects are interned
    # singletons, so an identity-keyed cache is safe and saves the str()
    # on every node of every stack analysis.
    return str(func)


def _mutation_output_map(func, mutated_args, n_outs) -> dict:
    """Map each mutated positional arg to the lowering-output index that
    carries its new value.

    Ground truth is the schema's alias-set pairing: an argument annotated
    ``Tensor(a!)`` is returned by the output annotated ``Tensor(a!)``
    (e.g. ``aminmax.out``'s min/max pair).  Ops whose single mutated arg has
    no aliased return (pure in-place like ``uniform_`` lowered to return the
    new buffer) fall back to output 0; multiple mutated args without a
    schema pairing are refused rather than silently corrupted.
    """
    mapping: dict = {}
    schema = getattr(func, "_schema", None)
    if schema is not None:
        for pos in mutated_args:
            if pos >= len(schema.arguments):
                continue
            ainfo = schema.arguments[pos].alias_info
            if ainfo is None:
                continue
            aset = set(ainfo.before_set)
            for j, ret in enumerate(schema.returns):
                rinfo = ret.alias_info
                if rinfo is not None and aset & set(rinfo.before_set):
                    if j < n_outs:
                        mapping[pos] = j
                    break
    missing = [p for p in mutated_args if p not in mapping]
    if missing:
        if len(mutated_args) == 1 and n_outs >= 1:
            mapping[mutated_args[0]] = 0
        else:
            raise UnsupportedOpError(
                f"Cannot pair mutated args {missing} of '{func}' with "
                f"their outputs ({n_outs} returned): the schema has no "
                "aliased return for them and more than one arg is mutated."
            )
    return mapping


def _strip_factory_kwargs(kwargs: dict) -> dict:
    return {
        k: v
        for k, v in kwargs.items()
        if k not in ("device", "layout", "pin_memory", "memory_format",
                     "non_blocking", "generator")
    }


# ---------------------------------------------------------------------------
# Grouped (template) materialization: structural dedup of call stacks.
#
# Deep models repeat their init structure — 48 transformer blocks record 48
# structurally identical call stacks per parameter kind, differing only in
# PRNG stream (op_nr) and captured external tensors.  Compiling the union
# program (the "fused" strategy) makes XLA chew through O(depth) copies of
# the same subgraph; grouping instead compiles ONE small program per unique
# stack *signature* (op sequence + shapes + scalar args) with per-node keys
# and externals passed as traced arguments, then executes it per instance
# (vmap-batched off-mesh).  Compile time becomes O(unique layer kinds), not
# O(depth) — the TPU-idiomatic shape for init, and the reason the deferred
# path beats eager init+transfer (BASELINE.md).


def _analyze_stack(stack: List[OpNode], record) -> Optional[Tuple]:
    """Signature + per-instance data for one call stack.

    Returns ``(sig, ext_values)`` where ``sig`` is a hashable
    structural signature — two stacks with equal signatures trace to
    identical jaxprs when replayed with keys/externals as arguments — or
    ``None`` if the stack is not groupable (unlowerable op present).
    """
    local = {n.op_nr: i for i, n in enumerate(stack)}
    storage_ids: Dict[int, int] = {}

    def sid(key: int) -> int:
        return storage_ids.setdefault(key, len(storage_ids))

    def win_sig(meta: Optional[torch.Tensor]):
        if meta is None:
            return None
        w = _MetaWindow(meta)
        return (
            sid(w.storage_key),
            w.shape,
            w.strides,
            w.offset,
            str(w.dtype),
            w.storage_elems,
        )

    ext_values: List[torch.Tensor] = []
    node_sigs = []
    for n in stack:
        is_view = _is_view_node(n)
        if not is_view and _packet_name(n.op.func) not in LOWERINGS:
            return None

        def norm(a):
            if isinstance(a, OutputRef):
                i = local.get(a.node.op_nr)
                if i is None:
                    # Dependency outside the stack — cannot template.
                    raise _NotGroupable
                return ("ref", i, a.index)
            if isinstance(a, torch.Tensor):
                if is_view:
                    # View nodes are never resolved at replay; their args
                    # must not consume external slots.
                    return ("viewext", tuple(a.shape), str(a.dtype))
                ext_values.append(a)
                return ("ext", len(ext_values) - 1, tuple(a.shape), str(a.dtype))
            if isinstance(
                a,
                (torch.dtype, torch.device, torch.layout, torch.memory_format),
            ):
                return ("t", str(a))
            return ("v", a)

        def rec(a):
            # Structural recursion replacing pytree.tree_flatten +
            # repr(treedef) (which dominated warm-materialize wall time):
            # traversal order over tuple/list/dict matches torch pytree's
            # flatten order (dicts: insertion order), so ``ext_values``
            # pairs up with replay-time ``tree_map`` consumption.  Exotic
            # containers (namedtuple/OrderedDict/registered pytrees) would
            # traverse differently there — send those to the fused path.
            ta = type(a)
            if ta is tuple or ta is list:
                return ("T" if ta is tuple else "L",
                        tuple(rec(x) for x in a))
            if ta is dict:
                return ("D", tuple((k, rec(v)) for k, v in a.items()))
            if isinstance(a, (tuple, list, dict)):
                raise _NotGroupable  # subclass: pytree order unknown
            return norm(a)

        try:
            args_sig = rec((n.op.args, n.op.kwargs))
        except _NotGroupable:
            return None
        except TypeError:
            return None  # unhashable leaf somewhere; fused path handles it
        node_sigs.append(
            (
                _packet_name(n.op.func),
                args_sig,
                tuple(win_sig(m) for m in n.out_metas),
                tuple(n.mutated_args),
                is_view,
            )
        )

    sig = (
        tuple(node_sigs),
        local[record.node.op_nr],
        record.index,
    )
    try:
        hash(sig)
    except TypeError:
        return None
    return sig, ext_values


class _NotGroupable(Exception):
    pass


# ---------------------------------------------------------------------------
# Fill fast path: the overwhelmingly common init stack is
# ``factory → (views) → whole-storage fill`` — every torch.nn default init
# (kaiming/xavier uniform_, normal_, ones/zeros/constant) records this shape.
# Replaying those through per-signature templates makes XLA compile one
# subgraph per unique parameter SHAPE (a resnet50 has 46).  Instead, fills
# are pooled across shapes into padded power-of-two buckets
# (ops.aten_jax.fill_bucket) and drawn as ONE vmapped kernel per
# (fill kind, dtype, bucket) — a handful of subgraphs for any model, with
# per-param slice/reshape being free for XLA.  Values are bitwise identical
# to the per-op lowering (which draws the same padded buckets; threefry
# fold_in keys are vmap-invariant).

_FILL_FINAL_OPS = {
    "aten.uniform_.default": "uniform",
    "aten.normal_.default": "normal",
    "aten.fill_.Scalar": "full",
    "aten.zero_.default": "zero",
}

# Factories whose value is dead once a whole-storage fill follows.
_FILL_FACTORY_OPS = {
    "aten.empty.memory_format",
    "aten.empty.default",
    "aten.empty_strided.default",
    "aten.zeros.default",
    "aten.ones.default",
    "aten.full.default",
}


def _match_fill(stack: List[OpNode], record):
    """Match a ``factory → (views) → whole-storage fill`` stack.

    Returns ``(kind, s0, s1, fill_idx)`` — fill kind, its two scalar
    parameters (raw, dtype-cast at bin build), and the fill node's index in
    ``stack`` — or ``None`` if the stack doesn't qualify.
    """
    non_view = [n for n in stack if not _is_view_node(n)]
    if not non_view:
        return None
    last = non_view[-1]
    kind = _FILL_FINAL_OPS.get(_packet_name(last.op.func))
    if kind is None:
        return None
    # Single storage throughout — so every pre-fill node's effects are
    # confined to this storage — and the final fill overwrites the WHOLE
    # storage, so every preceding compute node is dead regardless of kind
    # (e.g. the kaiming-uniform draw a Linear ctor runs before HF
    # ``_init_weights`` re-fills with ``normal_``).  Skipping dead draws
    # cannot shift RNG: replay keys are per-node (tape ordinal, rel nr),
    # not stream-positional.
    storages = set()
    for n in stack:
        for m in n.out_metas:
            if m is not None:
                storages.add(_MetaWindow(m).storage_key)
    if len(storages) != 1:
        return None
    fw = _MetaWindow(last.out_metas[0])
    if not fw.is_whole_contiguous(fw.storage_elems):
        return None
    rw = _MetaWindow(record.node.out_metas[record.index])
    if not rw.is_whole_contiguous(rw.storage_elems) or rw.dtype != fw.dtype:
        return None

    scalars = _fill_scalars(kind, last)
    if scalars is None:
        return None
    return kind, scalars[0], scalars[1], stack.index(last)


def _fill_scalars(kind: str, fill_node: OpNode):
    """The two scalar parameters of one fill node, or ``None`` when they
    are tensor-valued (not poolable).  Used by :func:`_match_fill` on the
    group representative AND re-derived per member at plan time
    (:func:`_plan_fill_bins` / :func:`_plan_big_fills`): the grouping
    signature does include scalar args, but the fast paths must not
    silently apply the representative's init scale to every member if
    that invariant ever loosens."""
    args = list(fill_node.op.args)
    kw = fill_node.op.kwargs
    if kind == "uniform":
        s0 = args[1] if len(args) > 1 else kw.get("from", 0.0)
        s1 = args[2] if len(args) > 2 else kw.get("to", 1.0)
    elif kind == "normal":
        s0 = args[1] if len(args) > 1 else kw.get("mean", 0.0)
        s1 = args[2] if len(args) > 2 else kw.get("std", 1.0)
    elif kind == "full":
        s0 = args[1] if len(args) > 1 else kw.get("value")
        s1 = 0
        if s0 is None:
            return None
    else:  # zero
        s0 = s1 = 0
    if isinstance(s0, (torch.Tensor, OutputRef)) or isinstance(
        s1, (torch.Tensor, OutputRef)
    ):
        return None
    return s0, s1


def _member_fill_scalars(kind: str, name: str, node: OpNode):
    """Per-member fill scalars for the pooled/big-fill paths.  Signature
    equality should make these equal the representative's; a mismatch in
    kind or a tensor-valued scalar here means the grouping invariant
    broke — refuse loudly rather than draw with the wrong init scale."""
    if _FILL_FINAL_OPS.get(_packet_name(node.op.func)) != kind:
        raise UnsupportedOpError(
            f"fill-fastpath grouping invariant violated for '{name}': "
            f"member fill op {node.op.name!r} does not match the group "
            f"kind {kind!r}"
        )
    scalars = _fill_scalars(kind, node)
    if scalars is None:
        raise UnsupportedOpError(
            f"fill-fastpath grouping invariant violated for '{name}': "
            "member fill scalars are tensor-valued"
        )
    return scalars


def _fill_fastpath_enabled() -> bool:
    import os

    return not os.environ.get("TDX_NO_FILL_FASTPATH")


# Introspection: number of params served by the fill fast path in the most
# recent materialize_module_jax call (tests/bench).
last_fill_fastpath_params = 0

# Phase timings of the most recent materialize_module_jax call:
# {plan_s, compile_s, transfer_s, exec_s, jobs: [(label, s, rss_mb)]}.
# Per-job numbers (blocking execute + RSS read) only under
# TDX_PROFILE_MATERIALIZE=1 — blocking serializes dispatch.
#
# Back-compat view: the numbers are the durations of the telemetry spans
# (materialize.plan/compile/transfer/execute/job — see
# torchdistx_tpu/telemetry and docs/observability.md), assembled into the
# legacy dict shape.  New code should read the telemetry collector.
last_profile: Dict[str, Any] = {}

# Telemetry counters, bound once (see telemetry._core.counter).  The
# whole-call hit counter mirrors the legacy `exec_cache_hits` module
# global; the mem/disk/compile counters resolve *which* tier served each
# program.
_T_CALLS = _telemetry.counter("materialize.calls")
_T_EXEC_HITS = _telemetry.counter("materialize.exec_cache_hits")
_T_EXEC_MEM_HITS = _telemetry.counter("materialize.exec_cache_mem_hits")
_T_EXEC_DISK_HITS = _telemetry.counter("materialize.exec_cache_disk_hits")
_T_COMPILES = _telemetry.counter("materialize.compiles")
_T_FILL_FAST = _telemetry.counter("materialize.fill_fastpath_hits")
_T_TORCH_FALLBACK = _telemetry.counter("materialize.torch_fallback_params")


def _profile_enabled() -> bool:
    import os

    return bool(os.environ.get("TDX_PROFILE_MATERIALIZE"))


def _rss_mb_now() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024
    except OSError:
        pass
    return 0.0


# Bound on any one vmapped draw's transient buffer: bins whose padded
# population exceeds this are drawn in row chunks inside the same program
# (a 48-layer model's 16M-element fills would otherwise materialize a
# multi-GB (48, bucket) intermediate).
_FILL_CHUNK_BYTES = 512 * 1024 * 1024

# Fills above this size stay on the template path: large params are few and
# shape-repeated within a model (48 identical qkv projections), so pooling
# them buys no kernel-shape dedup while padding wastes bandwidth/HBM and
# chunking multiplies subgraphs.  Pooling earns its keep on the long tail of
# small unique shapes (a resnet's 40+ conv/bn signatures).  The lowerings
# draw exact (unpadded) lengths above this same bound — ops.aten_jax owns
# the constant so both sides agree.
from .ops.aten_jax import FILL_POOL_MAX as _FILL_POOL_MAX  # noqa: E402


def _plan_fill_bins(group_list, stacks, target_dtypes, tape_ordinals):
    """Split signature groups into pooled fill bins + remaining groups.

    One bin — one compiled program — per ``(draw dtype, bucket)``; all fill
    kinds sharing the bucket ride in it.  Entries carry everything the fast
    draw needs (name, output shape, numel, RNG identity of the fill node,
    scalar params, target dtype).  Ordering is deterministic: bins in
    first-appearance order over ``group_list``, kinds and entries likewise.
    """
    import numpy as np

    from .ops.aten_jax import fill_bucket

    bins: Dict[tuple, dict] = {}
    rest = []
    for g in group_list:
        stack, rec = g["rep"]
        if any(len(e) for e in g["exts"]):
            rest.append(g)
            continue
        m = _match_fill(stack, rec)
        if m is None:
            rest.append(g)
            continue
        kind, _, _, fill_idx = m
        rw = _MetaWindow(rec.node.out_metas[rec.index])
        if rw.numel > _FILL_POOL_MAX:
            rest.append(g)
            continue
        ddt = jnp_dtype_of(rw.dtype)
        bucket = fill_bucket(rw.numel)
        b = bins.setdefault(
            (str(ddt), bucket),
            {"ddt": ddt, "bucket": bucket, "kinds": {}},
        )
        entries = b["kinds"].setdefault(kind, [])
        for name in g["names"]:
            node = stacks[name][fill_idx]
            m_s0, m_s1 = _member_fill_scalars(kind, name, node)
            entries.append(
                {
                    "name": name,
                    "shape": rw.shape,
                    "numel": rw.numel,
                    "ord": tape_ordinals[node.base_nr],
                    "rel": node.op_nr - node.base_nr,
                    "s0": m_s0,
                    "s1": m_s1,
                    "tdt": target_dtypes[name],
                }
            )
    bin_list = list(bins.values())
    for b in bin_list:
        b["kinds"] = list(b["kinds"].items())
    fill_ins = [
        tuple(
            (
                np.asarray([e["ord"] for e in entries], dtype=np.uint32),
                np.asarray([e["rel"] for e in entries], dtype=np.uint32),
                np.asarray([e["s0"] for e in entries], dtype=b["ddt"]),
                np.asarray([e["s1"] for e in entries], dtype=b["ddt"]),
            )
            for _, entries in b["kinds"]
        )
        for b in bin_list
    ]
    return bin_list, fill_ins, rest


def _plan_big_fills(
    group_list, stacks, target_dtypes, tape_ordinals, plan, fakes, mesh
):
    """Extract large-fill groups (numel > _FILL_POOL_MAX) into direct-draw
    subgroups for the big-fill job; returns ``(subgroups, traced_inputs,
    remaining_groups)``.

    Large fills are never pooled (padding buys nothing at few, repeated
    shapes); each subgroup is one (kind, draw dtype, SHAPE, target dtype)
    class.  Draws are emitted directly in the output's N-D shape — under
    counter-based threefry ``normal(k, (n,)).reshape(shape)`` equals
    ``normal(k, shape)`` bitwise, and a direct N-D draw lets the SPMD
    partitioner generate ANY-dim sharding shard-locally (the flat-draw →
    reshape chain only propagates dim-0 shardings; a (2048, 5504)
    down-projection sharded on dim 1 silently replicated).  An
    instance-stacked ``shard_map`` variant was tried and rejected: the
    unstack from instance-sharding to each param's final sharding makes
    the partitioner all-gather the whole group (measured 31 GB peak /
    186 s at 1.35B); direct propagation needs no redistribution at all.
    Measured on the 1.35B HF Llama 8-device materialize, the prior
    template-replay path held peak RSS at 23 GB; this path generates
    every shard on its owner.
    """
    import numpy as np

    subs: Dict[tuple, dict] = {}
    rest = []
    for g in group_list:
        stack, rec = g["rep"]
        m = None
        if not any(len(e) for e in g["exts"]):
            m = _match_fill(stack, rec)
        if m is not None:
            rw = _MetaWindow(rec.node.out_metas[rec.index])
            if rw.numel <= _FILL_POOL_MAX:
                m = None
        if m is None:
            rest.append(g)
            continue
        kind, _, _, fill_idx = m
        rw = _MetaWindow(rec.node.out_metas[rec.index])
        ddt = jnp_dtype_of(rw.dtype)
        tdt = target_dtypes[g["names"][0]]
        for name in g["names"]:
            spec = _resolve_spec(plan, name, fakes[name], mesh)
            sg = subs.setdefault(
                (kind, str(ddt), rw.shape, str(tdt), str(spec)),
                {
                    "kind": kind,
                    "ddt": ddt,
                    "shape": rw.shape,
                    "numel": rw.numel,
                    "tdt": tdt,
                    "spec": spec,
                    "entries": [],
                },
            )
            node = stacks[name][fill_idx]
            m_s0, m_s1 = _member_fill_scalars(kind, name, node)
            sg["entries"].append(
                {
                    "name": name,
                    "shape": rw.shape,
                    "numel": rw.numel,
                    "ord": tape_ordinals[node.base_nr],
                    "rel": node.op_nr - node.base_nr,
                    "s0": m_s0,
                    "s1": m_s1,
                    # target dtype is CLASS-level (sg["tdt"]): the group
                    # key above already folds in target_dtypes[name].
                }
            )
    sub_list = list(subs.values())
    big_ins = [
        (
            np.asarray([e["ord"] for e in sg["entries"]], dtype=np.uint32),
            np.asarray([e["rel"] for e in sg["entries"]], dtype=np.uint32),
            np.asarray([e["s0"] for e in sg["entries"]], dtype=sg["ddt"]),
            np.asarray([e["s1"] for e in sg["entries"]], dtype=sg["ddt"]),
        )
        for sg in sub_list
    ]
    return sub_list, big_ins, rest


def _make_bigfill_class_fn(sg):
    """Single-instance draw program for one big-fill class — bitwise equal
    to the per-op lowering's flat draw + reshape (threefry is counter-
    based; scaling commutes with reshape).  The per-instance RNG key and
    fill scalars are *inputs*, so ONE compiled program serves every
    instance of the class (a 24-layer Llama has ~170 large fills but only
    ~4 classes), and XLA's backward propagation from the output sharding
    generates each shard on its owning device — any sharded dim, zero
    redistribution.  Rejected alternatives, measured at 1.35B HF/8 dev:
    per-entry chains in one program (compiles O(entries): 42 s), stacked
    vmapped draws (in-program unstack makes the partitioner all-gather
    the group: 31 GB peak / 186 s; eager unstack doubles transient RSS).
    """
    kind, ddt, shape, tdt = sg["kind"], sg["ddt"], sg["shape"], sg["tdt"]

    def fn(kk, a, b_):
        import jax
        import jax.numpy as jnp

        if kind == "uniform":
            v = jax.random.uniform(kk, shape, dtype=ddt, minval=a, maxval=b_)
        elif kind == "normal":
            v = jax.random.normal(kk, shape, dtype=ddt) * b_ + a
        elif kind == "full":
            v = jnp.broadcast_to(a, shape).astype(ddt)
        else:  # zero
            v = jnp.zeros(shape, dtype=ddt)
        return v.astype(tdt)

    return fn


def _pack_host_leaves(leaves):
    """Group ``np.ndarray`` leaves by dtype into one flat buffer each.

    Returns ``(by_dt, order, layout, packed)``: slot indices per dtype,
    sorted dtype order, the static layout (shapes per slot — program
    identity for the unpack), and the concatenated host buffers.  Shared
    by the argpack transfer and the mono executable so the offset
    arithmetic exists once.
    """
    import numpy as np

    by_dt: Dict[str, list] = {}
    for i, l in enumerate(leaves):
        if isinstance(l, np.ndarray):
            by_dt.setdefault(str(l.dtype), []).append(i)
    order = sorted(by_dt)
    layout = tuple(
        (dt, tuple(tuple(leaves[i].shape) for i in by_dt[dt]))
        for dt in order
    )
    packed = [
        np.concatenate([leaves[i].ravel() for i in by_dt[dt]])
        for dt in order
    ]
    return by_dt, order, layout, packed


def _unpack_bufs(bufs, by_dt, order, layout):
    """Traced inverse of :func:`_pack_host_leaves`: slot → value dict."""
    import numpy as np

    vals = {}
    for buf, (dt, shapes) in zip(bufs, layout):
        off = 0
        for slot, shp in zip(by_dt[dt], shapes):
            n = int(np.prod(shp, dtype=np.int64))
            vals[slot] = buf[off:off + n].reshape(shp)
            off += n
    return vals


def _bin_entry_key(b):
    """Exec-cache identity of a bin program (scalar params are traced
    inputs, NOT identity — a changed init std reuses the executable)."""
    return tuple(
        (
            kind,
            tuple(
                (e["name"], e["numel"], e["shape"], str(e["tdt"]))
                for e in entries
            ),
        )
        for kind, entries in b["kinds"]
    )


def _bin_names(b):
    return [e["name"] for _, entries in b["kinds"] for e in entries]


def _make_bin_fn(b):
    """Trace function for one fill bin: per kind, a vmapped padded draw in
    row chunks of ≤_FILL_CHUNK_BYTES, then per-entry slice/reshape/cast.
    Bitwise equal to the per-op lowering replay (the lowerings draw the same
    buckets — ops.aten_jax.fill_bucket; threefry fold_in keys are
    vmap-invariant), so module- and tensor-path values agree."""
    import numpy as np

    ddt, bucket = b["ddt"], b["bucket"]
    rows_cap = max(
        1, _FILL_CHUNK_BYTES // (bucket * np.dtype(ddt).itemsize)
    )

    def fn(base_key, kin):
        import jax
        import jax.numpy as jnp

        fold = jax.vmap(
            lambda o, r: jax.random.fold_in(
                jax.random.fold_in(base_key, o), r
            )
        )
        out = {}
        for (kind, entries), (ords, rels, s0, s1) in zip(b["kinds"], kin):
            n = len(entries)
            for lo in range(0, n, rows_cap):
                hi = min(n, lo + rows_cap)
                if kind == "uniform":
                    chunk = jax.vmap(
                        lambda k, a, b_: jax.random.uniform(
                            k, (bucket,), dtype=ddt, minval=a, maxval=b_
                        )
                    )(fold(ords[lo:hi], rels[lo:hi]), s0[lo:hi], s1[lo:hi])
                elif kind == "normal":
                    chunk = jax.vmap(
                        lambda k, mu, sd: jax.random.normal(
                            k, (bucket,), dtype=ddt
                        )
                        * sd
                        + mu
                    )(fold(ords[lo:hi], rels[lo:hi]), s0[lo:hi], s1[lo:hi])
                elif kind == "full":
                    chunk = jnp.broadcast_to(
                        s0[lo:hi, None], (hi - lo, bucket)
                    ).astype(ddt)
                else:  # zero
                    chunk = jnp.zeros((hi - lo, bucket), dtype=ddt)
                for i in range(lo, hi):
                    e = entries[i]
                    out[e["name"]] = (
                        chunk[i - lo, : e["numel"]]
                        .reshape(e["shape"])
                        .astype(e["tdt"])
                    )
        return out

    return fn


def _make_template(stack: List[OpNode], record, target_dtype):
    """Build the replay template for one signature group.

    Closes over the *representative* instance's nodes (shapes/ops identical
    across the group by signature equality); per-node PRNG keys and external
    tensor values come in as arguments, so the jitted template is reused by
    every instance.
    """
    local = {n.op_nr: i for i, n in enumerate(stack)}

    def template(keys, exts):
        ext_iter = iter(exts)
        eng = _FunctionalReplay(
            None,
            check_guards=False,
            key_lookup=lambda node: keys[local[node.op_nr]],
            ext_lookup=lambda t: next(ext_iter),
        )
        for n in stack:
            eng.run_node(n)
        return eng.value_of_output(record.node, record.index).astype(
            target_dtype
        )

    return template


# ---------------------------------------------------------------------------
# Public API


def _named_fakes(module: nn.Module) -> List[Tuple[str, FakeTensor]]:
    out = []
    for name, p in module.named_parameters(remove_duplicate=True):
        if is_deferred(p):
            out.append((name, p))
    for name, b in module.named_buffers(remove_duplicate=True):
        if is_deferred(b):
            out.append((name, b))
    return out


def _resolve_spec(plan, name: str, fake: FakeTensor, mesh=None):
    from jax.sharding import PartitionSpec

    from .parallel.sharding import fit_spec_to_mesh, replicate_indivisible

    if plan is None:
        return PartitionSpec()
    if callable(plan):
        spec = plan(name, tuple(fake.shape))
    else:
        spec = plan.get(name)
    if spec is None:
        return PartitionSpec()
    if mesh is None:
        return spec
    return replicate_indivisible(
        fit_spec_to_mesh(spec, mesh), tuple(fake.shape), mesh
    )


def _base_key(seed: int, rng_impl: str):
    import jax

    return jax.random.key(seed, impl=rng_impl)


def materialize_tensor_jax(
    tensor: torch.Tensor,
    *,
    mesh=None,
    spec=None,
    seed: int = 0,
    dtype: Optional[torch.dtype] = None,
    rng_impl: str = "threefry2x32",
):
    """Materialize one fake tensor as a ``jax.Array`` (optionally sharded).

    ``rng_impl``: ``"threefry2x32"`` (default — bitwise stable across
    topologies/shardings, the multi-host guarantee) or ``"rbg"`` (XLA
    RngBitGenerator — much cheaper to compile, for single-chip or
    throwaway-init use; values may depend on backend/sharding).
    """
    import jax

    ensure_compilation_cache()

    record = _get_record(tensor) if isinstance(tensor, FakeTensor) else None
    if record is None:
        raise ValueError("`tensor` is not a deferred fake tensor.")

    target_dtype = jnp_dtype_of(dtype or tensor.dtype)

    def compute():
        eng = _FunctionalReplay(_base_key(seed, rng_impl), check_guards=False)
        eng.run_call_stack(record.node)
        return eng.value_of_output(record.node, record.index).astype(
            target_dtype
        )

    _check_guards_of(record.node)
    from .utils.compilation_cache import cache_everything

    with _telemetry.span("materialize.tensor"), cache_everything(), \
            _perf.program("materialize"):
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            sharding = NamedSharding(mesh, spec or PartitionSpec())
            return jax.jit(compute, out_shardings=sharding)()
        return jax.jit(compute)()


def _check_guards_of(target: OpNode) -> None:
    # Guard checks touch torch tensors; run them eagerly (outside jit trace).
    for node in _tape.build_call_stack(target):
        for guard in node.op.guards:
            guard.check()


def _plan_groups(
    jax_names: List[str],
    fakes: Dict[str, FakeTensor],
    stacks: Dict[str, List[OpNode]],
    target_dtypes: Dict[str, Any],
) -> Tuple[List[dict], List[str]]:
    """Partition params into signature groups and fused leftovers.

    A param is groupable iff its stack shares no node with any other param's
    stack (per-target replay of a shared storage could otherwise advance it
    past another target's read point) and every arg is hashable/templatable.
    Returns ``(group_list, leftover_names)``; each group carries its
    representative stack, per-instance external tensors, and op_nr rows.
    """
    owner_count: Dict[int, int] = {}
    for name in jax_names:
        for n in stacks[name]:
            owner_count[n.op_nr] = owner_count.get(n.op_nr, 0) + 1

    groups: Dict[tuple, dict] = {}
    fused: List[str] = []
    for name in jax_names:
        stack = stacks[name]
        if any(owner_count[n.op_nr] > 1 for n in stack):
            fused.append(name)
            continue
        rec = _get_record(fakes[name])
        analyzed = _analyze_stack(stack, rec)
        if analyzed is None:
            fused.append(name)
            continue
        sig, ext_values = analyzed
        key = (sig, str(target_dtypes[name]))
        g = groups.setdefault(
            key,
            {"key": key, "names": [], "exts": [], "rep": (stack, rec)},
        )
        g["names"].append(name)
        g["exts"].append(ext_values)
    return list(groups.values()), fused


# ---------------------------------------------------------------------------
# In-process executable cache.
#
# The group signature IS the program identity: two materializations whose
# groups carry equal signatures (and names/shardings/seed/rng) trace to the
# same jaxpr, with all instance data — op_nr rows, external tensors —
# entering as traced inputs.  Re-materializing the same architecture in one
# process (hyperparameter sweeps, re-init after resharding, test suites)
# therefore reuses the compiled executable outright: no retrace, no XLA
# compile, no persistent-cache deserialization.  Cross-process warm starts
# are covered separately by the persistent compilation cache
# (utils/compilation_cache.py).

_EXEC_CACHE: "Dict[tuple, Any]" = {}
_EXEC_CACHE_MAX = 64
_EXEC_CACHE_LOCK = threading.Lock()
# Incremented once per materialize_module_jax call whose programs ALL hit
# the cache (i.e. zero compiles happened) — introspection for tests/bench.
exec_cache_hits = 0


def _exec_cache_enabled() -> bool:
    import os

    return not os.environ.get("TDX_NO_EXEC_CACHE")


# Disk tier: AOT executables serialized per program (key = sha256 of the
# exec key).  A warm PROCESS skips retracing and the XLA-cache machinery
# outright — deserialize_and_load is the only per-program cost.  Follows
# the persistent compilation cache's enable flag AND the exec-cache flag;
# any load failure (jax/runtime version change, different device topology)
# silently falls back to compiling.
#
# Trust model: jax's deserialize_and_load unpickles the blob, so reading a
# blob executes whatever the writer put there.  The tier therefore only
# reads/writes a PRIVATE directory: created 0700, and refused entirely if
# it is not owned by this uid or is group/other-writable (e.g. a shared
# JAX_COMPILATION_CACHE_DIR on a multi-user cluster).

_EXEC_DISK_MAX_ENTRIES = 256


def _exec_disk_dir():
    # Blanket-guarded like ensure_compilation_cache: the cache is a pure
    # optimization and must never fail materialization (renamed jax config
    # attrs, read-only HOME, ...).
    try:
        import os
        import stat

        if os.environ.get("TDX_NO_COMPILATION_CACHE"):
            return None
        import jax

        if jax.default_backend() == "cpu":
            # Same rule as utils.compilation_cache: CPU executables are
            # tied to the build host's machine features (reloading warns
            # or SIGILLs), and the test suite's cache-hit invariants must
            # not leak across runs.  The tier's value is on accelerators.
            return None
        # Same dir resolution as ensure_compilation_cache: a programmatic
        # jax.config setting wins over the env var over the default.
        base = (
            jax.config.jax_compilation_cache_dir
            or os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or os.path.expanduser("~/.cache/torchdistx_tpu/xla_cache")
        )
        if "://" in base:
            # Remote cache dirs (gs://...) serve JAX's own persistent cache
            # through its filesystem layer; this tier is local-only — fall
            # back to the local default rather than mangling the URL into a
            # cwd-relative path.
            base = os.path.expanduser("~/.cache/torchdistx_tpu/xla_cache")
        d = os.path.join(base, "tdx_exec")
        os.makedirs(d, mode=0o700, exist_ok=True)
        st = os.stat(d)
        if st.st_uid != os.getuid() or (
            st.st_mode & (stat.S_IWGRP | stat.S_IWOTH)
        ):
            return None  # shared/foreign dir: never unpickle from it
        return d
    except Exception:  # noqa: BLE001
        return None


def _exec_disk_path(key):
    import hashlib
    import os

    d = _exec_disk_dir()
    if d is None:
        return None
    # Keys are nested tuples of primitives (strings/ints/bools) by
    # construction (_hashable_or_none guards hashability; all tensor-ish
    # parts are stringified) — repr() is deterministic for those.
    h = hashlib.sha256(repr(key).encode()).hexdigest()
    return os.path.join(d, f"{h}.pkl")


def _exec_disk_has(key) -> bool:
    """Cheap existence probe (no deserialize/load RPC)."""
    import os

    if not _exec_cache_enabled() or key is None:
        return False
    path = _exec_disk_path(key)
    return path is not None and os.path.exists(path)


def _exec_disk_get(key):
    import pickle

    if not _exec_cache_enabled():
        # TDX_NO_EXEC_CACHE opts out of SERVING cached executables, not
        # just storing them.
        return None
    path = _exec_disk_path(key)
    if path is None:
        return None
    try:
        with open(path, "rb") as f:
            blob, in_tree, out_tree = pickle.loads(f.read())
        from jax.experimental.serialize_executable import (
            deserialize_and_load,
        )

        loaded = deserialize_and_load(blob, in_tree, out_tree)
        import os

        os.utime(path)  # recency refresh: the prune evicts oldest-by-mtime
        _T_EXEC_DISK_HITS.add()
        return loaded
    except Exception:  # noqa: BLE001 — stale/foreign blob: recompile
        return None


def _exec_disk_put(key, cfn) -> None:
    import os
    import pickle

    path = _exec_disk_path(key)
    if path is None:
        return
    try:
        from jax.experimental.serialize_executable import serialize

        payload = pickle.dumps(serialize(cfn))
        # Unique per process AND thread: puts run from the build pool, and
        # two same-key writers sharing a tmp name would interleave into a
        # corrupt published blob.
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)  # atomic vs concurrent writers
        # Bound the tier: prune least-recently-used (mtime, refreshed on
        # disk hits) past the cap.
        d = os.path.dirname(path)
        entries = [e for e in os.listdir(d) if e.endswith(".pkl")]
        if len(entries) > _EXEC_DISK_MAX_ENTRIES:
            # Per-entry safe mtime: a concurrent process unlinking one
            # file mid-sort must not abort the whole prune (the blanket
            # except below would silently swallow it, letting the
            # directory grow unbounded under concurrent writers).
            def _mtime(e):
                try:
                    return os.path.getmtime(os.path.join(d, e))
                except OSError:
                    return 0.0

            entries.sort(key=_mtime)
            for e in entries[: len(entries) - _EXEC_DISK_MAX_ENTRIES]:
                try:
                    os.unlink(os.path.join(d, e))
                except OSError:
                    pass
    except Exception:  # noqa: BLE001 — cache write is pure optimization
        pass


def _exec_cache_get(key):
    """Memory tier only — the disk tier is consulted explicitly (inside
    the build pool, so deserialize+load RPCs overlap)."""
    if not _exec_cache_enabled():
        return None
    with _EXEC_CACHE_LOCK:
        fn = _EXEC_CACHE.get(key)
        if fn is not None:
            # LRU refresh: eviction pops the front, so a hit must move the
            # key to the back or a hot architecture can be evicted over
            # cold ones.
            del _EXEC_CACHE[key]
            _EXEC_CACHE[key] = fn
    if fn is not None:
        _T_EXEC_MEM_HITS.add()
    return fn


def _exec_cache_put(key, fn, *, disk: bool = True) -> None:
    if not _exec_cache_enabled():
        return
    with _EXEC_CACHE_LOCK:
        if key not in _EXEC_CACHE and len(_EXEC_CACHE) >= _EXEC_CACHE_MAX:
            _EXEC_CACHE.pop(next(iter(_EXEC_CACHE)))
        _EXEC_CACHE[key] = fn
    if disk:
        _exec_disk_put(key, fn)


def materialize_module_jax(
    module: nn.Module,
    *,
    mesh=None,
    plan: Optional[Any] = None,
    seed: int = 0,
    dtype: Optional[torch.dtype] = None,
    rng_impl: str = "threefry2x32",
    strategy: str = "auto",
    _fallback_torch: bool = True,
) -> Dict[str, Any]:
    """Materialize every fake param/buffer of ``module`` as JAX arrays.

    Returns ``{qualified_name: jax.Array}`` with per-leaf shardings from
    ``plan`` — XLA SPMD generates each shard on its own device.

    ``plan``: ``None`` (replicated), a dict ``{name: PartitionSpec}``, or a
    callable ``(name, shape) -> PartitionSpec | None`` (see
    :mod:`torchdistx_tpu.parallel.sharding` for FSDP/TP plan builders).
    ``dtype``: optional cast applied to every leaf (e.g. ``torch.bfloat16``
    for TPU training).  ``rng_impl``: see :func:`materialize_tensor_jax`
    (``"rbg"`` roughly halves XLA compile time for init-heavy tapes).

    ``strategy``:

    * ``"grouped"``/``"auto"`` — dedupe structurally identical per-param call
      stacks and compile one small program per unique signature (compile time
      O(unique layer kinds), not O(depth)); params whose stacks share nodes
      with other params fall back to the fused program, preserving
      write-ordering semantics through aliases.
    * ``"fused"`` — one monolithic jit of the union init subgraph (the
      round-1 behavior).

    XLA compile time dominates a cold materialization; the emitted HLO is
    process-stable by design, and the persistent compilation cache is
    enabled on first use (see utils/compilation_cache.py), so warm runs —
    restarts, sweeps, resharded re-inits of the same architecture — skip
    compilation entirely.
    """
    ensure_compilation_cache()
    global last_profile
    last_profile = {"jobs": []}
    _T_CALLS.add()
    # Phase spans (telemetry): plan → compile → transfer → execute, nested
    # under one materialize.module span.  last_profile is assembled from
    # the spans' durations, so it works with telemetry sinks off.  The
    # spans live in THIS frame so a raising path (guard violation, unknown
    # strategy, UnsupportedOpError) cannot leak them onto the thread-local
    # nesting stack or strand an open jax.profiler annotation — the call
    # span records the error class, the never-completed plan phase drops.
    _sp_call = _telemetry.start_span("materialize.module", strategy=strategy)
    _sp_plan = _telemetry.start_span("materialize.plan")
    try:
        # Compile observatory: every XLA compile this materialization
        # issues on THIS thread (the fused program, the per-job jits of
        # the execute phase) attributes to program="materialize" via the
        # jax.monitoring listener; the grouped compile pool's worker
        # threads scope themselves inside _build.
        with _perf.program("materialize"):
            return _materialize_module_jax(
                module,
                mesh=mesh,
                plan=plan,
                seed=seed,
                dtype=dtype,
                rng_impl=rng_impl,
                strategy=strategy,
                _fallback_torch=_fallback_torch,
                _sp_call=_sp_call,
                _sp_plan=_sp_plan,
            )
    except BaseException as e:
        if _perf.is_oom(e):
            # The OOM post-mortem: which component held the device when
            # materialization could not fit (a serving engine's pool and
            # weights share the chip with this allocation).
            _perf.oom_dump(
                "device_oom", site="materialize",
                error=f"{type(e).__name__}: {e}",
            )
        if _sp_plan.duration is None:
            _sp_plan.cancel()
        if _sp_call.duration is None:
            _sp_call.end(error=type(e).__name__)
        raise


def _replicate_mesh_args(all_args, mesh):
    """Explicitly place host argument leaves for mesh-lowered executables.

    Mesh-job programs are lowered from host numpy leaves, and calling
    them back with those raw leaves leans on ``Compiled.__call__``'s
    input-sharding tolerance — which for committed/host arrays against
    mesh-lowered programs is JAX-version-dependent (advisor r4, VERDICT
    item 8b).  A replicated ``NamedSharding`` placement IS the layout
    the executables were lowered for, on every version.  One batched
    ``device_put`` for all leaves; non-array leaves pass through.
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as _P

    rep = NamedSharding(mesh, _P())
    leaves, treedef = jax.tree.flatten(all_args)
    idx = [
        i for i, x in enumerate(leaves)
        if isinstance(x, (np.ndarray, jax.Array))
    ]
    placed = jax.device_put([leaves[i] for i in idx], rep)
    for i, arr in zip(idx, placed):
        leaves[i] = arr
    return jax.tree.unflatten(treedef, leaves)


def _materialize_module_jax(
    module: nn.Module,
    *,
    mesh,
    plan,
    seed,
    dtype,
    rng_impl,
    strategy,
    _fallback_torch,
    _sp_call,
    _sp_plan,
) -> Dict[str, Any]:
    import jax

    global exec_cache_hits

    named = _named_fakes(module)
    if not named:
        _sp_plan.cancel()
        _sp_call.end(n_params=0)
        return {}

    # Eager guard validation (torch-side, can't run under trace).
    for _, fake in named:
        _check_guards_of(_get_record(fake).node)

    fakes = dict(named)
    stacks: Dict[str, List[OpNode]] = {
        name: _tape.build_call_stack(_get_record(fake).node)
        for name, fake in named
    }

    jax_names: List[str] = []
    unsupported: List[Tuple[str, FakeTensor]] = []
    # Probe lowerability cheaply: every non-view node in each call stack
    # must have a lowering.
    for name, fake in named:
        ok = True
        for n in stacks[name]:
            if _is_view_node(n):
                continue
            if _packet_name(n.op.func) not in LOWERINGS:
                ok = False
                break
        (jax_names.append(name) if ok else unsupported.append((name, fake)))

    target_dtypes = {
        name: jnp_dtype_of(dtype or fakes[name].dtype) for name, _ in named
    }

    results: Dict[str, Any] = {}
    if strategy in ("auto", "grouped"):
        group_list, fused_names = _plan_groups(
            jax_names, fakes, stacks, target_dtypes
        )
    elif strategy == "fused":
        group_list, fused_names = [], list(jax_names)
    else:
        raise ValueError(f"unknown strategy: {strategy!r}")

    # Tape ordinals: distinct tapes reachable from the targets, numbered in
    # first-appearance order over the named params' stacks (deterministic
    # across processes — iteration follows module naming order).
    tape_ordinals: Dict[int, int] = {}
    for name, _ in named:
        for n in stacks[name]:
            tape_ordinals.setdefault(n.base_nr, len(tape_ordinals))

    if jax_names:
        import numpy as np

        # Pool trivial fill stacks across shapes into bucketed vmapped
        # draws; only the remaining groups pay per-signature templates.
        global last_fill_fastpath_params
        if _fill_fastpath_enabled():
            bin_list, fill_ins, tmpl_groups = _plan_fill_bins(
                group_list, stacks, target_dtypes, tape_ordinals
            )
        else:
            bin_list, fill_ins, tmpl_groups = [], [], list(group_list)
        last_fill_fastpath_params = sum(
            len(_bin_names(b)) for b in bin_list
        )
        if last_fill_fastpath_params:
            _T_FILL_FAST.add(last_fill_fastpath_params)

        # Instance-distribution axis for shard_map'd generation: the
        # largest mesh axis (shared by the big-fill job and the template
        # groups below).
        shard_axis = None
        if mesh is not None and mesh.devices.size > 1:
            shard_axis = max(mesh.shape, key=lambda a: mesh.shape[a])
            if mesh.shape[shard_axis] <= 1:
                shard_axis = None

        # Multi-device meshes: large fills leave the template path for the
        # big-fill job (direct draws shard; vmapped replay replicates —
        # see _plan_big_fills).  Single-device runs keep the template path:
        # program structure there is tuned for tunnel RPC count.
        if mesh is not None and mesh.devices.size > 1:
            big_list, big_ins, tmpl_groups = _plan_big_fills(
                tmpl_groups, stacks, target_dtypes, tape_ordinals,
                plan, fakes, mesh,
            )
        else:
            big_list, big_ins = [], []
        if mesh is not None and mesh.devices.size > 1:
            # Anything still generated replicated is visible, not silent.
            lone = [
                (g["names"][0],
                 int(_MetaWindow(
                     g["rep"][1].node.out_metas[g["rep"][1].index]
                 ).numel))
                for g in tmpl_groups
                if len(g["names"]) == 1
            ]
            if lone:
                logging.getLogger(__name__).info(
                    "materialize: %d singleton group(s) generate "
                    "replicated on the mesh: %s",
                    len(lone),
                    ", ".join(f"{n} ({sz} elems)" for n, sz in lone),
                )

        templates = [
            _make_template(*g["rep"], target_dtypes[g["names"][0]])
            for g in tmpl_groups
        ]
        # Per-group traced inputs: per-instance per-node RNG identities —
        # (tape ordinal, tape-relative op_nr) rows of shape (n_inst,
        # n_nodes) — and external tensor slots stacked along the instance
        # axis.  Instance data enters as *arguments*, so the traced program
        # is byte-identical for any same-architecture materialization
        # (exec-cache and persistent-cache hits).
        ords_in = [
            np.asarray(
                [
                    [tape_ordinals[n.base_nr] for n in stacks[name]]
                    for name in g["names"]
                ],
                dtype=np.uint32,
            )
            for g in tmpl_groups
        ]
        rels_in = [
            np.asarray(
                [
                    [n.op_nr - n.base_nr for n in stacks[name]]
                    for name in g["names"]
                ],
                dtype=np.uint32,
            )
            for g in tmpl_groups
        ]
        exts_in = [
            [
                np.stack(
                    [
                        g["exts"][i][j].detach().cpu().numpy()
                        for i in range(len(g["names"]))
                    ]
                )
                for j in range(len(g["exts"][0]))
            ]
            for g in tmpl_groups
        ]

        def compute_rest(base_key, ords_in, rels_in, exts_in):
            fold = jax.vmap(
                jax.vmap(
                    lambda o, r: jax.random.fold_in(
                        jax.random.fold_in(base_key, o), r
                    )
                )
            )
            out = {}
            # Signature groups: one vmapped template each — the compiled
            # program contains one subgraph per unique layer *kind*, not per
            # layer (compile time O(unique kinds), not O(depth)).
            #
            # On a mesh, every multi-instance group runs the vmap INSIDE
            # shard_map over the largest axis (instance rows padded up to a
            # multiple of the axis): each device replays only its own
            # instances.  Without this the SPMD partitioner cannot push the
            # per-param out_shardings back through the unstack/replay
            # machinery and REPLICATES every group's generation on every
            # device — measured 8 × full-model f32 RSS for a 1.35B HF
            # materialize on the 8-device virtual mesh.  Values are
            # unchanged: per-instance keys don't depend on placement.
            # Large-fill groups were already extracted to the big-fill job
            # (direct draws shard natively); remaining singleton groups
            # (e.g. a lone rotary buffer) stay replicated — their transient
            # is one small param, logged at plan time.
            for g, template, ords, rels, exts in zip(
                tmpl_groups, templates, ords_in, rels_in, exts_in
            ):
                import jax.numpy as _jnp

                keys = fold(ords, rels)
                n_inst = len(g["names"])
                ax = shard_axis
                if ax is not None and n_inst >= 2:
                    from jax.sharding import PartitionSpec as _P

                    from .parallel.pipeline import _shard_map

                    # Pad the instance axis up to a multiple of the mesh
                    # axis (repeating leading rows — their values are
                    # computed twice and dropped) so every multi-instance
                    # group distributes; only singletons stay replicated.
                    A = mesh.shape[ax]
                    pad = (-n_inst) % A
                    if pad:
                        reps = -(-(n_inst + pad) // n_inst)

                        def _padrow(x):
                            return _jnp.concatenate([x] * reps)[
                                : n_inst + pad
                            ]

                        keys = _padrow(keys)
                        exts = jax.tree.map(_padrow, exts)
                    row = _P(ax)
                    res = _shard_map(
                        lambda k, e: jax.vmap(template)(k, e),
                        mesh,
                        in_specs=(row, jax.tree.map(lambda _: row, exts)),
                        out_specs=row,
                        manual_axes={ax},
                    )(keys, exts)
                else:
                    res = jax.vmap(template)(keys, exts)
                for i, name in enumerate(g["names"]):
                    out[name] = res[i]
            # Fused leftovers: union of the remaining targets' call stacks,
            # replayed once in global chronological order — a per-target
            # replay could advance a shared storage past an earlier target's
            # read point (write-after-read through an alias), making results
            # depend on traversal order.
            if fused_names:
                eng = _FunctionalReplay(
                    base_key,
                    check_guards=False,
                    key_lookup=lambda node: jax.random.fold_in(
                        jax.random.fold_in(
                            base_key,
                            tape_ordinals.setdefault(
                                node.base_nr, len(tape_ordinals)
                            ),
                        ),
                        node.op_nr - node.base_nr,
                    ),
                )
                nodes: Dict[int, OpNode] = {}
                for name in fused_names:
                    for n in stacks[name]:
                        nodes[n.op_nr] = n
                for nr in sorted(nodes):
                    eng.run_node(nodes[nr])
                for name in fused_names:
                    rec = _get_record(fakes[name])
                    out[name] = eng.value_of_output(
                        rec.node, rec.index
                    ).astype(target_dtypes[name])
            return out

        if mesh is not None:
            from jax.sharding import NamedSharding

            shardings = {
                name: NamedSharding(
                    mesh, _resolve_spec(plan, name, fakes[name], mesh)
                )
                for name in jax_names
            }
        else:
            shardings = None

        # Device-id + per-output-sharding component of program identity:
        # str(NamedSharding) omits device identities — two same-shape meshes
        # over different devices must not share executables.
        def _mesh_key(names):
            if mesh is None:
                return None
            return (
                tuple(d.id for d in mesh.devices.flat),
                tuple(
                    (name, str(shardings[name])) for name in sorted(names)
                ),
            )

        def _hashable_or_none(key):
            try:
                hash(key)
            except TypeError:
                return None
            return key

        # The materialization is a set of independent programs — one per
        # fill bin plus one for the template/fused remainder — each
        # separately exec-cached (the AOT executable, not the jit wrapper:
        # the wrapper would pin the tape closure) and, on a miss, compiled
        # CONCURRENTLY: XLA compiles are independent, and on a tunneled
        # backend wall-clock compile time is dominated by per-program
        # round-trips (measured 6× speedup at 12 programs).
        #
        # Program identity excludes the seed — the base key is a traced
        # input, so one executable serves a whole seed sweep.
        #
        # cache_everything covers the WHOLE section, not just the compiles:
        # key construction (`jax.random.key` for rbg dispatches a few tiny
        # eager programs — threefry_seed, convert, concatenate) costs
        # ~0.5-0.8s PER PROGRAM to compile on a tunneled backend, and JAX's
        # default admission threshold (min 1s compile time) would silently
        # refuse to persist them — every process would pay them again.
        from .utils.compilation_cache import cache_everything

        with cache_everything():
            base_key = _base_key(seed, rng_impl)
        jobs = []  # (exec_key|None, trace_fn, args, out_shardings|None)
        shadow_jobs = []  # compiled+cached for future runs, never executed
        if bin_list:
            # ALL fill bins ride ONE program on cached runs: each
            # executable costs a deserialize + device-load RPC on a
            # cached-cold run (~0.3-0.6 s over the tunnel), so per-bin
            # programs made exec loads the cached-cold floor.  But a
            # merged program compiles its bins SERIALLY server-side,
            # while separate bins compile CONCURRENTLY — so on a compile
            # run the bins stay per-program (fast first materialize) and
            # the merged fillpack is compiled as a SHADOW job in the same
            # pool (overlapped, results discarded) purely to seed the
            # cache for future cached-cold runs.
            fill_names = [n for b in bin_list for n in _bin_names(b)]
            fkey = _hashable_or_none(
                (
                    "fillpack",
                    rng_impl,
                    tuple(
                        (str(b["ddt"]), b["bucket"], _bin_entry_key(b))
                        for b in bin_list
                    ),
                    _mesh_key(fill_names),
                )
            )
            bin_fns = [_make_bin_fn(b) for b in bin_list]

            def fills_fn(base_key, all_fins):
                out = {}
                for fn, fins in zip(bin_fns, all_fins):
                    out.update(fn(base_key, fins))
                return out

            osh_all = (
                {name: shardings[name] for name in fill_names}
                if shardings is not None
                else None
            )
            fill_args = (base_key, list(fill_ins))
            # Existence probe only — a stale blob (e.g. after a runtime
            # upgrade) routes ONE materialize through a serial merged
            # compile, which stores a fresh blob (self-healing); probing
            # loadability here would pay the full deserialize RPC up
            # front on every cached-cold run instead.
            merged_ready = fkey is not None and (
                _exec_cache_get(fkey) is not None or _exec_disk_has(fkey)
            )
            if merged_ready:
                jobs.append((fkey, fills_fn, fill_args, osh_all))
            else:
                for b, fn, fins in zip(bin_list, bin_fns, fill_ins):
                    names = _bin_names(b)
                    bkey = _hashable_or_none(
                        (
                            "fillbin",
                            str(b["ddt"]),
                            b["bucket"],
                            rng_impl,
                            _bin_entry_key(b),
                            _mesh_key(names),
                        )
                    )
                    osh = (
                        {name: shardings[name] for name in names}
                        if shardings is not None
                        else None
                    )
                    jobs.append((bkey, fn, (base_key, fins), osh))
                if fkey is not None and _exec_cache_enabled():
                    shadow_jobs.append(
                        (fkey, fills_fn, fill_args, osh_all)
                    )

        # Big-fill classes: ONE single-instance program per (kind, dtype,
        # shape, target dtype, sharding) class, executed once per instance
        # with the instance's key/scalars as replicated inputs.  See
        # _make_bigfill_class_fn for why this shape wins.  Class programs
        # join the same build pool (concurrent compiles / disk loads).
        class_jobs = []
        if big_list:
            from jax.sharding import NamedSharding as _NS
            from jax.sharding import PartitionSpec as _P

            repl = _NS(mesh, _P())
            all_ords = np.concatenate([bi[0] for bi in big_ins])
            all_rels = np.concatenate([bi[1] for bi in big_ins])
            with cache_everything():
                keys_rep = jax.device_put(
                    jax.jit(
                        lambda k, o, r: jax.vmap(
                            lambda oo, rr: jax.random.fold_in(
                                jax.random.fold_in(k, oo), rr
                            )
                        )(o, r)
                    )(base_key, all_ords, all_rels),
                    repl,
                )
                s_rep = [
                    (
                        jax.device_put(bi[2], repl),
                        jax.device_put(bi[3], repl),
                    )
                    for bi in big_ins
                ]
            mesh_ids = tuple(d.id for d in mesh.devices.flat)
            for j, sg in enumerate(big_list):
                osh_c = _NS(mesh, sg["spec"])
                ckey = _hashable_or_none(
                    (
                        "bigfillcls",
                        rng_impl,
                        sg["kind"],
                        str(sg["ddt"]),
                        sg["shape"],
                        str(sg["tdt"]),
                        mesh_ids,
                        str(osh_c),
                    )
                )
                class_jobs.append(
                    (
                        ckey,
                        _make_bigfill_class_fn(sg),
                        (keys_rep[0], s_rep[j][0][0], s_rep[j][1][0]),
                        osh_c,
                    )
                )

        if tmpl_groups or fused_names:
            # Cacheable only when nothing takes the fused path — the fused
            # branch bakes instance data into the trace.
            rest_key = None
            if tmpl_groups and not fused_names and not unsupported:
                rest_key = _hashable_or_none(
                    (
                        "rest",
                        tuple(
                            (g["key"], tuple(g["names"]))
                            for g in tmpl_groups
                        ),
                        rng_impl,
                        _mesh_key(
                            [n for g in tmpl_groups for n in g["names"]]
                        ),
                    )
                )
            rest_names = [n for g in tmpl_groups for n in g["names"]]
            rest_names += fused_names
            osh = (
                {name: shardings[name] for name in rest_names}
                if shardings is not None
                else None
            )
            jobs.append(
                (rest_key, compute_rest,
                 (base_key, ords_in, rels_in, exts_in), osh)
            )

        # --- Mono executable: the WHOLE single-chip materialization as ONE
        # program.  On a tunneled backend the cached-cold floor is the
        # executable-load RPCs (deserialize + device load each); the mono
        # path needs exactly one exec load, one packed host→device
        # transfer, and one dispatch — measured ~25% faster cached-cold
        # than the per-program loads on gpt2small AND gpt2xl (interleaved
        # A/B).  Composed from the CANONICAL job set — the merged fillpack
        # + the rest program — NOT this run's `jobs` list, whose shape
        # differs between the first run (per-bin jobs) and cached runs
        # (merged fillpack): a key over `jobs` could never hit the blob
        # its own first run seeded.  Identity = canonical keys + packed
        # layout, so any change in architecture/plan/dtype misses cleanly;
        # per-job caches remain the fallback.  Compiled as a shadow job on
        # miss — overlapped with the real compiles.  Single-device only:
        # mesh runs are local (no tunnel RPC economics).
        import os as _os

        mono_key = None
        mono_jobs = []
        if (
            jobs
            and mesh is None
            and not unsupported
            and _exec_cache_enabled()
            and not _os.environ.get("TDX_NO_MONO")
        ):
            if bin_list:
                mono_jobs.append((fkey, fills_fn, fill_args))
            if tmpl_groups or fused_names:
                mono_jobs.append(
                    (rest_key, compute_rest,
                     (base_key, ords_in, rels_in, exts_in))
                )
            if mono_jobs and all(k is not None for k, _, _ in mono_jobs):
                all_args_m = [a for _, _, a in mono_jobs]
                leaves_m, treedef_m = jax.tree.flatten(all_args_m)
                # Every non-host leaf must be the base key (true for all
                # current job shapes); anything else falls back silently.
                if all(
                    isinstance(l, np.ndarray) or l is base_key
                    for l in leaves_m
                ):
                    by_dt_m, order_m, layout_m, packed_m = (
                        _pack_host_leaves(leaves_m)
                    )
                    mono_key = _hashable_or_none(
                        (
                            "mono",
                            tuple(k for k, _, _ in mono_jobs),
                            layout_m,
                            rng_impl,
                        )
                    )
        if mono_key is not None:

            def _mono_fn(bk, *bufs):
                vals = _unpack_bufs(bufs, by_dt_m, order_m, layout_m)
                new_leaves = [
                    vals.get(i, bk) for i in range(len(leaves_m))
                ]
                out = {}
                for (_, fn, _), a in zip(
                    mono_jobs, jax.tree.unflatten(treedef_m, new_leaves)
                ):
                    out.update(fn(*a))
                return out

            mfn = _exec_cache_get(mono_key)
            if mfn is None:
                mfn = _exec_disk_get(mono_key)
                if mfn is not None:
                    _exec_cache_put(mono_key, mfn, disk=False)
            if mfn is not None:
                # Phase stamps land here; the downstream stamps are
                # setdefault so the mono timings aren't overwritten.
                last_profile["plan_s"] = _sp_plan.end()
                last_profile["compile_s"] = 0.0
                _sp = _telemetry.start_span(
                    "materialize.transfer", job="mono"
                )
                buf_dev = jax.device_put(packed_m)
                last_profile["transfer_s"] = _sp.end()
                _sp = _telemetry.start_span(
                    "materialize.execute", job="mono"
                )
                results.update(mfn(base_key, *buf_dev))
                if _profile_enabled():
                    jax.block_until_ready(list(results.values()))
                    rss = _rss_mb_now()
                    _sp.end(rss_mb=rss)
                    last_profile["jobs"].append(
                        ("mono", _sp.duration, rss)
                    )
                last_profile["exec_s"] = _sp.end()
                exec_cache_hits += 1
                _T_EXEC_HITS.add()
                # Everything executed; the sections below see empty work.
                jobs, class_jobs, shadow_jobs = [], [], []
            else:
                shadow_jobs.append(
                    (mono_key, _mono_fn, (base_key, *packed_m), None)
                )

        last_profile.setdefault("plan_s", _sp_plan.end())
        compiled: Dict[int, Any] = {}
        misses = []
        n_exec = len(jobs) + len(class_jobs)
        for i, (key, _, _, _) in enumerate(jobs + class_jobs):
            # Memory tier only here; the disk tier (deserialize + device
            # load, a tunnel RPC each) runs inside the pool below so loads
            # overlap like compiles do.
            hit = _exec_cache_get(key) if key is not None else None
            compiled[i] = hit
            if hit is None:
                misses.append(i)

        # Shadow jobs (the merged fillpack) ride the same pool — compiled
        # concurrently with the real misses, stored for future cached-cold
        # runs, never executed this run.  They do NOT count toward
        # had_compiles: a run whose every EXECUTED program was cached is
        # still a cache hit even while it seeds the merged blob.
        build_list = jobs + class_jobs + shadow_jobs
        misses += range(n_exec, len(build_list))
        had_compiles = False
        if misses:
            _sp_compile = _telemetry.start_span(
                "materialize.compile", n_programs=len(misses)
            )

            def _build(i):
                nonlocal had_compiles
                key, fn, args, osh = build_list[i]
                if key is not None:
                    cfn = _exec_disk_get(key)
                    if cfn is not None:
                        _exec_cache_put(key, cfn, disk=False)
                        return cfn
                if i < n_exec:
                    had_compiles = True
                jfn = (
                    jax.jit(fn, out_shardings=osh)
                    if osh is not None
                    else jax.jit(fn)
                )
                # Observatory scope per worker thread: the monitoring
                # listener attributes the backend compile precisely;
                # without monitoring, ensure_counted records the
                # lower+compile wall time instead — exactly once either
                # way.  (A persistent-cache hit compiles nothing and
                # deserializes in milliseconds; it still counts as a
                # program load, which is what the count family tracks.)
                import time as _time

                _t0 = _time.perf_counter()
                with _perf.program("materialize") as _sc:
                    cfn = jfn.lower(*args).compile()
                _sc.ensure_counted(_time.perf_counter() - _t0)
                _T_COMPILES.add()
                if key is not None:
                    _exec_cache_put(key, cfn)
                return cfn

            with cache_everything():
                if len(misses) == 1:
                    compiled[misses[0]] = _build(misses[0])
                else:
                    from concurrent.futures import ThreadPoolExecutor

                    with ThreadPoolExecutor(
                        min(len(misses), 16)
                    ) as pool:
                        for i, cfn in zip(
                            misses, pool.map(_build, misses)
                        ):
                            compiled[i] = cfn
            last_profile.setdefault("compile_s", _sp_compile.end())

        last_profile.setdefault("compile_s", 0.0)
        # Ship every job's host argument leaves in ONE transfer per dtype:
        # on a tunneled backend each host→device put is a full RPC (~40 ms
        # measured), and the ~70 tiny index/fill arrays (a few KB total!)
        # cost seconds when transferred one by one — that dominated
        # cached-cold wall time.  Pack per dtype on host, put once, and
        # unpack on device with a small exec-cached program (slice +
        # reshape is free for XLA).
        #
        # The argpack applies to single-device runs only — that is where
        # the per-RPC cost lives (the tunneled chip).  Mesh jobs instead
        # get their host leaves explicitly placed as mesh-replicated
        # arrays (the elif below): Compiled.__call__ input-sharding
        # tolerance for committed single-device arrays against
        # mesh-lowered programs is version-dependent (advisor r4), so we
        # hand them the placement they were lowered for.
        all_args = [args for _, _, args, _ in jobs]
        if jobs and mesh is None:
            _sp_transfer = _telemetry.start_span("materialize.transfer")
            leaves, treedef = jax.tree.flatten(all_args)
            by_dtype, order, layout, packed = _pack_host_leaves(leaves)
            if packed:
                unpack_key = ("argpack", layout)
                ufn = _exec_cache_get(unpack_key)
                if ufn is None:
                    ufn = _exec_disk_get(unpack_key)
                    if ufn is not None:
                        _exec_cache_put(unpack_key, ufn, disk=False)
                if ufn is None:

                    def unpack(*bufs):
                        vals = _unpack_bufs(bufs, by_dtype, order, layout)
                        # dtype-major slot order — matches the consuming
                        # loop below AND executables cached by earlier
                        # versions of this layout key.
                        return tuple(
                            vals[i] for dt in order for i in by_dtype[dt]
                        )

                    with cache_everything():
                        ufn = jax.jit(unpack).lower(*packed).compile()
                    _exec_cache_put(unpack_key, ufn)
                unpacked = iter(ufn(*jax.device_put(packed)))
                for dt in order:
                    for i in by_dtype[dt]:
                        leaves[i] = next(unpacked)
            all_args = jax.tree.unflatten(treedef, leaves)
            last_profile.setdefault("transfer_s", _sp_transfer.end())
        elif jobs:
            # Mesh jobs: hand the executables explicitly mesh-replicated
            # inputs rather than raw host leaves (VERDICT item 8b — see
            # _replicate_mesh_args).
            _sp_transfer = _telemetry.start_span("materialize.transfer")
            all_args = _replicate_mesh_args(all_args, mesh)
            last_profile.setdefault("transfer_s", _sp_transfer.end())
        last_profile.setdefault("transfer_s", 0.0)
        _sp_exec = (
            _telemetry.start_span(
                "materialize.execute",
                n_jobs=len(jobs),
                n_classes=len(big_list),
            )
            if jobs or big_list
            else None
        )
        _prof = _profile_enabled()
        for i in range(len(jobs)):
            if _prof:
                key = jobs[i][0]
                label = (
                    key[0] if isinstance(key, tuple) and key else "rest"
                )
                _spj = _telemetry.start_span(
                    "materialize.job", label=label
                )
                res_i = compiled[i](*all_args[i])
                jax.block_until_ready(list(res_i.values()))
                rss = _rss_mb_now()
                _spj.end(rss_mb=rss)
                last_profile["jobs"].append((label, _spj.duration, rss))
            else:
                res_i = compiled[i](*all_args[i])
            results.update(res_i)
        # Big-fill classes: one dispatch per instance of the class's
        # compiled program (dispatches are cheap; compiles were O(classes)).
        _spb = (
            _telemetry.start_span("materialize.job", label="bigfillcls")
            if _prof and big_list
            else None
        )
        off = 0
        for j, sg in enumerate(big_list):
            cfn = compiled[len(jobs) + j]
            s0r, s1r = s_rep[j]
            for t, e in enumerate(sg["entries"]):
                results[e["name"]] = cfn(keys_rep[off + t], s0r[t], s1r[t])
            off += len(sg["entries"])
        if _spb is not None:
            jax.block_until_ready(
                [results[e["name"]] for sg in big_list for e in sg["entries"]]
            )
            rss = _rss_mb_now()
            _spb.end(rss_mb=rss)
            last_profile["jobs"].append(
                ("bigfillcls", _spb.duration, rss)
            )
        last_profile.setdefault(
            "exec_s", _sp_exec.end() if _sp_exec is not None else 0.0
        )
        if (jobs or class_jobs) and not had_compiles:
            exec_cache_hits += 1
            _T_EXEC_HITS.add()

    # Torch fallback for ops with no lowering: replay on host, transfer with
    # the planned sharding.  Per-tensor, so peak host RAM ≈ largest param.
    if unsupported:
        if not _fallback_torch:
            raise UnsupportedOpError(
                f"No JAX lowering for params: {[n for n, _ in unsupported]}"
            )
        from .deferred_init import materialize_tensor

        if _sp_plan.duration is None:
            # No jax-path planning closed the phase (every param is
            # unsupported): drop it BEFORE the fallback span starts, so
            # the fallback parents on materialize.module rather than on a
            # plan span the trace will never contain.
            _sp_plan.cancel()
        _T_TORCH_FALLBACK.add(len(unsupported))
        with _telemetry.span(
            "materialize.torch_fallback", n_params=len(unsupported)
        ):
            for name, fake in unsupported:
                real = materialize_tensor(fake, device="cpu")
                arr = jax.numpy.asarray(
                    real.detach().cpu().numpy(), dtype=target_dtypes[name]
                )
                if mesh is not None:
                    from jax.sharding import NamedSharding

                    arr = jax.device_put(
                        arr,
                        NamedSharding(
                            mesh, _resolve_spec(plan, name, fake, mesh)
                        ),
                    )
                results[name] = arr
    if _sp_plan.duration is None:
        # No jax-path planning happened (every param unsupported): the
        # plan phase never closed — drop it rather than record the whole
        # call under the wrong name.
        _sp_plan.cancel()
    _sp_call.end(n_params=len(results))
    _telemetry.emit_counters()
    return results
