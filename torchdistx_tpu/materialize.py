"""TPU-native materialization: replay the deferred-init tape as JAX arrays.

This is the reason this framework exists (SURVEY.md §7, BASELINE.md): take a
module whose parameters are fake + recorded, and instantiate them **directly
as (sharded) ``jax.Array`` leaves on a TPU mesh** — shard-then-materialize
with no full-tensor host round-trip.  The reference stops at replaying onto
real torch devices (deferred_init.cc:505-666); the TPU-native path compiles
the whole init subgraph into a single ``jit`` whose ``out_shardings`` place
every parameter shard on its device over ICI, letting XLA's SPMD partitioner
generate per-shard init (including partitioned RNG) without ever building the
full tensor anywhere.

Mutation/view semantics on an immutable substrate
-------------------------------------------------
The reference replays in-place/view-heavy init code onto *mutable storage*.
Functionally, each recorded meta **storage** becomes a flat value in an
environment; tensors are strided windows onto those values:

* reading a tensor = strided gather from its storage buffer
  (fast path: contiguous whole-storage view = reshape);
* an in-place op = pure compute + strided scatter back through the written
  tensor's layout;
* a view op = no compute at all — its outputs are just layouts, resolved at
  read time (this subsumes the reference's view keep-alive and aliasing
  machinery, deferred_init.cc:416-461).

Replay order is the same chronological call-stack the torch path uses
(_tape.build_call_stack ≈ deferred_init.cc:529-621), so write-after-write and
read-after-write through any alias resolve exactly as recorded.

RNG: every node draws from
``fold_in(fold_in(key(seed), tape_ordinal), tape_relative_op_nr)`` where
``tape_ordinal`` numbers the distinct tapes reachable from the target(s) in
first-appearance order and the relative op_nr is ``op_nr - base_nr`` (first
op of the node's tape).  Properties: deterministic, independent of
materialization order, reproducible across processes *and* across tapes in
one process (absolute op counters never leak in), collision-free when
separately recorded submodules are merged into one module (distinct
ordinals), equal between :func:`materialize_tensor_jax` and
:func:`materialize_module_jax` for the ordinary single-tape module, and
identical across hosts — so multi-host sharded materialization is
consistent by construction (the NCCL-broadcast-init analog: no broadcast
needed at all).

Ops with no JAX lowering fall back to torch replay + ``jax.device_put`` with
the planned sharding (per-tensor, so host RAM stays bounded by the largest
parameter, not the model).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import torch
import torch.nn as nn
import torch.utils._pytree as pytree

from . import _tape
from ._tape import OpNode, OutputRef
from .deferred_init import _get_record, is_deferred
from .fake import FakeTensor
from .ops.aten_jax import LOWERINGS, UnsupportedOpError
from .utils.compilation_cache import ensure_compilation_cache
from .utils.dtypes import jnp_dtype_of

__all__ = [
    "materialize_tensor_jax",
    "materialize_module_jax",
]


def _is_view_node(node: OpNode) -> bool:
    """Pure view op: outputs alias inputs, nothing is written.

    Ground truth is the op schema (the reference infers the same from output
    storages aliasing argument storages, deferred_init.cc:416-461)."""
    if node.mutated_args:
        return False
    try:
        returns = node.op.func._schema.returns
    except AttributeError:
        return False
    return bool(returns) and all(r.alias_info is not None for r in returns)


class _MetaWindow:
    """Layout of one tensor over its flat storage buffer."""

    __slots__ = (
        "storage_key",
        "shape",
        "strides",
        "offset",
        "dtype",
        "numel",
        "storage_elems",
    )

    def __init__(self, meta: torch.Tensor):
        storage = meta.untyped_storage()
        self.storage_key = storage._cdata
        self.shape = tuple(meta.shape)
        self.strides = tuple(meta.stride())
        self.offset = meta.storage_offset()
        self.dtype = meta.dtype
        self.numel = meta.numel()
        self.storage_elems = storage.size() // max(meta.element_size(), 1)

    def is_whole_contiguous(self, buffer_len: int) -> bool:
        if self.offset != 0 or self.numel != buffer_len:
            return False
        expected = 1
        for size, stride in zip(reversed(self.shape), reversed(self.strides)):
            if size != 1 and stride != expected:
                return False
            expected *= size
        return True

    def flat_indices(self):
        import jax.numpy as jnp

        idx = jnp.asarray(self.offset)
        for size, stride in zip(self.shape, self.strides):
            idx = idx[..., None] + jnp.arange(size) * stride
        return idx


class _FunctionalReplay:
    """Replays tape nodes as pure JAX computation over storage buffers.

    ``key_lookup``/``ext_lookup`` parametrize the replay for template reuse
    (the grouped strategy): per-node PRNG keys and external tensor values come
    in as traced arguments instead of being baked into the trace, so one
    compiled program serves every structurally identical call stack.
    """

    def __init__(
        self,
        base_key,
        *,
        check_guards: bool = True,
        key_lookup=None,
        ext_lookup=None,
    ):
        self.base_key = base_key
        self.check_guards = check_guards
        self.key_lookup = key_lookup
        self.ext_lookup = ext_lookup
        # storage key -> (flat jnp value, element count)
        self.storages: Dict[int, Any] = {}
        self.replayed: set = set()
        # Tape base_nr -> ordinal, assigned in replay (chronological) order;
        # recording order is deterministic for a given program, so ordinals
        # are process-stable.  See key_for.
        self.tape_ordinals: Dict[int, int] = {}

    def key_for(self, node: OpNode):
        import jax

        if self.key_lookup is not None:
            return self.key_lookup(node)
        # Stream identity = (tape ordinal, tape-relative op_nr):
        # reproducible across processes and across tapes in one process —
        # absolute op_nrs depend on how many tapes preceded this one and
        # never enter a key — and collision-free when a call stack spans
        # several tapes (each gets a distinct ordinal).  Matches the module
        # path for single-tape modules (module docstring, RNG note).
        ordinal = self.tape_ordinals.setdefault(
            node.base_nr, len(self.tape_ordinals)
        )
        return jax.random.fold_in(
            jax.random.fold_in(self.base_key, ordinal),
            node.op_nr - node.base_nr,
        )

    # -- engine plumbing ----------------------------------------------------

    def read(self, window: _MetaWindow):
        buf = self.storages[window.storage_key]
        if window.is_whole_contiguous(buf.shape[0]):
            return buf.reshape(window.shape)
        return buf[window.flat_indices()]

    def write(self, window: _MetaWindow, value):
        import jax.numpy as jnp

        value = jnp.broadcast_to(value, window.shape).astype(
            jnp_dtype_of(window.dtype)
        )
        buf = self.storages.get(window.storage_key)
        if buf is None:
            # Fresh storage: a flat buffer covering the whole allocation.
            buf = jnp.zeros(
                (window.storage_elems,), dtype=jnp_dtype_of(window.dtype)
            )
        if window.is_whole_contiguous(buf.shape[0]):
            self.storages[window.storage_key] = value.reshape(-1)
        else:
            self.storages[window.storage_key] = buf.at[
                window.flat_indices()
            ].set(value)

    def value_of_output(self, node: OpNode, index: int):
        meta = node.out_metas[index]
        return self.read(_MetaWindow(meta))

    # -- node replay --------------------------------------------------------

    def run_call_stack(self, target: OpNode) -> None:
        for node in _tape.build_call_stack(target):
            self.run_node(node)

    def run_node(self, node: OpNode) -> None:
        import jax
        import jax.numpy as jnp

        if node.op_nr in self.replayed:
            return
        self.replayed.add(node.op_nr)
        if self.check_guards:
            for guard in node.op.guards:
                guard.check()

        if _is_view_node(node):
            # Views are layouts, not computation; ensure the base storage
            # exists (it must, via dependencies) and move on.
            return

        def resolve(a):
            if isinstance(a, OutputRef):
                meta = a.node.out_metas[a.index]
                return self.read(_MetaWindow(meta))
            if isinstance(a, torch.Tensor):
                if self.ext_lookup is not None:
                    return self.ext_lookup(a)
                return jnp.asarray(a.detach().cpu().numpy())
            return a

        op = node.op
        args, kwargs = pytree.tree_map(resolve, (op.args, op.kwargs))
        name = _packet_name(op.func)
        fn = LOWERINGS.get(name)
        if fn is None:
            raise UnsupportedOpError(
                f"No JAX lowering for '{name}' (recorded as {op.name})."
            )

        ctx = _LowerCtx(self, node)
        out = fn(ctx, *args, **_strip_factory_kwargs(kwargs))
        outs = out if isinstance(out, (list, tuple)) else [out]

        if node.mutated_args:
            # In-place: scatter each mutated arg's OWN result back through
            # that tensor's layout (writes are visible through every alias).
            # The arg→output pairing comes from the schema alias sets; a
            # blanket outs[0] would corrupt the second buffer of a
            # two-mutation op such as aminmax.out.
            out_of = _mutation_output_map(op.func, node.mutated_args, len(outs))
            for pos in node.mutated_args:
                ref = _tape.arg_at_schema_pos(op.func, op.args, op.kwargs, pos)
                if isinstance(ref, OutputRef):
                    meta = ref.node.out_metas[ref.index]
                    self.write(_MetaWindow(meta), outs[out_of[pos]])
        # Fresh outputs define their storages.
        for i, meta in enumerate(node.out_metas):
            if meta is None or i >= len(outs):
                continue
            window = _MetaWindow(meta)
            if window.storage_key not in self.storages:
                self.write(window, outs[i])


class _LowerCtx:
    """Per-node context handed to lowerings: PRNG key + output metadata."""

    __slots__ = ("engine", "node")

    def __init__(self, engine: _FunctionalReplay, node: OpNode):
        self.engine = engine
        self.node = node

    @property
    def key(self):
        return self.engine.key_for(self.node)

    def out_meta(self, index: int) -> torch.Tensor:
        return self.node.out_metas[index]


def _packet_name(func) -> str:
    # e.g. "aten.uniform_.default"
    return str(func)


def _mutation_output_map(func, mutated_args, n_outs) -> dict:
    """Map each mutated positional arg to the lowering-output index that
    carries its new value.

    Ground truth is the schema's alias-set pairing: an argument annotated
    ``Tensor(a!)`` is returned by the output annotated ``Tensor(a!)``
    (e.g. ``aminmax.out``'s min/max pair).  Ops whose single mutated arg has
    no aliased return (pure in-place like ``uniform_`` lowered to return the
    new buffer) fall back to output 0; multiple mutated args without a
    schema pairing are refused rather than silently corrupted.
    """
    mapping: dict = {}
    schema = getattr(func, "_schema", None)
    if schema is not None:
        for pos in mutated_args:
            if pos >= len(schema.arguments):
                continue
            ainfo = schema.arguments[pos].alias_info
            if ainfo is None:
                continue
            aset = set(ainfo.before_set)
            for j, ret in enumerate(schema.returns):
                rinfo = ret.alias_info
                if rinfo is not None and aset & set(rinfo.before_set):
                    if j < n_outs:
                        mapping[pos] = j
                    break
    missing = [p for p in mutated_args if p not in mapping]
    if missing:
        if len(mutated_args) == 1 and n_outs >= 1:
            mapping[mutated_args[0]] = 0
        else:
            raise UnsupportedOpError(
                f"Cannot pair mutated args {missing} of '{func}' with "
                f"their outputs ({n_outs} returned): the schema has no "
                "aliased return for them and more than one arg is mutated."
            )
    return mapping


def _strip_factory_kwargs(kwargs: dict) -> dict:
    return {
        k: v
        for k, v in kwargs.items()
        if k not in ("device", "layout", "pin_memory", "memory_format",
                     "non_blocking", "generator")
    }


# ---------------------------------------------------------------------------
# Grouped (template) materialization: structural dedup of call stacks.
#
# Deep models repeat their init structure — 48 transformer blocks record 48
# structurally identical call stacks per parameter kind, differing only in
# PRNG stream (op_nr) and captured external tensors.  Compiling the union
# program (the "fused" strategy) makes XLA chew through O(depth) copies of
# the same subgraph; grouping instead compiles ONE small program per unique
# stack *signature* (op sequence + shapes + scalar args) with per-node keys
# and externals passed as traced arguments, then executes it per instance
# (vmap-batched off-mesh).  Compile time becomes O(unique layer kinds), not
# O(depth) — the TPU-idiomatic shape for init, and the reason the deferred
# path beats eager init+transfer (BASELINE.md).


def _analyze_stack(stack: List[OpNode], record) -> Optional[Tuple]:
    """Signature + per-instance data for one call stack.

    Returns ``(sig, ext_values)`` where ``sig`` is a hashable
    structural signature — two stacks with equal signatures trace to
    identical jaxprs when replayed with keys/externals as arguments — or
    ``None`` if the stack is not groupable (unlowerable op present).
    """
    local = {n.op_nr: i for i, n in enumerate(stack)}
    storage_ids: Dict[int, int] = {}

    def sid(key: int) -> int:
        return storage_ids.setdefault(key, len(storage_ids))

    def win_sig(meta: Optional[torch.Tensor]):
        if meta is None:
            return None
        w = _MetaWindow(meta)
        return (
            sid(w.storage_key),
            w.shape,
            w.strides,
            w.offset,
            str(w.dtype),
            w.storage_elems,
        )

    ext_values: List[torch.Tensor] = []
    node_sigs = []
    for n in stack:
        is_view = _is_view_node(n)
        if not is_view and _packet_name(n.op.func) not in LOWERINGS:
            return None

        def norm(a):
            if isinstance(a, OutputRef):
                i = local.get(a.node.op_nr)
                if i is None:
                    # Dependency outside the stack — cannot template.
                    raise _NotGroupable
                return ("ref", i, a.index)
            if isinstance(a, torch.Tensor):
                if is_view:
                    # View nodes are never resolved at replay; their args
                    # must not consume external slots.
                    return ("viewext", tuple(a.shape), str(a.dtype))
                ext_values.append(a)
                return ("ext", len(ext_values) - 1, tuple(a.shape), str(a.dtype))
            if isinstance(
                a,
                (torch.dtype, torch.device, torch.layout, torch.memory_format),
            ):
                return ("t", str(a))
            return ("v", a)

        try:
            leaves, treedef = pytree.tree_flatten((n.op.args, n.op.kwargs))
            norm_leaves = tuple(norm(a) for a in leaves)
        except _NotGroupable:
            return None
        except TypeError:
            return None  # unhashable leaf somewhere; fused path handles it
        node_sigs.append(
            (
                _packet_name(n.op.func),
                repr(treedef),
                norm_leaves,
                tuple(win_sig(m) for m in n.out_metas),
                tuple(n.mutated_args),
                is_view,
            )
        )

    sig = (
        tuple(node_sigs),
        local[record.node.op_nr],
        record.index,
    )
    try:
        hash(sig)
    except TypeError:
        return None
    return sig, ext_values


class _NotGroupable(Exception):
    pass


def _make_template(stack: List[OpNode], record, target_dtype):
    """Build the replay template for one signature group.

    Closes over the *representative* instance's nodes (shapes/ops identical
    across the group by signature equality); per-node PRNG keys and external
    tensor values come in as arguments, so the jitted template is reused by
    every instance.
    """
    local = {n.op_nr: i for i, n in enumerate(stack)}

    def template(keys, exts):
        ext_iter = iter(exts)
        eng = _FunctionalReplay(
            None,
            check_guards=False,
            key_lookup=lambda node: keys[local[node.op_nr]],
            ext_lookup=lambda t: next(ext_iter),
        )
        for n in stack:
            eng.run_node(n)
        return eng.value_of_output(record.node, record.index).astype(
            target_dtype
        )

    return template


# ---------------------------------------------------------------------------
# Public API


def _named_fakes(module: nn.Module) -> List[Tuple[str, FakeTensor]]:
    out = []
    for name, p in module.named_parameters(remove_duplicate=True):
        if is_deferred(p):
            out.append((name, p))
    for name, b in module.named_buffers(remove_duplicate=True):
        if is_deferred(b):
            out.append((name, b))
    return out


def _resolve_spec(plan, name: str, fake: FakeTensor, mesh=None):
    from jax.sharding import PartitionSpec

    from .parallel.sharding import fit_spec_to_mesh, replicate_indivisible

    if plan is None:
        return PartitionSpec()
    if callable(plan):
        spec = plan(name, tuple(fake.shape))
    else:
        spec = plan.get(name)
    if spec is None:
        return PartitionSpec()
    if mesh is None:
        return spec
    return replicate_indivisible(
        fit_spec_to_mesh(spec, mesh), tuple(fake.shape), mesh
    )


def _base_key(seed: int, rng_impl: str):
    import jax

    return jax.random.key(seed, impl=rng_impl)


def materialize_tensor_jax(
    tensor: torch.Tensor,
    *,
    mesh=None,
    spec=None,
    seed: int = 0,
    dtype: Optional[torch.dtype] = None,
    rng_impl: str = "threefry2x32",
):
    """Materialize one fake tensor as a ``jax.Array`` (optionally sharded).

    ``rng_impl``: ``"threefry2x32"`` (default — bitwise stable across
    topologies/shardings, the multi-host guarantee) or ``"rbg"`` (XLA
    RngBitGenerator — much cheaper to compile, for single-chip or
    throwaway-init use; values may depend on backend/sharding).
    """
    import jax

    ensure_compilation_cache()

    record = _get_record(tensor) if isinstance(tensor, FakeTensor) else None
    if record is None:
        raise ValueError("`tensor` is not a deferred fake tensor.")

    target_dtype = jnp_dtype_of(dtype or tensor.dtype)

    def compute():
        eng = _FunctionalReplay(_base_key(seed, rng_impl), check_guards=False)
        eng.run_call_stack(record.node)
        return eng.value_of_output(record.node, record.index).astype(
            target_dtype
        )

    _check_guards_of(record.node)
    from .utils.compilation_cache import cache_everything

    with cache_everything():
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            sharding = NamedSharding(mesh, spec or PartitionSpec())
            return jax.jit(compute, out_shardings=sharding)()
        return jax.jit(compute)()


def _check_guards_of(target: OpNode) -> None:
    # Guard checks touch torch tensors; run them eagerly (outside jit trace).
    for node in _tape.build_call_stack(target):
        for guard in node.op.guards:
            guard.check()


def _plan_groups(
    jax_names: List[str],
    fakes: Dict[str, FakeTensor],
    stacks: Dict[str, List[OpNode]],
    target_dtypes: Dict[str, Any],
) -> Tuple[List[dict], List[str]]:
    """Partition params into signature groups and fused leftovers.

    A param is groupable iff its stack shares no node with any other param's
    stack (per-target replay of a shared storage could otherwise advance it
    past another target's read point) and every arg is hashable/templatable.
    Returns ``(group_list, leftover_names)``; each group carries its
    representative stack, per-instance external tensors, and op_nr rows.
    """
    owner_count: Dict[int, int] = {}
    for name in jax_names:
        for n in stacks[name]:
            owner_count[n.op_nr] = owner_count.get(n.op_nr, 0) + 1

    groups: Dict[tuple, dict] = {}
    fused: List[str] = []
    for name in jax_names:
        stack = stacks[name]
        if any(owner_count[n.op_nr] > 1 for n in stack):
            fused.append(name)
            continue
        rec = _get_record(fakes[name])
        analyzed = _analyze_stack(stack, rec)
        if analyzed is None:
            fused.append(name)
            continue
        sig, ext_values = analyzed
        key = (sig, str(target_dtypes[name]))
        g = groups.setdefault(
            key,
            {"key": key, "names": [], "exts": [], "rep": (stack, rec)},
        )
        g["names"].append(name)
        g["exts"].append(ext_values)
    return list(groups.values()), fused


# ---------------------------------------------------------------------------
# In-process executable cache.
#
# The group signature IS the program identity: two materializations whose
# groups carry equal signatures (and names/shardings/seed/rng) trace to the
# same jaxpr, with all instance data — op_nr rows, external tensors —
# entering as traced inputs.  Re-materializing the same architecture in one
# process (hyperparameter sweeps, re-init after resharding, test suites)
# therefore reuses the compiled executable outright: no retrace, no XLA
# compile, no persistent-cache deserialization.  Cross-process warm starts
# are covered separately by the persistent compilation cache
# (utils/compilation_cache.py).

_EXEC_CACHE: "Dict[tuple, Any]" = {}
_EXEC_CACHE_MAX = 16
_EXEC_CACHE_LOCK = threading.Lock()
exec_cache_hits = 0  # introspection for tests/benchmarks


def _exec_cache_enabled() -> bool:
    import os

    return not os.environ.get("TDX_NO_EXEC_CACHE")


def _exec_cache_get(key):
    global exec_cache_hits
    if not _exec_cache_enabled():
        return None
    with _EXEC_CACHE_LOCK:
        fn = _EXEC_CACHE.get(key)
        if fn is not None:
            exec_cache_hits += 1
            # LRU refresh: eviction pops the front, so a hit must move the
            # key to the back or a hot architecture can be evicted over
            # cold ones.
            del _EXEC_CACHE[key]
            _EXEC_CACHE[key] = fn
    return fn


def _exec_cache_put(key, fn) -> None:
    if not _exec_cache_enabled():
        return
    with _EXEC_CACHE_LOCK:
        if key not in _EXEC_CACHE and len(_EXEC_CACHE) >= _EXEC_CACHE_MAX:
            _EXEC_CACHE.pop(next(iter(_EXEC_CACHE)))
        _EXEC_CACHE[key] = fn


def materialize_module_jax(
    module: nn.Module,
    *,
    mesh=None,
    plan: Optional[Any] = None,
    seed: int = 0,
    dtype: Optional[torch.dtype] = None,
    rng_impl: str = "threefry2x32",
    strategy: str = "auto",
    _fallback_torch: bool = True,
) -> Dict[str, Any]:
    """Materialize every fake param/buffer of ``module`` as JAX arrays.

    Returns ``{qualified_name: jax.Array}`` with per-leaf shardings from
    ``plan`` — XLA SPMD generates each shard on its own device.

    ``plan``: ``None`` (replicated), a dict ``{name: PartitionSpec}``, or a
    callable ``(name, shape) -> PartitionSpec | None`` (see
    :mod:`torchdistx_tpu.parallel.sharding` for FSDP/TP plan builders).
    ``dtype``: optional cast applied to every leaf (e.g. ``torch.bfloat16``
    for TPU training).  ``rng_impl``: see :func:`materialize_tensor_jax`
    (``"rbg"`` roughly halves XLA compile time for init-heavy tapes).

    ``strategy``:

    * ``"grouped"``/``"auto"`` — dedupe structurally identical per-param call
      stacks and compile one small program per unique signature (compile time
      O(unique layer kinds), not O(depth)); params whose stacks share nodes
      with other params fall back to the fused program, preserving
      write-ordering semantics through aliases.
    * ``"fused"`` — one monolithic jit of the union init subgraph (the
      round-1 behavior).

    XLA compile time dominates a cold materialization; the emitted HLO is
    process-stable by design, and the persistent compilation cache is
    enabled on first use (see utils/compilation_cache.py), so warm runs —
    restarts, sweeps, resharded re-inits of the same architecture — skip
    compilation entirely.
    """
    import jax

    ensure_compilation_cache()

    named = _named_fakes(module)
    if not named:
        return {}

    # Eager guard validation (torch-side, can't run under trace).
    for _, fake in named:
        _check_guards_of(_get_record(fake).node)

    fakes = dict(named)
    stacks: Dict[str, List[OpNode]] = {
        name: _tape.build_call_stack(_get_record(fake).node)
        for name, fake in named
    }

    jax_names: List[str] = []
    unsupported: List[Tuple[str, FakeTensor]] = []
    # Probe lowerability cheaply: every non-view node in each call stack
    # must have a lowering.
    for name, fake in named:
        ok = True
        for n in stacks[name]:
            if _is_view_node(n):
                continue
            if _packet_name(n.op.func) not in LOWERINGS:
                ok = False
                break
        (jax_names.append(name) if ok else unsupported.append((name, fake)))

    target_dtypes = {
        name: jnp_dtype_of(dtype or fakes[name].dtype) for name, _ in named
    }

    results: Dict[str, Any] = {}
    if strategy in ("auto", "grouped"):
        group_list, fused_names = _plan_groups(
            jax_names, fakes, stacks, target_dtypes
        )
    elif strategy == "fused":
        group_list, fused_names = [], list(jax_names)
    else:
        raise ValueError(f"unknown strategy: {strategy!r}")

    # Tape ordinals: distinct tapes reachable from the targets, numbered in
    # first-appearance order over the named params' stacks (deterministic
    # across processes — iteration follows module naming order).
    tape_ordinals: Dict[int, int] = {}
    for name, _ in named:
        for n in stacks[name]:
            tape_ordinals.setdefault(n.base_nr, len(tape_ordinals))

    if jax_names:
        import numpy as np

        templates = [
            _make_template(*g["rep"], target_dtypes[g["names"][0]])
            for g in group_list
        ]
        # Per-group traced inputs: per-instance per-node RNG identities —
        # (tape ordinal, tape-relative op_nr) rows of shape (n_inst,
        # n_nodes) — and external tensor slots stacked along the instance
        # axis.  Instance data enters as *arguments*, so the traced program
        # is byte-identical for any same-architecture materialization
        # (exec-cache and persistent-cache hits).
        ords_in = [
            np.asarray(
                [
                    [tape_ordinals[n.base_nr] for n in stacks[name]]
                    for name in g["names"]
                ],
                dtype=np.uint32,
            )
            for g in group_list
        ]
        rels_in = [
            np.asarray(
                [
                    [n.op_nr - n.base_nr for n in stacks[name]]
                    for name in g["names"]
                ],
                dtype=np.uint32,
            )
            for g in group_list
        ]
        exts_in = [
            [
                np.stack(
                    [
                        g["exts"][i][j].detach().cpu().numpy()
                        for i in range(len(g["names"]))
                    ]
                )
                for j in range(len(g["exts"][0]))
            ]
            for g in group_list
        ]

        def compute(base_key, ords_in, rels_in, exts_in):
            fold = jax.vmap(
                jax.vmap(
                    lambda o, r: jax.random.fold_in(
                        jax.random.fold_in(base_key, o), r
                    )
                )
            )
            out = {}
            # Signature groups: one vmapped template each — the compiled
            # program contains one subgraph per unique layer *kind*, not per
            # layer (compile time O(unique kinds), not O(depth)).
            for g, template, ords, rels, exts in zip(
                group_list, templates, ords_in, rels_in, exts_in
            ):
                res = jax.vmap(template)(fold(ords, rels), exts)
                for i, name in enumerate(g["names"]):
                    out[name] = res[i]
            # Fused leftovers: union of the remaining targets' call stacks,
            # replayed once in global chronological order — a per-target
            # replay could advance a shared storage past an earlier target's
            # read point (write-after-read through an alias), making results
            # depend on traversal order.
            if fused_names:
                eng = _FunctionalReplay(
                    base_key,
                    check_guards=False,
                    key_lookup=lambda node: jax.random.fold_in(
                        jax.random.fold_in(
                            base_key,
                            tape_ordinals.setdefault(
                                node.base_nr, len(tape_ordinals)
                            ),
                        ),
                        node.op_nr - node.base_nr,
                    ),
                )
                nodes: Dict[int, OpNode] = {}
                for name in fused_names:
                    for n in stacks[name]:
                        nodes[n.op_nr] = n
                for nr in sorted(nodes):
                    eng.run_node(nodes[nr])
                for name in fused_names:
                    rec = _get_record(fakes[name])
                    out[name] = eng.value_of_output(
                        rec.node, rec.index
                    ).astype(target_dtypes[name])
            return out

        if mesh is not None:
            from jax.sharding import NamedSharding

            shardings = {
                name: NamedSharding(
                    mesh, _resolve_spec(plan, name, fakes[name], mesh)
                )
                for name in jax_names
            }
        else:
            shardings = None

        # Executable-cache key: full program identity.  Only when every
        # target is grouped — the fused path bakes instance data into the
        # trace, so its programs are not reusable.
        # Program identity excludes the seed: the base key enters the
        # program as a traced input, so one executable serves a whole
        # seed sweep.
        exec_key = None
        if group_list and not fused_names and not unsupported:
            try:
                exec_key = (
                    tuple(
                        (g["key"], tuple(g["names"])) for g in group_list
                    ),
                    rng_impl,
                    None
                    if mesh is None
                    # str(NamedSharding) omits device identities — two
                    # same-shape meshes over different devices must not
                    # share executables, so key the device ids explicitly.
                    else (
                        tuple(d.id for d in mesh.devices.flat),
                        tuple(
                            (name, str(s))
                            for name, s in sorted(shardings.items())
                        ),
                    ),
                )
                hash(exec_key)
            except TypeError:
                exec_key = None

        base_key = _base_key(seed, rng_impl)
        jfn = _exec_cache_get(exec_key) if exec_key is not None else None
        if jfn is None:
            from .utils.compilation_cache import cache_everything

            if shardings is not None:
                jfn = jax.jit(compute, out_shardings=shardings)
            else:
                jfn = jax.jit(compute)
            with cache_everything():
                if exec_key is not None:
                    # Cache the AOT-compiled executable, not the jit
                    # wrapper: the wrapper would pin `compute`'s closure —
                    # the whole tape (OpNodes, deep-copied args, fakes) —
                    # for the cache entry's lifetime.  The compiled object
                    # holds only the executable; input shapes/dtypes are
                    # fixed by the group signatures in the key (and the key
                    # aval by rng_impl), so the AOT call always matches.
                    jfn = jfn.lower(
                        base_key, ords_in, rels_in, exts_in
                    ).compile()
                    _exec_cache_put(exec_key, jfn)
                results.update(jfn(base_key, ords_in, rels_in, exts_in))
        else:
            results.update(jfn(base_key, ords_in, rels_in, exts_in))

    # Torch fallback for ops with no lowering: replay on host, transfer with
    # the planned sharding.  Per-tensor, so peak host RAM ≈ largest param.
    if unsupported:
        if not _fallback_torch:
            raise UnsupportedOpError(
                f"No JAX lowering for params: {[n for n, _ in unsupported]}"
            )
        from .deferred_init import materialize_tensor

        for name, fake in unsupported:
            real = materialize_tensor(fake, device="cpu")
            arr = jax.numpy.asarray(
                real.detach().cpu().numpy(), dtype=target_dtypes[name]
            )
            if mesh is not None:
                from jax.sharding import NamedSharding

                arr = jax.device_put(
                    arr,
                    NamedSharding(mesh, _resolve_spec(plan, name, fake, mesh)),
                )
            results[name] = arr
    return results
