"""Deferred module initialization: record construction, inspect, materialize.

Rebuild of the reference's deferred-init feature
(/root/reference/src/cc/torchdistx/deferred_init.cc, src/python/torchdistx/
deferred_init.py).  ``deferred_init(module_fn, *args, **kwargs)`` constructs a
module whose parameters/buffers are fake while recording every operation into
the op tape (:mod:`torchdistx_tpu._tape`); ``materialize_tensor`` /
``materialize_module`` replay the tape to instantiate real tensors.  The
load-bearing use case is shard-then-materialize: inspect the full architecture
with zero allocation, decide a sharding plan, then materialize each shard
directly on its device — on TPU via :mod:`torchdistx_tpu.materialize`, which
replays the tape as sharded ``jax.Array`` leaves on a mesh.

Interception design: the reference registers a pre-autograd ``DeferredInit``
dispatch-key fallback (deferred_init.cc:879-882) that deep-copies each call
frame, redispatches with the ``Fake`` key added, and records the op iff a fake
tensor flows in or out (deferred_init.cc:767-797); ``nn.Parameter``'s
non-dispatcher ``Tensor.data`` accesses are caught by swapping autograd's
global ``VariableHooksInterface`` for a recording proxy
(deferred_init.cc:888-1127).  Here a ``TorchDispatchMode`` plays the dispatch
fallback, and no hooks proxy is needed at all: with wrapper-subclass fakes,
``nn.Parameter(fake)`` routes through ``aten::detach`` which IS dispatched —
the hooks machinery collapses into the ordinary record path.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Optional

import torch
import torch.nn as nn
import torch.utils._pytree as pytree
from torch.utils._python_dispatch import TorchDispatchMode

from . import _tape
from ._tape import OpNode, Tape, TensorRecord  # noqa: F401 (public graph types)
from .fake import (
    FakeTensor,
    _fake_handler,
    _flat_leaves,
    _ensure_tpu_device_registered,
    _suppress_cuda_lazy_init,
)

__all__ = [
    "deferred_init",
    "materialize_tensor",
    "materialize_module",
    "is_deferred",
]

_SLOT = "deferred_init"
_tls = threading.local()

# Terminal ops force materialization of their args and then run for real —
# the analog of the reference's terminal-op set (deferred_init.cc:812-814,
# `aten::item`).  `_local_scalar_dense` is what `.item()` lowers to at this
# seam; `aten::equal` also requires real data.
_TERMINAL_OPS = {
    "aten::item",
    "aten::_local_scalar_dense",
    "aten::equal",
    "aten::allclose",
}


def _get_record(fake: FakeTensor) -> Optional[TensorRecord]:
    return fake._slots.get(_SLOT)


def is_deferred(tensor: torch.Tensor) -> bool:
    """True if ``tensor`` is fake and carries a deferred-init record."""
    return isinstance(tensor, FakeTensor) and _get_record(tensor) is not None


class _DeferredInitMode(TorchDispatchMode):
    """Record/redispatch mode — analog of ``DeferredInitHandler::run``
    (deferred_init.cc:767-797)."""

    def __init__(self, tape: Tape, default_device: Optional[torch.device]):
        super().__init__()
        self.tape = tape
        self.default_device = default_device

    def __torch_dispatch__(self, func, types, args=(), kwargs=None):
        kwargs = kwargs or {}
        if func.name() in _TERMINAL_OPS:
            # Force-materialize fake args, then run for real
            # (deferred_init.cc:774-779).
            def mat(a):
                if isinstance(a, FakeTensor):
                    return materialize_tensor(a)
                return a

            r_args, r_kwargs = pytree.tree_map(mat, (tuple(args), dict(kwargs)))
            return func(*r_args, **r_kwargs)

        # Redispatch through the fake handler so outputs come out fake
        # (the `redispatchToFake` step, deferred_init.cc:830-835).
        out = _fake_handler(
            func, args, kwargs, default_device=self.default_device
        )

        flat_in = _flat_leaves((args, kwargs))
        flat_out = _flat_leaves(out)
        fake_outputs = [o for o in flat_out if isinstance(o, FakeTensor)]
        has_fake_arg = any(isinstance(a, FakeTensor) for a in flat_in)
        if has_fake_arg or fake_outputs:
            # Record iff a fake flows in or out (deferred_init.cc:780-796).
            _tape.record_op(self.tape, func, args, kwargs, fake_outputs)
        return out


@contextlib.contextmanager
def _deferred_init_context(device: Optional[Any] = None):
    """Enter/leave the deferred-init recording context — analog of
    enterDeferredInit/leaveDeferredInit (deferred_init.cc:1138-1160)."""
    if device is not None:
        device = torch.device(device)
        if device.type == "tpu":
            _ensure_tpu_device_registered()
    tape = _tape.push_tape()
    mode = _DeferredInitMode(tape, default_device=device)
    level = getattr(_tls, "level", 0)
    _tls.level = level + 1
    try:
        with contextlib.ExitStack() as stack:
            # Same CUDA lazy-init suppression as fake_mode: factory bindings
            # would otherwise fail for claimed "cuda" devices on CUDA-less
            # hosts before dispatch reaches the mode (_C/fake.cc:18-36).
            stack.enter_context(_suppress_cuda_lazy_init())
            if device is not None:
                # Same DeviceContext routing as fake_mode: factories arrive
                # already carrying the claimed default device.
                stack.enter_context(torch.device(device))
            stack.enter_context(mode)
            yield tape
    finally:
        _tls.level = level
        _tape.pop_tape()


def deferred_init(module_fn: Callable[..., Any], *args, **kwargs):
    """Construct ``module_fn(*args, **kwargs)`` with fake, recorded tensors.

    Analog of the reference's ``deferred_init`` (deferred_init.py:19-44).
    The optional keyword-only ``device_`` sets the claimed device for the
    module's factory calls (e.g. ``device_="tpu"`` to fake a model "on TPU");
    by default factories claim the device they ask for, else CPU.
    """
    device = kwargs.pop("device_", None)
    with _deferred_init_context(device=device):
        return module_fn(*args, **kwargs)


def _wrap_materialized(fake: FakeTensor, node: OpNode, index: int) -> torch.Tensor:
    """Apply the identity/class-preservation contract.

    Analog of materializeVariable (_C/deferred_init.cc:60-94): materializing
    the same (node, output) twice returns the *same* Python object, and a
    fake ``nn.Parameter`` materializes as an ``nn.Parameter``.
    """
    cached = node.materialized_pyobjs.get(index)
    if cached is not None:
        return cached
    real = node.op.outputs[index]
    # Re-apply requires_grad post-replay: `requires_grad_()` is not
    # dispatcher-visible, so like the reference we restore it from the fake
    # (deferred_init.cc:721-725).
    if isinstance(real, torch.Tensor):
        if real.is_leaf and real.requires_grad != fake.requires_grad:
            real.requires_grad_(fake.requires_grad)
        if isinstance(fake, nn.Parameter) or getattr(fake, "_is_param", False):
            if not isinstance(real, nn.Parameter):
                real = nn.Parameter(real, requires_grad=fake.requires_grad)
    node.materialized_pyobjs[index] = real
    return real


@contextlib.contextmanager
def _replay_device_override(device: Optional[Any]):
    if device is None:
        yield
        return
    target = torch.device(device)
    prev = getattr(_tape._tls, "device_override", None)
    _tape._tls.device_override = target
    try:
        yield
    finally:
        _tape._tls.device_override = prev


def materialize_tensor(
    tensor: torch.Tensor, *, device: Optional[Any] = None
) -> torch.Tensor:
    """Materialize a fake tensor by replaying its recorded subgraph.

    Analog of the reference's ``materialize_tensor`` (deferred_init.py:47-59,
    deferred_init.cc:1162-1168,712-728).  No-op for real tensors and for
    fakes with no record.  ``device`` optionally redirects replayed factory
    ops to a different real device (needed when the fake claims a device,
    like ``tpu:0``, that torch cannot allocate on; the JAX path in
    :mod:`torchdistx_tpu.materialize` is the native route for those).
    """
    if not isinstance(tensor, FakeTensor):
        return tensor
    record = _get_record(tensor)
    if record is None:
        return tensor
    call_stack = _tape.build_call_stack(record.node)
    # Replay with recording/fake modes disabled: materialization may run
    # inside the deferred-init context (terminal ops do, deferred_init.cc:768
    # runs under the NoDeferredInit guard) and must execute for real.
    with _replay_device_override(device), torch.utils._python_dispatch._disable_current_modes():
        for node in call_stack:
            _tape.replay_node(node)
    return _wrap_materialized(tensor, record.node, record.index)


def _collect_materialization_targets(
    module: nn.Module,
    buffers_only: bool,
    check_fn: Optional[Callable[[nn.Module], bool]],
    out: list,
) -> None:
    # Depth-first over children like the reference (deferred_init.py:91-92).
    for child in module.children():
        _collect_materialization_targets(child, buffers_only, check_fn, out)
    if check_fn is not None and not check_fn(module):
        return
    if not buffers_only:
        for key, param in module._parameters.items():
            if param is not None and is_deferred(param):
                out.append((module._parameters, key, param))
    for key, buf in module._buffers.items():
        if buf is not None and is_deferred(buf):
            out.append((module._buffers, key, buf))


def materialize_module(
    module: nn.Module,
    *,
    buffers_only: bool = False,
    check_fn: Optional[Callable[[nn.Module], bool]] = None,
    device: Optional[Any] = None,
) -> nn.Module:
    """Materialize all fake parameters/buffers of ``module`` in place.

    Analog of the reference's ``materialize_module`` (deferred_init.py:62-99):
    depth-first over ``module.children()``, rewriting ``module._parameters``
    and ``module._buffers`` in place; ``buffers_only`` skips parameters;
    ``check_fn`` gates whole submodules (the FSDP shard-then-materialize
    hook).  Returns ``module``.

    All targets' call stacks are merged and replayed once in global
    chronological order, so results never depend on module traversal order
    (an in-place op on a storage shared between two targets must not replay
    before an earlier-recorded read by the other target).
    """
    targets: list = []
    _collect_materialization_targets(module, buffers_only, check_fn, targets)
    nodes = {}
    for _, _, fake in targets:
        record = _get_record(fake)
        for node in _tape.build_call_stack(record.node):
            nodes[node.op_nr] = node
    with _replay_device_override(device), \
            torch.utils._python_dispatch._disable_current_modes():
        for nr in sorted(nodes):
            _tape.replay_node(nodes[nr])
    for container, key, fake in targets:
        record = _get_record(fake)
        container[key] = _wrap_materialized(fake, record.node, record.index)
    return module
