"""Autoregressive generation: jitted prefill + ``lax.scan`` decode loop.

Model-agnostic over the family protocol (``init_cache`` / ``forward_cached``
— llama and gpt2 both implement it).  The whole generation — prefill and all
decode steps — is one compiled program with static shapes: the KV cache is
allocated at ``prompt_len + max_new_tokens`` up front, positions are traced
scalars, and the token loop is a ``lax.scan`` (no host round-trips between
steps, the TPU decode idiom).

Sampling: greedy (``temperature=0``), temperature, and top-k; per-step keys
derive from ``fold_in(key, step)``.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

__all__ = ["generate"]


def _sample(logits, key, temperature: float, top_k: Optional[int]):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


@functools.partial(
    jax.jit,
    static_argnames=(
        "model", "cfg", "max_new_tokens", "temperature", "top_k", "eos_id",
    ),
)
def generate(
    params,
    prompt: Any,
    key,
    *,
    model,
    cfg,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    eos_id: Optional[int] = None,
):
    """Generate ``max_new_tokens`` continuations of ``prompt (B, S)``.

    Returns ``(B, max_new_tokens)`` int32 tokens.  After ``eos_id`` (if
    given) a sequence keeps emitting ``eos_id``; once EVERY sequence is
    done the remaining decode steps skip the model forward entirely
    (``lax.cond`` early exit) and just emit the eos fill.
    """
    b, s = prompt.shape
    total = s + max_new_tokens
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) = {total} "
            f"exceeds cfg.max_seq_len ({cfg.max_seq_len})"
        )
    cache = model.init_cache(cfg, b, total)

    # Hoist decode prep (fused projection weights) OUT of the token scan:
    # one concat per generation, read by every step.
    prep = getattr(model, "prep_decode", None)
    if prep is not None:
        params = prep(params, cfg)

    logits, cache = model.forward_cached(params, prompt, cfg, cache, 0)
    first = _sample(
        logits[:, -1], jax.random.fold_in(key, 0), temperature, top_k
    ).astype(jnp.int32)
    done0 = (
        first == eos_id if eos_id is not None else jnp.zeros((b,), bool)
    )

    def live_step(tok, cache, done, i):
        logits, cache = model.forward_cached(
            params, tok[:, None], cfg, cache, s + i
        )
        nxt = _sample(
            logits[:, -1], jax.random.fold_in(key, i + 1), temperature, top_k
        ).astype(jnp.int32)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        return nxt, cache, done

    def step(carry, i):
        tok, cache, done = carry
        if eos_id is None:
            tok, cache, done = live_step(tok, cache, done, i)
            return (tok, cache, done), tok
        # All-done early exit: once every sequence has hit eos, the
        # remaining scan iterations emit eos WITHOUT paying the model
        # forward (lax.cond executes one branch on TPU; the drained
        # branch is a fill).  Token semantics are unchanged — the old
        # code's where(done, eos, _) forced eos for exactly these steps.
        tok, cache, done = jax.lax.cond(
            done.all(),
            lambda tok, cache, done, i: (
                jnp.full_like(tok, eos_id), cache, done
            ),
            live_step,
            tok, cache, done, i,
        )
        return (tok, cache, done), tok

    (_, _, _), rest = jax.lax.scan(
        step, (first, cache, done0), jnp.arange(max_new_tokens - 1)
    )
    return jnp.concatenate([first[:, None], rest.T.astype(jnp.int32)], axis=1)
