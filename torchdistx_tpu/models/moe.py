"""Mixture-of-Experts Llama variant — expert parallelism over an ``ep`` axis.

Llama blocks with the dense FFN replaced by a top-k-routed expert FFN
(Switch/Mixtral style): a router scores E experts per token, the top-k are
selected with renormalized gates, tokens are dispatched into fixed-capacity
per-expert buffers (static shapes — the TPU requirement), expert FFNs run
batched over the expert dim, and outputs are combined gate-weighted.
Tokens over capacity are dropped (standard capacity-factor semantics).

Sharding: expert weights ``(L, E, D, F)`` carry ``P(None, "ep", fsdp, tp)``
and the dispatch buffers ``(E, C, D)`` shard over ``ep`` — XLA's SPMD
partitioner turns the dispatch/combine einsums into all-to-alls over the
``ep`` axis, which is exactly expert parallelism.  A load-balancing aux loss
(Switch Transformer eq. 4) keeps routing uniform.

The reference framework has no MoE (SURVEY.md §2.3: EP "not required") —
native new capability.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import attention
from . import llama as llama_mod

__all__ = [
    "MoEConfig",
    "moe_test",
    "init_params",
    "abstract_params",
    "param_specs",
    "forward",
    "loss_fn",
    "num_params",
    "pp_pieces",
    "pp_value_and_grad",
]


@dataclasses.dataclass(frozen=True)
class MoEConfig(llama_mod.LlamaConfig):
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01


def moe_test() -> MoEConfig:
    return MoEConfig(
        vocab_size=256,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_dim=128,
        max_seq_len=128,
        dtype=jnp.float32,
        remat=False,
        n_experts=4,
        experts_per_token=2,
    )


def _shapes(cfg: MoEConfig) -> dict:
    base = llama_mod._shapes(cfg)
    L, D, F, E = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.n_experts
    base["layers"].pop("w_gate")
    base["layers"].pop("w_up")
    base["layers"].pop("w_down")
    base["layers"]["router"] = (L, D, E)
    base["layers"]["e_gate"] = (L, E, D, F)
    base["layers"]["e_up"] = (L, E, D, F)
    base["layers"]["e_down"] = (L, E, F, D)
    return base


def abstract_params(cfg: MoEConfig):
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s, cfg.dtype),
        _shapes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


def param_specs(
    cfg: MoEConfig,
    *,
    tp: Optional[str] = "tp",
    fsdp: Optional[str] = "fsdp",
    pp: Optional[str] = None,
    ep: Optional[str] = "ep",
):
    base = llama_mod.param_specs(cfg, tp=tp, fsdp=fsdp, pp=pp)
    for k in ("w_gate", "w_up", "w_down"):
        base["layers"].pop(k)
    base["layers"]["router"] = P(pp)
    base["layers"]["e_gate"] = P(pp, ep, fsdp, tp)
    base["layers"]["e_up"] = P(pp, ep, fsdp, tp)
    base["layers"]["e_down"] = P(pp, ep, tp, fsdp)
    return base


def init_params(key, cfg: MoEConfig):
    import zlib

    shapes = _shapes(cfg)

    def leaf(path, shape):
        name = path[-1]
        if name in ("attn_norm", "mlp_norm") or path[0] == "norm":
            return jnp.ones(shape, dtype=cfg.dtype)
        std = 0.02
        if name in ("wo", "e_down"):
            std = 0.02 / (2.0 * cfg.n_layers) ** 0.5
        leaf_key = jax.random.fold_in(key, zlib.crc32("/".join(path).encode()))
        return (
            jax.random.normal(leaf_key, shape, dtype=jnp.float32) * std
        ).astype(cfg.dtype)

    def walk(tree, path=()):
        if isinstance(tree, tuple):
            return leaf(path, tree)
        return {k: walk(v, path + (k,)) for k, v in tree.items()}

    return walk(shapes)


def num_params(cfg: MoEConfig) -> int:
    total = 0
    for leaf in jax.tree.leaves(
        _shapes(cfg), is_leaf=lambda x: isinstance(x, tuple)
    ):
        n = 1
        for s in leaf:
            n *= s
        total += n
    return total


def _capacity(cfg: MoEConfig, n_tokens: int) -> int:
    import math

    cap = math.ceil(
        cfg.capacity_factor * n_tokens * cfg.experts_per_token / cfg.n_experts
    )
    return max(int(cap), 1)


def moe_ffn(h, router_w, e_gate, e_up, e_down, cfg: MoEConfig):
    """Top-k routed expert FFN.  h ``(B, S, D)`` → (out ``(B, S, D)``,
    aux_loss scalar)."""
    b, s, d = h.shape
    t = b * s
    e, k = cfg.n_experts, cfg.experts_per_token
    cap = _capacity(cfg, t)
    ht = h.reshape(t, d)

    router_logits = (ht @ router_w).astype(jnp.float32)  # (T, E)
    probs = jax.nn.softmax(router_logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # (T, K)
    gate_vals = gate_vals / gate_vals.sum(axis=-1, keepdims=True)

    # Position of each (token, choice) inside its expert's buffer: running
    # count of prior selections of the same expert, token-major order.
    onehot = jax.nn.one_hot(gate_idx, e, dtype=jnp.int32)  # (T, K, E)
    flat = onehot.reshape(t * k, e)
    pos = (jnp.cumsum(flat, axis=0) - 1)  # (T*K, E)
    pos = (pos * flat).sum(-1)  # (T*K,)
    expert_flat = gate_idx.reshape(t * k)
    keep = pos < cap
    pos_c = jnp.clip(pos, 0, cap - 1)

    # Dispatch: (E, C, D) buffers.
    tok_idx = jnp.repeat(jnp.arange(t), k)
    contrib = ht[tok_idx] * keep[:, None].astype(ht.dtype)
    dispatch = jnp.zeros((e, cap, d), dtype=ht.dtype).at[
        expert_flat, pos_c
    ].add(contrib)

    # Batched expert FFN on the MXU: (E, C, D) @ (E, D, F).
    gated = jax.nn.silu(jnp.einsum("ecd,edf->ecf", dispatch, e_gate))
    up = jnp.einsum("ecd,edf->ecf", dispatch, e_up)
    expert_out = jnp.einsum("ecf,efd->ecd", gated * up, e_down)

    # Combine: gather each choice's output, gate-weight, sum over k.
    out_choice = expert_out[expert_flat, pos_c]  # (T*K, D)
    weights = (gate_vals.reshape(t * k) * keep).astype(ht.dtype)
    out = (out_choice * weights[:, None]).reshape(t, k, d).sum(axis=1)

    # Load-balancing aux loss (GShard/Mixtral form): E · Σ_e f_e · p̄_e with
    # f_e counting ALL k routed choices — load arriving via second choices
    # must be visible to the balancing pressure, since dispatch routes it.
    frac = jnp.mean(
        jax.nn.one_hot(gate_idx, e, dtype=jnp.float32).sum(axis=1), axis=0
    ) / k
    mean_prob = probs.mean(axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    return out.reshape(b, s, d), aux


def _build_block_core(
    cfg: MoEConfig, *, mesh=None, seq_axis=None, attn_impl="auto"
):
    """One MoE block as ``block(x, aux_sum, lp) -> (x, aux_sum)`` over
    unstacked layer params — shared by :func:`forward` (scan and GPipe)
    and the 1F1B pipeline pieces."""

    def block_core(x, aux_sum, lp):
        bb, s = x.shape[0], x.shape[1]
        positions = jnp.arange(s)[None]
        h = llama_mod._rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"]).reshape(bb, s, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"]).reshape(bb, s, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"]).reshape(bb, s, cfg.n_kv_heads, cfg.head_dim)
        q = llama_mod._rope(q, positions, cfg.rope_theta)
        k = llama_mod._rope(k, positions, cfg.rope_theta)
        attn = attention(
            q, k, v, causal=True, impl=attn_impl, mesh=mesh, seq_axis=seq_axis
        )
        x = x + attn.reshape(bb, s, -1) @ lp["wo"]
        h = llama_mod._rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        ffn, aux = moe_ffn(
            h, lp["router"], lp["e_gate"], lp["e_up"], lp["e_down"], cfg
        )
        return x + ffn, aux_sum + aux

    return block_core


def _pp_block(block_core):
    """Pipelined activation pytree adapter: the aux channel is one value
    per batch row (every row of a microbatch carries that microbatch's
    running aux sum)."""

    def pp_block(act, lp):
        x_new, aux_new = block_core(act["h"], act["aux"][:, 0], lp)
        return {
            "h": x_new,
            "aux": jnp.broadcast_to(aux_new[..., None], act["aux"].shape),
        }

    return pp_block


def forward(
    params,
    tokens,
    cfg: MoEConfig,
    *,
    mesh=None,
    seq_axis: Optional[str] = None,
    attn_impl: str = "auto",
    pp_axis: Optional[str] = None,
    n_microbatches: int = 1,
    return_aux: bool = False,
):
    """Token ids → logits; MoE FFN per block.

    ``pp_axis`` runs the blocks through the GPipe pipeline with the router
    aux loss travelling as a per-row side channel in the pipelined
    activation pytree.  Under pp, routing/capacity are computed per
    *microbatch* (each stage sees ``B/M`` tokens) — same semantics as
    training on microbatches, documented divergence from the dense path
    (equal logits when capacity is ample; aux becomes the mean of
    per-microbatch aux losses).
    """
    b, s = tokens.shape
    if pp_axis is not None:
        from ..ops.attention import resolve_stage_attn_impl

        attn_impl = resolve_stage_attn_impl(attn_impl)
    x = jnp.take(params["embed"]["weight"], tokens, axis=0).astype(cfg.dtype)

    block_core = _build_block_core(
        cfg, mesh=mesh, seq_axis=seq_axis, attn_impl=attn_impl
    )

    if pp_axis is not None:
        from ..parallel.pipeline import pipeline_forward

        pp_block = _pp_block(block_core)
        body = jax.checkpoint(pp_block) if cfg.remat else pp_block
        out = pipeline_forward(
            {"h": x, "aux": jnp.zeros((b, 1), jnp.float32)},
            params["layers"],
            body,
            mesh=mesh,
            axis=pp_axis,
            n_microbatches=n_microbatches,
        )
        x = out["h"]
        # Each row holds its microbatch's Σ_layers aux; the mean over rows
        # is the microbatch-mean aux sum.
        aux_sum = out["aux"].mean()
    else:
        def block(carry, lp):
            x, aux_sum = carry
            return block_core(x, aux_sum, lp), None

        body = jax.checkpoint(block) if cfg.remat else block
        (x, aux_sum), _ = jax.lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), params["layers"]
        )
    logits = llama_mod._head_logits(params, x, cfg)
    if return_aux:
        return logits, aux_sum / cfg.n_layers
    return logits


def loss_fn(
    params,
    tokens,
    targets,
    cfg: MoEConfig,
    *,
    mesh=None,
    seq_axis: Optional[str] = None,
    attn_impl: str = "auto",
    pp_axis: Optional[str] = None,
    n_microbatches: int = 1,
):
    """Cross-entropy + router load-balancing aux loss."""
    logits, aux = forward(
        params, tokens, cfg, mesh=mesh, seq_axis=seq_axis,
        attn_impl=attn_impl, pp_axis=pp_axis,
        n_microbatches=n_microbatches, return_aux=True,
    )
    return llama_mod._ce(logits, targets) + cfg.router_aux_coef * aux


# ---------------------------------------------------------------------------
# 1F1B pipeline pieces: the router aux-loss accumulator rides the pipeline
# as a side channel of the activation pytree (same per-microbatch routing
# semantics as the GPipe path); the last stage folds it into the loss.


def pp_pieces(cfg: MoEConfig, *, mesh=None, attn_impl: str = "auto"):
    """``(embed_fn, block_fn, head_loss_fn)`` for the 1F1B schedule."""
    from ..ops.attention import resolve_stage_attn_impl

    impl = resolve_stage_attn_impl(attn_impl)
    pp_block = _pp_block(_build_block_core(cfg, mesh=mesh, attn_impl=impl))
    body = jax.checkpoint(pp_block) if cfg.remat else pp_block

    def embed_fn(ep, tokens_mb):
        bt = tokens_mb.shape[0]
        x = jnp.take(
            ep["embed"]["weight"], tokens_mb, axis=0
        ).astype(cfg.dtype)
        return {"h": x, "aux": jnp.zeros((bt, 1), jnp.float32)}

    def head_loss_fn(hp, act, targets_mb):
        # Shares llama's head/CE helpers (hp is {"norm","lm_head"}-shaped)
        # so the 1F1B loss cannot drift from the GPipe/unpipelined one.
        ce = llama_mod._ce(
            llama_mod._head_logits(hp, act["h"], cfg), targets_mb
        )
        # Each row holds this microbatch's Σ_layers aux; the row mean is
        # that sum, normalized per layer as in loss_fn.
        aux = act["aux"].mean() / cfg.n_layers
        return ce + cfg.router_aux_coef * aux

    return embed_fn, body, head_loss_fn


def pp_value_and_grad(
    params,
    tokens,
    targets,
    cfg: MoEConfig,
    *,
    mesh,
    pp_axis: str = "pp",
    n_microbatches: int = 1,
    attn_impl: str = "auto",
):
    """``(loss, grads)`` via the 1F1B pipeline (see
    parallel.pipeline.pipeline_value_and_grad).  Routing/capacity are
    per-microbatch, as in the GPipe path."""
    from ..parallel.pipeline import pipeline_value_and_grad

    embed_fn, block_fn, head_loss_fn = pp_pieces(
        cfg, mesh=mesh, attn_impl=attn_impl
    )
    loss, (g_ep, g_lp, g_hp) = pipeline_value_and_grad(
        {"embed": params["embed"]},
        params["layers"],
        {"norm": params["norm"], "lm_head": params["lm_head"]},
        tokens,
        targets,
        embed_fn,
        block_fn,
        head_loss_fn,
        mesh=mesh,
        axis=pp_axis,
        n_microbatches=n_microbatches,
    )
    return loss, {
        "embed": g_ep["embed"],
        "layers": g_lp,
        "norm": g_hp["norm"],
        "lm_head": g_hp["lm_head"],
    }
