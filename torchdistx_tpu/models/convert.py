"""HF-checkpoint → native parameter bridge.

Closes the loop between the deferred-init world and the native training
stack: construct a HF model under ``deferred_init`` (zero allocation),
materialize its parameters as sharded ``jax.Array``s
(:func:`torchdistx_tpu.materialize.materialize_module_jax`), then convert
the flat ``{qualified_name: array}`` dict into the stacked-layer pytrees the
native model families (:mod:`~torchdistx_tpu.models.llama`,
:mod:`~torchdistx_tpu.models.gpt2`) train and decode with.

Layout notes:

* HF GPT-2 uses Conv1D — weights already ``(in, out)``, no transpose.
* HF Llama uses ``nn.Linear`` — weights ``(out, in)``, transposed here.
* RoPE half-split convention matches between HF Llama and
  :func:`llama._rope` (verified by the logit-equivalence tests).
* Layer stacking: per-layer leaves are stacked on a new leading axis in
  layer order, matching the ``lax.scan`` layout.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp

from . import gpt2 as gpt2_mod
from . import llama as llama_mod

__all__ = [
    "gpt2_config_from_hf",
    "llama_config_from_hf",
    "gpt2_params_from_hf",
    "llama_params_from_hf",
]


def gpt2_config_from_hf(hf_config, **overrides) -> gpt2_mod.GPT2Config:
    return gpt2_mod.GPT2Config(
        vocab_size=hf_config.vocab_size,
        dim=hf_config.n_embd,
        n_layers=hf_config.n_layer,
        n_heads=hf_config.n_head,
        max_seq_len=hf_config.n_positions,
        norm_eps=hf_config.layer_norm_epsilon,
        **overrides,
    )


def llama_config_from_hf(hf_config, **overrides) -> llama_mod.LlamaConfig:
    return llama_mod.LlamaConfig(
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(
            hf_config, "num_key_value_heads", hf_config.num_attention_heads
        ),
        ffn_dim=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        norm_eps=hf_config.rms_norm_eps,
        **overrides,
    )


def _get(arrays: Dict[str, Any], name: str, *, prefixes=("", "transformer.",
                                                         "model.")):
    for p in prefixes:
        if p + name in arrays:
            return jnp.asarray(arrays[p + name])
    raise KeyError(
        f"parameter '{name}' not found (tried prefixes {list(prefixes)}); "
        f"have e.g. {sorted(arrays)[:5]}"
    )


def _stack(arrays, fmt: str, n_layers: int, *, transpose=False):
    leaves = []
    for i in range(n_layers):
        a = _get(arrays, fmt.format(i=i))
        leaves.append(a.T if transpose else a)
    return jnp.stack(leaves)


def gpt2_params_from_hf(
    arrays: Dict[str, Any], cfg: Optional[gpt2_mod.GPT2Config] = None
):
    """Flat HF GPT-2 param dict → native stacked pytree.

    ``arrays``: ``{name: array-like}`` — the output of
    ``materialize_module_jax(GPT2LMHeadModel-instance)``, a torch
    ``state_dict()`` (tensors converted via ``numpy()``), or any mix.
    """
    L = cfg.n_layers if cfg is not None else _count_layers(arrays, "h.{i}.ln_1.weight")
    return {
        "wte": {"weight": _get(arrays, "wte.weight")},
        "wpe": {"weight": _get(arrays, "wpe.weight")},
        "layers": {
            "ln_1": {
                "scale": _stack(arrays, "h.{i}.ln_1.weight", L),
                "bias": _stack(arrays, "h.{i}.ln_1.bias", L),
            },
            "attn_qkv": {
                "weight": _stack(arrays, "h.{i}.attn.c_attn.weight", L),
                "bias": _stack(arrays, "h.{i}.attn.c_attn.bias", L),
            },
            "attn_proj": {
                "weight": _stack(arrays, "h.{i}.attn.c_proj.weight", L),
                "bias": _stack(arrays, "h.{i}.attn.c_proj.bias", L),
            },
            "ln_2": {
                "scale": _stack(arrays, "h.{i}.ln_2.weight", L),
                "bias": _stack(arrays, "h.{i}.ln_2.bias", L),
            },
            "mlp_fc": {
                "weight": _stack(arrays, "h.{i}.mlp.c_fc.weight", L),
                "bias": _stack(arrays, "h.{i}.mlp.c_fc.bias", L),
            },
            "mlp_proj": {
                "weight": _stack(arrays, "h.{i}.mlp.c_proj.weight", L),
                "bias": _stack(arrays, "h.{i}.mlp.c_proj.bias", L),
            },
        },
        "ln_f": {
            "scale": _get(arrays, "ln_f.weight"),
            "bias": _get(arrays, "ln_f.bias"),
        },
    }


def llama_params_from_hf(
    arrays: Dict[str, Any], cfg: Optional[llama_mod.LlamaConfig] = None
):
    """Flat HF Llama param dict → native stacked pytree (linears
    transposed to ``(in, out)``)."""
    L = (
        cfg.n_layers
        if cfg is not None
        else _count_layers(arrays, "layers.{i}.input_layernorm.weight")
    )
    lm_head = (
        _get(arrays, "lm_head.weight")
        if any(k.endswith("lm_head.weight") for k in arrays)
        else _get(arrays, "embed_tokens.weight")
    )
    return {
        "embed": {"weight": _get(arrays, "embed_tokens.weight")},
        "layers": {
            "attn_norm": _stack(arrays, "layers.{i}.input_layernorm.weight", L),
            "wq": _stack(arrays, "layers.{i}.self_attn.q_proj.weight", L,
                         transpose=True),
            "wk": _stack(arrays, "layers.{i}.self_attn.k_proj.weight", L,
                         transpose=True),
            "wv": _stack(arrays, "layers.{i}.self_attn.v_proj.weight", L,
                         transpose=True),
            "wo": _stack(arrays, "layers.{i}.self_attn.o_proj.weight", L,
                         transpose=True),
            "mlp_norm": _stack(
                arrays, "layers.{i}.post_attention_layernorm.weight", L
            ),
            "w_gate": _stack(arrays, "layers.{i}.mlp.gate_proj.weight", L,
                             transpose=True),
            "w_up": _stack(arrays, "layers.{i}.mlp.up_proj.weight", L,
                           transpose=True),
            "w_down": _stack(arrays, "layers.{i}.mlp.down_proj.weight", L,
                             transpose=True),
        },
        "norm": {"weight": _get(arrays, "norm.weight")},
        "lm_head": {"weight": lm_head.T},
    }


def _count_layers(arrays, fmt: str) -> int:
    i = 0
    while True:
        name = fmt.format(i=i)
        if not any(k.endswith(name) for k in arrays):
            return i
        i += 1
