"""Llama-2-family decoder, TPU-native — the flagship model of this framework.

Pure-functional JAX implementation designed for the MXU and XLA's SPMD
partitioner, not a port of any torch module:

* **Stacked layers + ``lax.scan``** — all transformer blocks live in one
  pytree with a leading ``(n_layers, ...)`` dim, scanned over.  Compile time
  is O(1) in depth and XLA pipelines the layer loop.
* **bfloat16 compute, float32 softmax/norm/loss** — matmuls hit the MXU in
  bf16; numerically sensitive reductions run in f32.
* **Weights stored ``(in, out)``** so every projection is a plain ``x @ w``
  einsum that XLA tiles onto the 128×128 systolic array.
* **GQA** (``n_kv_heads <= n_heads``) and **RoPE** as in Llama-2/3.
* **Sharding by spec, not by code**: :func:`param_specs` emits a
  ``PartitionSpec`` pytree (Megatron-style TP + ZeRO-style FSDP dims);
  the forward is sharding-agnostic and XLA inserts the collectives.
* **Selective remat**: ``cfg.remat`` wraps the scanned block in
  ``jax.checkpoint`` — the standard HBM-for-FLOPs trade on TPU.

Capability parity note: the reference's BASELINE configs name Llama-2-7B/70B
as deferred-init workloads (BASELINE.md configs 4-5); this module provides
the native training-side model those workloads feed into, plus
:func:`abstract_params` / :func:`init_sharded` — the JAX-native
shard-then-materialize flow (inspect shapes with zero allocation, then
compile init with sharded outputs so every shard is generated on its own
device; cf. /root/reference/docs/src/deferred_init.rst:17-44).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import attention

__all__ = [
    "LlamaConfig",
    "llama_test",
    "llama_tiny",
    "llama_7b",
    "llama_70b",
    "init_params",
    "abstract_params",
    "init_sharded",
    "param_specs",
    "forward",
    "loss_fn",
    "num_params",
    "init_cache",
    "forward_cached",
    "forward_paged",
    "prep_decode",
    "pp_pieces",
    "pp_value_and_grad",
]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # Layer-loop unroll for the TRAINING forward: None = auto (full
    # unroll up to 32 layers — measured 20% faster fwd+bwd than the
    # rolled scan at 16 layers on v5e: XLA schedules/overlaps across
    # layer boundaries; partial unroll is WORSE than either extreme).
    # Beyond the auto bound the rolled scan keeps compile time O(1) in
    # depth.  The decode path always scans (measured: unroll loses).
    layer_unroll: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def _unroll(self) -> int:
        if self.layer_unroll:
            return self.layer_unroll
        return self.n_layers if self.n_layers <= 32 else 1


def llama_test() -> LlamaConfig:
    """CI-sized config: big enough to exercise GQA/scan/sharding."""
    return LlamaConfig(
        vocab_size=256,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_dim=128,
        max_seq_len=128,
        dtype=jnp.float32,
        remat=False,
    )


def llama_tiny() -> LlamaConfig:
    """~15M params — single-chip smoke/bench scale."""
    return LlamaConfig(
        vocab_size=32000,
        dim=256,
        n_layers=4,
        n_heads=8,
        n_kv_heads=8,
        ffn_dim=688,
        max_seq_len=2048,
    )


def llama_7b() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=32,
        ffn_dim=11008, max_seq_len=4096,
    )


def llama_70b() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=32000, dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
        ffn_dim=28672, max_seq_len=4096,
    )


# ---------------------------------------------------------------------------
# Parameters


def _shapes(cfg: LlamaConfig) -> dict:
    L, D, F, V = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.vocab_size
    Hq = cfg.n_heads * cfg.head_dim
    Hkv = cfg.n_kv_heads * cfg.head_dim
    return {
        "embed": {"weight": (V, D)},
        "layers": {
            "attn_norm": (L, D),
            "wq": (L, D, Hq),
            "wk": (L, D, Hkv),
            "wv": (L, D, Hkv),
            "wo": (L, Hq, D),
            "mlp_norm": (L, D),
            "w_gate": (L, D, F),
            "w_up": (L, D, F),
            "w_down": (L, F, D),
        },
        "norm": {"weight": (D,)},
        "lm_head": {"weight": (D, V)},
    }


def abstract_params(cfg: LlamaConfig):
    """Shape/dtype-only parameter pytree — the fake-tensor analog for the
    native model path (zero allocation; inspect then shard then init)."""
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s, cfg.dtype),
        _shapes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


def param_specs(
    cfg: LlamaConfig,
    *,
    tp: Optional[str] = "tp",
    fsdp: Optional[str] = "fsdp",
    pp: Optional[str] = None,
):
    """Megatron-TP + FSDP partition specs matching :func:`abstract_params`.

    Column-parallel projections (wq/wk/wv/w_gate/w_up) shard their *out* dim
    over ``tp``; row-parallel (wo/w_down) shard their *in* dim, so the pair
    needs exactly one ``psum`` per block (the classic Megatron layout).  The
    other large dim shards over ``fsdp`` (ZeRO-3).  Norms replicate.
    ``pp`` (if given) shards the stacked layer dim into pipeline stages.
    """
    return {
        "embed": {"weight": P(fsdp, tp)},
        "layers": {
            "attn_norm": P(pp),
            "wq": P(pp, fsdp, tp),
            "wk": P(pp, fsdp, tp),
            "wv": P(pp, fsdp, tp),
            "wo": P(pp, tp, fsdp),
            "mlp_norm": P(pp),
            "w_gate": P(pp, fsdp, tp),
            "w_up": P(pp, fsdp, tp),
            "w_down": P(pp, tp, fsdp),
        },
        "norm": {"weight": P()},
        "lm_head": {"weight": P(fsdp, tp)},
    }


def init_params(key, cfg: LlamaConfig):
    """Initialize parameters (host-order-independent: per-leaf fold_in keys).

    Scaled-normal init as in Llama: N(0, 0.02) for projections/embeddings,
    ones for norms; the down/out projections use the depth-scaled std
    0.02/sqrt(2*n_layers) (GPT-2/Llama residual-stream scaling).
    """
    import zlib

    shapes = _shapes(cfg)
    resid_scaled = {"wo", "w_down"}

    def leaf(path, shape):
        name = path[-1]
        if name in ("attn_norm", "mlp_norm") or path[0] == "norm":
            return jnp.ones(shape, dtype=cfg.dtype)
        std = 0.02
        if name in resid_scaled:
            std = 0.02 / (2.0 * cfg.n_layers) ** 0.5
        # crc32, not hash(): Python's str hash is salted per process, which
        # would make init non-deterministic across restarts and trace
        # *different* programs on different hosts.
        leaf_key = jax.random.fold_in(key, zlib.crc32("/".join(path).encode()))
        return (jax.random.normal(leaf_key, shape, dtype=jnp.float32) * std).astype(
            cfg.dtype
        )

    def walk(tree, path=()):
        if isinstance(tree, tuple):
            return leaf(path, tree)
        return {k: walk(v, path + (k,)) for k, v in tree.items()}

    return walk(shapes)


def init_sharded(key, cfg: LlamaConfig, mesh, *, tp="tp", fsdp="fsdp"):
    """Shard-then-materialize, native: compile init with sharded outputs so
    XLA generates each parameter shard directly on its owning device — no
    full tensor ever exists on any single host/chip (the north-star flow of
    BASELINE.md; the torch-module analog is
    :func:`torchdistx_tpu.materialize.materialize_module_jax`)."""
    from ..parallel.sharding import fit_shardings

    specs = param_specs(cfg, tp=tp, fsdp=fsdp)
    shardings = fit_shardings(specs, abstract_params(cfg), mesh)
    fn = jax.jit(partial(init_params, cfg=cfg), out_shardings=shardings)
    return fn(key)


def num_params(cfg: LlamaConfig) -> int:
    total = 0
    for leaf in jax.tree.leaves(
        _shapes(cfg), is_leaf=lambda x: isinstance(x, tuple)
    ):
        n = 1
        for s in leaf:
            n *= s
        total += n
    return total


# ---------------------------------------------------------------------------
# Forward


def _rmsnorm(x, weight, eps):
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * weight.astype(x.dtype)


# Shared by the unpipelined forward/loss and the 1F1B pieces — one
# definition of the head and the loss, so the paths cannot drift.


def _head(params, x, cfg: LlamaConfig):
    """Final norm + lm_head in ``cfg.dtype`` — the ONE head definition;
    needs ``norm``/``lm_head``."""
    x = _rmsnorm(x, params["norm"]["weight"], cfg.norm_eps)
    return x @ params["lm_head"]["weight"].astype(cfg.dtype)


def _head_logits(params, x, cfg: LlamaConfig):
    """:func:`_head` under the public f32-logits contract."""
    return _head(params, x, cfg).astype(jnp.float32)


def _ce(logits, targets):
    """Mean next-token cross-entropy in f32, from logits of any float
    dtype.  logsumexp form, not log_softmax: the full (B, S, V) log-prob
    array never materializes (measured ~2% of the 350M train step), and
    the f32 upcast fuses into the reduction, so bf16 logits never
    materialize an f32 copy either."""
    lse = jax.scipy.special.logsumexp(
        logits.astype(jnp.float32), axis=-1
    )
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[
        ..., 0
    ].astype(jnp.float32)
    return (lse - tgt).mean()


def _head_ce(params, x, targets, cfg: LlamaConfig):
    """Loss-path head + CE: :func:`_head`'s ``cfg.dtype`` logits feed
    :func:`_ce` directly (the training loss never materializes the
    (B, S, V) float32 logits that :func:`forward`'s public contract
    returns — at bf16 that halves the loss path's HBM traffic).
    Bitwise-identical to ``_ce(_head_logits(...))`` at float32."""
    return _ce(_head(params, x, cfg), targets)


def _rope_tables(positions, theta, half, dtype):
    """(cos, sin) of shape (B, S, 1, half) — position-only, so callers
    iterating layers (the decode scan) compute them ONCE per step."""
    freqs = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # (B, S, half)
    cos = jnp.cos(angles)[:, :, None, :].astype(dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(dtype)
    return cos, sin


def _rope_apply(x, cos, sin):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )


def _rope(x, positions, theta):
    # x: (B, S, H, D). Rotate pairs (even, odd) halves as in Llama.
    return _rope_apply(
        x, *_rope_tables(positions, theta, x.shape[-1] // 2, x.dtype)
    )


def _build_block(
    cfg: LlamaConfig,
    *,
    positions=None,
    mesh=None,
    seq_axis=None,
    attn_impl="auto",
    pre_permuted=False,
):
    """One transformer block as ``block(x, lp) -> x`` over unstacked layer
    params — shared by :func:`forward` and the 1F1B pipeline pieces.
    ``positions=None`` derives contiguous positions from the input shape."""

    def block(x, lp):
        bb, s = x.shape[0], x.shape[1]
        pos = (
            jnp.arange(s)[None] if positions is None else positions
        )
        h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"]).reshape(bb, s, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"]).reshape(bb, s, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"]).reshape(bb, s, cfg.n_kv_heads, cfg.head_dim)
        q = _rope(q, pos, cfg.rope_theta)
        k = _rope(k, pos, cfg.rope_theta)
        attn = attention(
            q, k, v, causal=True, impl=attn_impl, mesh=mesh,
            seq_axis=seq_axis, pre_permuted=pre_permuted,
        )
        x = x + attn.reshape(bb, s, -1) @ lp["wo"]
        h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        gated = jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])
        x = x + gated @ lp["w_down"]
        return x

    return block


def forward(
    params,
    tokens,
    cfg: LlamaConfig,
    *,
    mesh=None,
    seq_axis: Optional[str] = None,
    attn_impl: str = "auto",
    pp_axis: Optional[str] = None,
    n_microbatches: int = 1,
    seq_layout: str = "contiguous",
):
    """Token ids ``(B, S)`` → logits ``(B, S, V)`` (float32).

    Sharding-agnostic: run it under ``jit`` with sharded params/tokens and
    XLA partitions it.  ``seq_axis`` switches attention to the ring
    implementation over that mesh axis (sequence/context parallelism for
    long sequences).  ``pp_axis`` runs the transformer blocks through the
    GPipe pipeline (:mod:`torchdistx_tpu.parallel.pipeline`) with
    ``n_microbatches`` microbatches (pp composes with tp/fsdp; use jnp or
    pallas attention inside the pipeline, not ring).

    ``seq_layout="zigzag"`` keeps the *whole model's* activations in the
    zigzag sequence order of the load-balanced causal ring schedule:
    tokens are permuted once at the embedding, RoPE uses the original
    per-token positions, every attention call runs the zigzag ring with
    no per-layer resharding, and the returned logits are in **zigzag
    order** — use :func:`loss_fn`'s matching ``seq_layout`` (it aligns
    the targets), or invert with
    ``parallel.ring_attention._zigzag_perm(s, sp)[1]``.  Requires
    ``seq_axis`` and no pipeline axis.
    """
    x = _forward_hidden(
        params, tokens, cfg, mesh=mesh, seq_axis=seq_axis,
        attn_impl=attn_impl, pp_axis=pp_axis,
        n_microbatches=n_microbatches, seq_layout=seq_layout,
    )
    return _head_logits(params, x, cfg)


def _forward_hidden(
    params,
    tokens,
    cfg: LlamaConfig,
    *,
    mesh=None,
    seq_axis: Optional[str] = None,
    attn_impl: str = "auto",
    pp_axis: Optional[str] = None,
    n_microbatches: int = 1,
    seq_layout: str = "contiguous",
):
    """The transformer body of :func:`forward`: embedding + blocks, no
    final norm/head — shared by :func:`forward` (f32 logits, the public
    contract) and :func:`loss_fn` (cfg.dtype logits via :func:`_head_ce`,
    half the loss-path HBM traffic at bf16)."""
    b, s = tokens.shape
    if seq_layout == "zigzag":
        if seq_axis is None or mesh is None:
            raise ValueError("seq_layout='zigzag' needs mesh= and seq_axis=")
        if pp_axis is not None:
            raise ValueError("seq_layout='zigzag' does not compose with pp")
        from ..parallel.ring_attention import _zigzag_perm

        perm, _ = _zigzag_perm(s, mesh.shape[seq_axis])
        tokens = tokens[:, perm]
        # RoPE sees each token's ORIGINAL position.
        positions = jnp.asarray(perm)[None]
        if attn_impl not in ("auto", "ring_zigzag"):
            # Zigzag-ordered activations are only meaningful to the zigzag
            # ring schedule; any other kernel would attend in permuted order.
            raise ValueError(
                f"attn_impl={attn_impl!r} is incompatible with "
                "seq_layout='zigzag' (requires 'auto' or 'ring_zigzag')"
            )
        attn_impl = "ring_zigzag"
        pre_permuted = True
    elif seq_layout == "contiguous":
        # (1, S): broadcasts over any (micro)batch size.
        positions = jnp.arange(s)[None]
        pre_permuted = False
    else:
        raise ValueError(f"unknown seq_layout: {seq_layout!r}")
    if pp_axis is not None:
        from ..ops.attention import resolve_stage_attn_impl

        attn_impl = resolve_stage_attn_impl(attn_impl)
    x = jnp.take(params["embed"]["weight"], tokens, axis=0).astype(cfg.dtype)

    block = _build_block(
        cfg, positions=positions, mesh=mesh, seq_axis=seq_axis,
        attn_impl=attn_impl, pre_permuted=pre_permuted,
    )
    body = jax.checkpoint(block) if cfg.remat else block
    if pp_axis is not None:
        from ..parallel.pipeline import pipeline_forward

        x = pipeline_forward(
            x, params["layers"], body, mesh=mesh, axis=pp_axis,
            n_microbatches=n_microbatches,
        )
    else:
        x, _ = jax.lax.scan(lambda h, lp: (body(h, lp), None), x,
                            params["layers"], unroll=cfg._unroll)
    return x


def init_cache(cfg: LlamaConfig, batch: int, max_len: int):
    """Static-shape KV cache: ``(L, B, Smax, Hkv, Dh)`` per k/v."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
    }


def prep_decode(params, cfg: LlamaConfig):
    """Decode-prepped params: qkv and gate/up projections pre-fused.

    A decode step is latency-bound on per-op overhead, not FLOPs — fusing
    ``wq``/``wk``/``wv`` into one ``(D, (Hq+2·Hkv)·Dh)`` matmul and
    ``w_gate``/``w_up`` into one ``(D, 2F)`` matmul cuts the per-layer
    matmul count from 7 to 4.  Called ONCE per generation (outside the
    token scan — :mod:`.generate` hoists it), so the concat cost is
    amortized over every decode step.  :func:`forward_cached` accepts
    either raw or prepped params.  Idempotent: prepped input is returned
    unchanged.
    """
    if "wqkv" in params["layers"]:
        return params
    lp = dict(params["layers"])
    lp["wqkv"] = jnp.concatenate([lp.pop("wq"), lp.pop("wk"), lp.pop("wv")],
                                 axis=-1)
    lp["wgu"] = jnp.concatenate([lp.pop("w_gate"), lp.pop("w_up")], axis=-1)
    return {**params, "layers": lp}


def forward_cached(params, tokens, cfg: LlamaConfig, cache, pos):
    """Incremental forward: ``tokens (B, T)`` at positions ``pos..pos+T-1``.

    Returns ``(logits (B, T, V) f32, new_cache)``.  One compiled program
    serves both prefill (T = prompt length) and decode (T = 1) — shapes are
    static, ``pos`` is a traced scalar.  ``params`` may be raw or
    :func:`prep_decode`-prepped.  Raw params are fused IN the call — fine
    for a one-shot prefill, but a caller jitting a per-token decode loop
    directly must hoist :func:`prep_decode` out of the loop (as
    :mod:`.generate` does) or pay the weight-fusion concat every step.

    The KV caches ride the layer scan as CARRY, updated in place by a
    one-token ``dynamic_update_slice`` — passing them as scan xs/ys would
    copy the full per-layer cache every layer every step (~2× the cache
    size in HBM traffic per decode step).
    """
    from ..ops.attention import cached_attention

    if "wqkv" not in params["layers"]:
        params = prep_decode(params, cfg)
    b, t = tokens.shape
    x = jnp.take(params["embed"]["weight"], tokens, axis=0).astype(cfg.dtype)
    positions = jnp.broadcast_to(pos + jnp.arange(t), (b, t))
    n_q = cfg.n_heads * cfg.head_dim
    n_kv = cfg.n_kv_heads * cfg.head_dim
    # Rope tables are position-only — computed ONCE per step here, not per
    # layer inside the scan.
    cos, sin = _rope_tables(
        positions, cfg.rope_theta, cfg.head_dim // 2, cfg.dtype
    )

    def block(carry, layer):
        x, kc, vc = carry
        lp, i = layer
        h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        qkv = h @ lp["wqkv"]
        q = qkv[..., :n_q].reshape(b, t, cfg.n_heads, cfg.head_dim)
        k = qkv[..., n_q:n_q + n_kv].reshape(
            b, t, cfg.n_kv_heads, cfg.head_dim
        )
        v = qkv[..., n_q + n_kv:].reshape(
            b, t, cfg.n_kv_heads, cfg.head_dim
        )
        q = _rope_apply(q, cos, sin)
        k = _rope_apply(k, cos, sin)
        kc = jax.lax.dynamic_update_slice(kc, k[None], (i, 0, pos, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, v[None], (i, 0, pos, 0, 0))
        attn = cached_attention(
            q,
            jax.lax.dynamic_index_in_dim(kc, i, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(vc, i, 0, keepdims=False),
            pos,
        )
        x = x + attn.reshape(b, t, -1) @ lp["wo"]
        h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        gu = h @ lp["wgu"]
        gated = jax.nn.silu(gu[..., : cfg.ffn_dim]) * gu[..., cfg.ffn_dim:]
        x = x + gated @ lp["w_down"]
        return (x, kc, vc), None

    (x, new_k, new_v), _ = jax.lax.scan(
        block,
        (x, cache["k"], cache["v"]),
        (params["layers"], jnp.arange(cfg.n_layers)),
    )
    return _head_logits(params, x, cfg), {"k": new_k, "v": new_v}


def forward_paged(params, tokens, cfg: LlamaConfig, cache, block_tables,
                  positions):
    """``T`` tokens per slot against a block/paged KV cache (serving path).

    ``tokens (B, T)`` holds each slot's current tokens at its OWN
    positions ``positions[b] .. positions[b]+T-1`` — unlike
    :func:`forward_cached`, whose scalar ``pos`` forces every batch row
    to the same depth, so it cannot serve a continuously batched decode
    where slots admit and retire independently.  ``T == 1`` is the
    decode step; ``T > 1`` is a **chunked-prefill block**: the chunk's
    KV scatters into the slot's pages, then every chunk query attends
    the slot's full cached prefix — shared prefix-cache pages included —
    plus the chunk itself (causal).  ``cache`` is the paged pool
    ``{"k","v"}: (L, NB, bs, Hkv, Dh)`` and ``block_tables (B, M)`` maps
    slot-logical blocks to pages (see :mod:`torchdistx_tpu.serving`).

    Returns ``(logits (B, T, V) f32, new cache)``.  Same fused-weight layer
    scan as :func:`forward_cached` (prep_decode applies; caches ride the
    scan carry), with the slice write/read swapped for a page scatter and
    the block-table gather of :func:`ops.attention.paged_attention` —
    values match the contiguous path exactly.

    A position that has run past its table (``pos//bs >= M``) scatters
    into page 0 — the trash page the serving engine never hands out — so
    a retired-but-still-batched slot (or a prefill chunk's padding tail)
    can never corrupt a live slot's cache.
    """
    from ..ops.attention import paged_attention, paged_write_index

    if "wqkv" not in params["layers"]:
        params = prep_decode(params, cfg)
    b, t = tokens.shape
    x = jnp.take(params["embed"]["weight"], tokens, axis=0).astype(cfg.dtype)
    n_q = cfg.n_heads * cfg.head_dim
    n_kv = cfg.n_kv_heads * cfg.head_dim
    pos_bt = positions[:, None] + jnp.arange(t)[None]
    cos, sin = _rope_tables(
        pos_bt, cfg.rope_theta, cfg.head_dim // 2, cfg.dtype,
    )
    # (B, T) write steering: each token of the block lands in its slot's
    # own pages (pads past the table steer to trash).
    blk, off = paged_write_index(
        block_tables, pos_bt, cache["k"].shape[2]
    )

    # jax.named_scope regions (attn/mlp) label the HLO so a profiler
    # capture (telemetry.timeplane, docs/observability.md "Time plane")
    # attributes device time to model regions — metadata only, the
    # compiled computation (and token identity) is unchanged.
    def block(carry, layer):
        x, kc, vc = carry
        lp, i = layer
        with jax.named_scope("attn"):
            h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
            qkv = h @ lp["wqkv"]
            q = qkv[..., :n_q].reshape(b, t, cfg.n_heads, cfg.head_dim)
            k = qkv[..., n_q:n_q + n_kv].reshape(
                b, t, cfg.n_kv_heads, cfg.head_dim
            )
            v = qkv[..., n_q + n_kv:].reshape(
                b, t, cfg.n_kv_heads, cfg.head_dim
            )
            q = _rope_apply(q, cos, sin)
            k = _rope_apply(k, cos, sin)
            kc = kc.at[i, blk, off].set(k)
            vc = vc.at[i, blk, off].set(v)
            attn = paged_attention(
                q,
                jax.lax.dynamic_index_in_dim(kc, i, 0, keepdims=False),
                jax.lax.dynamic_index_in_dim(vc, i, 0, keepdims=False),
                block_tables,
                positions,
            )
            x = x + attn.reshape(b, t, -1) @ lp["wo"]
        with jax.named_scope("mlp"):
            h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
            gu = h @ lp["wgu"]
            gated = (
                jax.nn.silu(gu[..., : cfg.ffn_dim]) * gu[..., cfg.ffn_dim:]
            )
            x = x + gated @ lp["w_down"]
        return (x, kc, vc), None

    (x, new_k, new_v), _ = jax.lax.scan(
        block,
        (x, cache["k"], cache["v"]),
        (params["layers"], jnp.arange(cfg.n_layers)),
    )
    return _head_logits(params, x, cfg), {"k": new_k, "v": new_v}


def loss_fn(
    params,
    tokens,
    targets,
    cfg: LlamaConfig,
    *,
    mesh=None,
    seq_axis: Optional[str] = None,
    attn_impl: str = "auto",
    pp_axis: Optional[str] = None,
    n_microbatches: int = 1,
    seq_layout: str = "contiguous",
):
    """Mean next-token cross-entropy (float32).

    ``seq_layout="zigzag"``: the forward runs entirely in zigzag sequence
    order (see :func:`forward`); targets are aligned by the same
    permutation, and the mean is order-invariant.

    Computes the head through :func:`_head_ce` — logits stay in
    ``cfg.dtype`` on the loss path (bitwise-identical to
    ``_ce(forward(...))`` at float32; at bf16 it halves the loss path's
    HBM traffic, f32 softmax math unchanged).
    """
    x = _forward_hidden(
        params, tokens, cfg, mesh=mesh, seq_axis=seq_axis,
        attn_impl=attn_impl, pp_axis=pp_axis,
        n_microbatches=n_microbatches, seq_layout=seq_layout,
    )
    if seq_layout == "zigzag":
        from ..parallel.ring_attention import _zigzag_perm

        perm, _ = _zigzag_perm(tokens.shape[1], mesh.shape[seq_axis])
        targets = targets[:, perm]
    return _head_ce(params, x, targets, cfg)


# ---------------------------------------------------------------------------
# 1F1B pipeline pieces (see parallel.pipeline.pipeline_value_and_grad):
# embedding on stage 0, blocks pipelined, loss head inside the last stage.


def pp_pieces(cfg: LlamaConfig, *, mesh=None, attn_impl: str = "auto"):
    """``(embed_fn, block_fn, head_loss_fn)`` for the 1F1B schedule."""
    from ..ops.attention import resolve_stage_attn_impl

    impl = resolve_stage_attn_impl(attn_impl)
    block = _build_block(cfg, mesh=mesh, attn_impl=impl)
    body = jax.checkpoint(block) if cfg.remat else block

    def embed_fn(ep, tokens_mb):
        return jnp.take(
            ep["embed"]["weight"], tokens_mb, axis=0
        ).astype(cfg.dtype)

    def head_loss_fn(hp, h, targets_mb):
        return _head_ce(hp, h, targets_mb, cfg)

    return embed_fn, body, head_loss_fn


def pp_value_and_grad(
    params,
    tokens,
    targets,
    cfg: LlamaConfig,
    *,
    mesh,
    pp_axis: str = "pp",
    n_microbatches: int = 1,
    attn_impl: str = "auto",
):
    """``(loss, grads)`` via the 1F1B pipeline — a drop-in replacement for
    ``jax.value_and_grad(loss_fn)`` when training pipeline-parallel, with
    O(P) live activations instead of O(M + P) (GPipe autodiff)."""
    from ..parallel.pipeline import pipeline_value_and_grad

    embed_fn, block_fn, head_loss_fn = pp_pieces(
        cfg, mesh=mesh, attn_impl=attn_impl
    )
    loss, (g_ep, g_lp, g_hp) = pipeline_value_and_grad(
        {"embed": params["embed"]},
        params["layers"],
        {"norm": params["norm"], "lm_head": params["lm_head"]},
        tokens,
        targets,
        embed_fn,
        block_fn,
        head_loss_fn,
        mesh=mesh,
        axis=pp_axis,
        n_microbatches=n_microbatches,
    )
    grads = {
        "embed": g_ep["embed"],
        "layers": g_lp,
        "norm": g_hp["norm"],
        "lm_head": g_hp["lm_head"],
    }
    return loss, grads
