"""TPU-native model stack.

The reference framework ships no models of its own — its BASELINE workloads
instantiate torchvision / HF models through deferred init.  This framework
supports that torch-module path (:mod:`torchdistx_tpu.deferred_init`) *and*
ships JAX-native model families designed for the TPU training stack:

* :mod:`torchdistx_tpu.models.llama` — Llama-2-family decoder (flagship).
* :mod:`torchdistx_tpu.models.gpt2` — GPT-2 family.
"""

from . import gpt2, llama  # noqa: F401
