"""ResNet-50 (torch-side testbed model) — BASELINE config 2.

BASELINE.md config 2 is "deferred_init torchvision resnet50, materialize on
single TPU chip"; torchvision is not in this environment, so this is a
standard ResNet-50 in plain ``torch.nn`` with the same module types
(Conv2d / BatchNorm2d / Linear / pooling) and the same init behavior —
the deferred-init tape it records is structurally identical to
torchvision's (kaiming conv init, BN ones/zeros, linear uniform).

This is a *torch-side workload model* for exercising the fake/deferred/
materialize pipeline on a convnet tape (the JAX model stack lives in the
sibling modules).  Architecture per He et al. 2015 (arXiv:1512.03385).
"""

from __future__ import annotations

import torch.nn as nn

__all__ = ["resnet50", "Bottleneck", "ResNet"]


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, in_ch: int, width: int, stride: int = 1,
                 downsample: nn.Module | None = None):
        super().__init__()
        out_ch = width * self.expansion
        self.conv1 = nn.Conv2d(in_ch, width, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        self.conv2 = nn.Conv2d(width, width, 3, stride=stride, padding=1,
                               bias=False)
        self.bn2 = nn.BatchNorm2d(width)
        self.conv3 = nn.Conv2d(width, out_ch, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(out_ch)
        self.relu = nn.ReLU(inplace=True)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Module):
    def __init__(self, layers: list[int], num_classes: int = 1000):
        super().__init__()
        self.in_ch = 64
        self.conv1 = nn.Conv2d(3, 64, 7, stride=2, padding=3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, stride=2, padding=1)
        self.layer1 = self._make_layer(64, layers[0])
        self.layer2 = self._make_layer(128, layers[1], stride=2)
        self.layer3 = self._make_layer(256, layers[2], stride=2)
        self.layer4 = self._make_layer(512, layers[3], stride=2)
        self.avgpool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(512 * Bottleneck.expansion, num_classes)

    def _make_layer(self, width: int, blocks: int, stride: int = 1):
        downsample = None
        out_ch = width * Bottleneck.expansion
        if stride != 1 or self.in_ch != out_ch:
            downsample = nn.Sequential(
                nn.Conv2d(self.in_ch, out_ch, 1, stride=stride, bias=False),
                nn.BatchNorm2d(out_ch),
            )
        layers = [Bottleneck(self.in_ch, width, stride, downsample)]
        self.in_ch = out_ch
        layers += [Bottleneck(out_ch, width) for _ in range(1, blocks)]
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        x = self.avgpool(x).flatten(1)
        return self.fc(x)


def resnet50(num_classes: int = 1000) -> ResNet:
    return ResNet([3, 4, 6, 3], num_classes)
