"""GPT-2 family, TPU-native (BASELINE config 3's model family).

Same design rules as the flagship (:mod:`torchdistx_tpu.models.llama`):
stacked layers + ``lax.scan``, bf16 matmuls / f32 reductions, ``(in, out)``
weight layout, sharding via :func:`param_specs`, remat.  GPT-2 specifics:
learned positional embeddings, pre-LN with biases, GELU MLP, standard MHA
(no GQA), logits tied to the token embedding.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import attention

__all__ = [
    "GPT2Config",
    "gpt2_test",
    "gpt2_small",
    "gpt2_xl",
    "init_params",
    "abstract_params",
    "param_specs",
    "forward",
    "loss_fn",
    "num_params",
    "init_cache",
    "forward_cached",
    "forward_paged",
    "pp_pieces",
    "pp_value_and_grad",
]


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    max_seq_len: int = 1024
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # Training-forward layer-loop unroll; None = auto (see llama).
    layer_unroll: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def _unroll(self) -> int:
        if self.layer_unroll:
            return self.layer_unroll
        return self.n_layers if self.n_layers <= 32 else 1

    @property
    def ffn_dim(self) -> int:
        return 4 * self.dim


def gpt2_test() -> GPT2Config:
    return GPT2Config(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, max_seq_len=128,
        dtype=jnp.float32, remat=False,
    )


def gpt2_small() -> GPT2Config:
    return GPT2Config()


def gpt2_xl() -> GPT2Config:
    return GPT2Config(dim=1600, n_layers=48, n_heads=25, max_seq_len=1024)


def _shapes(cfg: GPT2Config) -> dict:
    L, D, F, V, S = (
        cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.vocab_size, cfg.max_seq_len,
    )
    return {
        "wte": {"weight": (V, D)},
        "wpe": {"weight": (S, D)},
        "layers": {
            "ln_1": {"scale": (L, D), "bias": (L, D)},
            "attn_qkv": {"weight": (L, D, 3 * D), "bias": (L, 3 * D)},
            "attn_proj": {"weight": (L, D, D), "bias": (L, D)},
            "ln_2": {"scale": (L, D), "bias": (L, D)},
            "mlp_fc": {"weight": (L, D, F), "bias": (L, F)},
            "mlp_proj": {"weight": (L, F, D), "bias": (L, D)},
        },
        "ln_f": {"scale": (D,), "bias": (D,)},
    }


def abstract_params(cfg: GPT2Config):
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s, cfg.dtype),
        _shapes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


def param_specs(
    cfg: GPT2Config,
    *,
    tp: Optional[str] = "tp",
    fsdp: Optional[str] = "fsdp",
    pp: Optional[str] = None,
):
    """Megatron TP for GPT-2: qkv/fc column-parallel (out dim), proj
    row-parallel (in dim); embeddings sharded (vocab|seq over fsdp, model
    dim over tp); norms replicated; ``pp`` shards the layer dim into
    pipeline stages."""
    return {
        "wte": {"weight": P(fsdp, tp)},
        "wpe": {"weight": P(fsdp, tp)},
        "layers": {
            "ln_1": {"scale": P(pp), "bias": P(pp)},
            "attn_qkv": {"weight": P(pp, fsdp, tp), "bias": P(pp, tp)},
            "attn_proj": {"weight": P(pp, tp, fsdp), "bias": P(pp)},
            "ln_2": {"scale": P(pp), "bias": P(pp)},
            "mlp_fc": {"weight": P(pp, fsdp, tp), "bias": P(pp, tp)},
            "mlp_proj": {"weight": P(pp, tp, fsdp), "bias": P(pp)},
        },
        "ln_f": {"scale": P(), "bias": P()},
    }


def init_params(key, cfg: GPT2Config):
    """GPT-2 init: N(0, 0.02) weights/embeddings, residual projections
    scaled by 1/sqrt(2·n_layers), zeros biases, ones LN scales."""
    import zlib

    shapes = _shapes(cfg)
    resid_scaled = {"attn_proj", "mlp_proj"}

    def leaf(path, shape):
        name = path[-1]
        parent = path[-2] if len(path) > 1 else ""
        if name == "scale":
            return jnp.ones(shape, dtype=cfg.dtype)
        if name == "bias":
            return jnp.zeros(shape, dtype=cfg.dtype)
        std = 0.02
        if parent in resid_scaled:
            std = 0.02 / (2.0 * cfg.n_layers) ** 0.5
        leaf_key = jax.random.fold_in(key, zlib.crc32("/".join(path).encode()))
        return (
            jax.random.normal(leaf_key, shape, dtype=jnp.float32) * std
        ).astype(cfg.dtype)

    def walk(tree, path=()):
        if isinstance(tree, tuple):
            return leaf(path, tree)
        return {k: walk(v, path + (k,)) for k, v in tree.items()}

    return walk(shapes)


def num_params(cfg: GPT2Config) -> int:
    total = 0
    for leaf in jax.tree.leaves(
        _shapes(cfg), is_leaf=lambda x: isinstance(x, tuple)
    ):
        n = 1
        for s in leaf:
            n *= s
        total += n
    return total


def _layernorm(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    return out.astype(x.dtype) * scale.astype(x.dtype) + bias.astype(x.dtype)


# Shared by the unpipelined forward/loss and the 1F1B pieces — one
# definition of the embedding, the head, and the loss, so the paths
# cannot drift.


def _embed(params, tokens, cfg: GPT2Config):
    """wte[tokens] + wpe[:S] — ``params`` needs only ``wte``/``wpe``."""
    s = tokens.shape[1]
    x = jnp.take(params["wte"]["weight"], tokens, axis=0).astype(cfg.dtype)
    return x + params["wpe"]["weight"][:s].astype(cfg.dtype)[None]


def _head(params, x, cfg: GPT2Config):
    """ln_f + tied-embedding logits in ``cfg.dtype`` — the ONE head
    definition; needs ``ln_f``/``wte``."""
    x = _layernorm(
        x, params["ln_f"]["scale"], params["ln_f"]["bias"], cfg.norm_eps
    )
    return x @ params["wte"]["weight"].astype(cfg.dtype).T


def _head_logits(params, x, cfg: GPT2Config):
    """:func:`_head` under the public f32-logits contract."""
    return _head(params, x, cfg).astype(jnp.float32)


def _ce(logits, targets):
    """Mean next-token CE in f32 from logits of any float dtype (see
    llama._ce: logsumexp form, upcast fused into the reduction)."""
    lse = jax.scipy.special.logsumexp(
        logits.astype(jnp.float32), axis=-1
    )
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[
        ..., 0
    ].astype(jnp.float32)
    return (lse - tgt).mean()


def _head_ce(params, x, targets, cfg: GPT2Config):
    """Loss-path :func:`_head` + CE with ``cfg.dtype`` logits (see
    llama._head_ce; bitwise-identical to ``_ce(_head_logits(...))`` at
    float32)."""
    return _ce(_head(params, x, cfg), targets)


def _build_block(
    cfg: GPT2Config, *, mesh=None, seq_axis=None, attn_impl="auto"
):
    """One transformer block as ``block(x, lp) -> x`` over unstacked layer
    params — shared by :func:`forward` and the 1F1B pipeline pieces."""

    def block(x, lp):
        bb, s = x.shape[0], x.shape[1]
        h = _layernorm(x, lp["ln_1"]["scale"], lp["ln_1"]["bias"], cfg.norm_eps)
        qkv = h @ lp["attn_qkv"]["weight"] + lp["attn_qkv"]["bias"].astype(
            cfg.dtype
        )
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(bb, s, cfg.n_heads, cfg.head_dim)
        k = k.reshape(bb, s, cfg.n_heads, cfg.head_dim)
        v = v.reshape(bb, s, cfg.n_heads, cfg.head_dim)
        attn = attention(
            q, k, v, causal=True, impl=attn_impl, mesh=mesh, seq_axis=seq_axis
        ).reshape(bb, s, -1)
        x = x + attn @ lp["attn_proj"]["weight"] + lp["attn_proj"][
            "bias"
        ].astype(cfg.dtype)
        h = _layernorm(x, lp["ln_2"]["scale"], lp["ln_2"]["bias"], cfg.norm_eps)
        h = jax.nn.gelu(
            h @ lp["mlp_fc"]["weight"] + lp["mlp_fc"]["bias"].astype(cfg.dtype)
        )
        x = x + h @ lp["mlp_proj"]["weight"] + lp["mlp_proj"]["bias"].astype(
            cfg.dtype
        )
        return x

    return block


def forward(
    params,
    tokens,
    cfg: GPT2Config,
    *,
    mesh=None,
    seq_axis: Optional[str] = None,
    attn_impl: str = "auto",
    pp_axis: Optional[str] = None,
    n_microbatches: int = 1,
):
    """Token ids ``(B, S)`` → logits ``(B, S, V)`` (f32, tied embeddings)."""
    x = _forward_hidden(
        params, tokens, cfg, mesh=mesh, seq_axis=seq_axis,
        attn_impl=attn_impl, pp_axis=pp_axis,
        n_microbatches=n_microbatches,
    )
    return _head_logits(params, x, cfg)


def _forward_hidden(
    params,
    tokens,
    cfg: GPT2Config,
    *,
    mesh=None,
    seq_axis: Optional[str] = None,
    attn_impl: str = "auto",
    pp_axis: Optional[str] = None,
    n_microbatches: int = 1,
):
    """Embedding + blocks, no ln_f/head (see llama._forward_hidden)."""
    if pp_axis is not None:
        from ..ops.attention import resolve_stage_attn_impl

        attn_impl = resolve_stage_attn_impl(attn_impl)
    x = _embed(params, tokens, cfg)

    block = _build_block(
        cfg, mesh=mesh, seq_axis=seq_axis, attn_impl=attn_impl
    )
    body = jax.checkpoint(block) if cfg.remat else block
    if pp_axis is not None:
        from ..parallel.pipeline import pipeline_forward

        x = pipeline_forward(
            x, params["layers"], body, mesh=mesh, axis=pp_axis,
            n_microbatches=n_microbatches,
        )
    else:
        x, _ = jax.lax.scan(lambda h, lp: (body(h, lp), None), x,
                            params["layers"], unroll=cfg._unroll)
    return x


def init_cache(cfg: GPT2Config, batch: int, max_len: int):
    """Static-shape KV cache: ``(L, B, Smax, H, Dh)`` per k/v."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
    }


def forward_cached(params, tokens, cfg: GPT2Config, cache, pos):
    """Incremental forward (see :func:`llama.forward_cached`)."""
    from ..ops.attention import cached_attention

    b, t = tokens.shape
    x = jnp.take(params["wte"]["weight"], tokens, axis=0).astype(cfg.dtype)
    pos_ids = pos + jnp.arange(t)
    x = x + jnp.take(params["wpe"]["weight"], pos_ids, axis=0).astype(
        cfg.dtype
    )[None]

    def block(carry, layer):
        # Caches ride the carry, updated in place with a one-token slice
        # (scan xs/ys would copy the full per-layer cache every layer —
        # see llama.forward_cached).
        x, kc, vc = carry
        lp, i = layer
        h = _layernorm(x, lp["ln_1"]["scale"], lp["ln_1"]["bias"], cfg.norm_eps)
        qkv = h @ lp["attn_qkv"]["weight"] + lp["attn_qkv"]["bias"].astype(
            cfg.dtype
        )
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, cfg.n_heads, cfg.head_dim)
        k = k.reshape(b, t, cfg.n_heads, cfg.head_dim)
        v = v.reshape(b, t, cfg.n_heads, cfg.head_dim)
        kc = jax.lax.dynamic_update_slice(kc, k[None], (i, 0, pos, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, v[None], (i, 0, pos, 0, 0))
        attn = cached_attention(
            q,
            jax.lax.dynamic_index_in_dim(kc, i, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(vc, i, 0, keepdims=False),
            pos,
        ).reshape(b, t, -1)
        x = x + attn @ lp["attn_proj"]["weight"] + lp["attn_proj"][
            "bias"
        ].astype(cfg.dtype)
        h = _layernorm(x, lp["ln_2"]["scale"], lp["ln_2"]["bias"], cfg.norm_eps)
        h = jax.nn.gelu(
            h @ lp["mlp_fc"]["weight"] + lp["mlp_fc"]["bias"].astype(cfg.dtype)
        )
        x = x + h @ lp["mlp_proj"]["weight"] + lp["mlp_proj"]["bias"].astype(
            cfg.dtype
        )
        return (x, kc, vc), None

    (x, new_k, new_v), _ = jax.lax.scan(
        block,
        (x, cache["k"], cache["v"]),
        (params["layers"], jnp.arange(cfg.n_layers)),
    )
    return _head_logits(params, x, cfg), {"k": new_k, "v": new_v}


def forward_paged(params, tokens, cfg: GPT2Config, cache, block_tables,
                  positions):
    """``T`` tokens per slot against a paged KV cache — per-slot
    positions; ``T == 1`` decode, ``T > 1`` a chunked-prefill block
    (see :func:`llama.forward_paged`; GPT-2: learned positional embeds,
    pre-LN biases, no GQA)."""
    from ..ops.attention import paged_attention, paged_write_index

    b, t = tokens.shape
    pos_ids = positions[:, None] + jnp.arange(t)[None]
    x = jnp.take(params["wte"]["weight"], tokens, axis=0).astype(cfg.dtype)
    # jnp.take clamps out-of-range ids: a chunk's padding tail past
    # max_seq_len reads the last wpe row, and its K/V lands in trash.
    x = x + jnp.take(params["wpe"]["weight"], pos_ids, axis=0).astype(
        cfg.dtype
    )
    blk, off = paged_write_index(
        block_tables, pos_ids, cache["k"].shape[2]
    )

    # attn/mlp named_scope regions for profiler attribution (see
    # llama.forward_paged) — HLO metadata only, values unchanged.
    def block(carry, layer):
        x, kc, vc = carry
        lp, i = layer
        with jax.named_scope("attn"):
            h = _layernorm(
                x, lp["ln_1"]["scale"], lp["ln_1"]["bias"], cfg.norm_eps
            )
            qkv = h @ lp["attn_qkv"]["weight"] + lp["attn_qkv"][
                "bias"
            ].astype(cfg.dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(b, t, cfg.n_heads, cfg.head_dim)
            k = k.reshape(b, t, cfg.n_heads, cfg.head_dim)
            v = v.reshape(b, t, cfg.n_heads, cfg.head_dim)
            kc = kc.at[i, blk, off].set(k)
            vc = vc.at[i, blk, off].set(v)
            attn = paged_attention(
                q,
                jax.lax.dynamic_index_in_dim(kc, i, 0, keepdims=False),
                jax.lax.dynamic_index_in_dim(vc, i, 0, keepdims=False),
                block_tables,
                positions,
            ).reshape(b, t, -1)
            x = x + attn @ lp["attn_proj"]["weight"] + lp["attn_proj"][
                "bias"
            ].astype(cfg.dtype)
        with jax.named_scope("mlp"):
            h = _layernorm(
                x, lp["ln_2"]["scale"], lp["ln_2"]["bias"], cfg.norm_eps
            )
            h = jax.nn.gelu(
                h @ lp["mlp_fc"]["weight"]
                + lp["mlp_fc"]["bias"].astype(cfg.dtype)
            )
            x = x + h @ lp["mlp_proj"]["weight"] + lp["mlp_proj"][
                "bias"
            ].astype(cfg.dtype)
        return (x, kc, vc), None

    (x, new_k, new_v), _ = jax.lax.scan(
        block,
        (x, cache["k"], cache["v"]),
        (params["layers"], jnp.arange(cfg.n_layers)),
    )
    return _head_logits(params, x, cfg), {"k": new_k, "v": new_v}


def loss_fn(
    params,
    tokens,
    targets,
    cfg: GPT2Config,
    *,
    mesh=None,
    seq_axis: Optional[str] = None,
    attn_impl: str = "auto",
    pp_axis: Optional[str] = None,
    n_microbatches: int = 1,
):
    x = _forward_hidden(
        params, tokens, cfg, mesh=mesh, seq_axis=seq_axis,
        attn_impl=attn_impl, pp_axis=pp_axis,
        n_microbatches=n_microbatches,
    )
    return _head_ce(params, x, targets, cfg)


# ---------------------------------------------------------------------------
# 1F1B pipeline pieces (see parallel.pipeline.pipeline_value_and_grad):
# wte+wpe embedding on stage 0, blocks pipelined, ln_f + tied-logits loss
# inside the last stage.


def pp_pieces(cfg: GPT2Config, *, mesh=None, attn_impl: str = "auto"):
    """``(embed_fn, block_fn, head_loss_fn)`` for the 1F1B schedule.

    Shares :func:`_embed` / :func:`_head_logits` / :func:`_ce` with the
    unpipelined forward/loss so the two paths cannot drift."""
    from ..ops.attention import resolve_stage_attn_impl

    impl = resolve_stage_attn_impl(attn_impl)
    block = _build_block(cfg, mesh=mesh, attn_impl=impl)
    body = jax.checkpoint(block) if cfg.remat else block

    def embed_fn(ep, tokens_mb):
        return _embed(ep, tokens_mb, cfg)

    def head_loss_fn(hp, h, targets_mb):
        return _head_ce(hp, h, targets_mb, cfg)

    return embed_fn, body, head_loss_fn


def pp_value_and_grad(
    params,
    tokens,
    targets,
    cfg: GPT2Config,
    *,
    mesh,
    pp_axis: str = "pp",
    n_microbatches: int = 1,
    attn_impl: str = "auto",
):
    """``(loss, grads)`` via the 1F1B pipeline.

    The TIED token embedding rides the pipeline's ``shared_params``
    channel: stage 0's embed and the last stage's head both read it, and
    it is carried with ONE (V, D) f32 gradient accumulator — its total
    gradient is the (psum'd) sum of the two contributions, exactly what
    autodiff of the tied forward produces, at half the accumulator
    memory of duplicating it into both stages' params."""
    from ..parallel.pipeline import pipeline_value_and_grad

    embed_fn, block_fn, head_loss_fn = pp_pieces(
        cfg, mesh=mesh, attn_impl=attn_impl
    )

    def embed_sp(ep_, tokens_mb, sp_):
        return embed_fn({**ep_, **sp_}, tokens_mb)

    def head_loss_sp(hp_, h, targets_mb, sp_):
        return head_loss_fn({**hp_, **sp_}, h, targets_mb)

    ep = {"wpe": params["wpe"]}
    hp = {"ln_f": params["ln_f"]}
    sp = {"wte": params["wte"]}
    loss, (g_ep, g_lp, g_hp, g_sp) = pipeline_value_and_grad(
        ep, params["layers"], hp, tokens, targets,
        embed_sp, block_fn, head_loss_sp,
        mesh=mesh, axis=pp_axis, n_microbatches=n_microbatches,
        shared_params=sp,
    )
    grads = {
        "wte": g_sp["wte"],
        "wpe": g_ep["wpe"],
        "layers": g_lp,
        "ln_f": g_hp["ln_f"],
    }
    return loss, grads
