"""ctypes bindings for the native core (libtdx_core.so).

The reference binds its C++ runtime through a pybind11 extension
(/root/reference/src/python/torchdistx/_C/); pybind11 isn't available in this
environment, so the native core exposes a C ABI (src/cc/tdx_core/graph.h)
bound here with ctypes — same layering, different binding tech.

Loading is lazy and failure-tolerant: if the library isn't built (or g++ is
unavailable for the on-demand build), the tape falls back to the pure-Python
graph with identical semantics.  ``TDX_DISABLE_NATIVE=1`` forces the
fallback (used by tests to compare both paths).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_PKG_DIR)
_LIB_PATH = os.path.join(_PKG_DIR, "lib", "libtdx_core.so")
_SRC = os.path.join(_REPO_ROOT, "src", "cc", "tdx_core", "graph.cc")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _stale() -> bool:
    try:
        return os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)
    except OSError:
        return True


def _try_build() -> bool:
    """One-shot on-demand build (g++, single TU) so the native path is live
    in dev checkouts without a separate build step.

    Compiles to a process-unique temp file and ``os.replace``s it into
    place: concurrent processes (parallel pytest, pytest + bench) must never
    dlopen a half-written .so or truncate one another process has mapped.
    """
    if not os.path.exists(_SRC):
        return False
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    try:
        os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
        subprocess.run(
            [
                "g++", "-std=c++17", "-O2", "-fPIC", "-shared",
                "-o", tmp, _SRC,
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _LIB_PATH)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("TDX_DISABLE_NATIVE"):
            _load_failed = True
            return None
        if (not os.path.exists(_LIB_PATH) or _stale()) and not _try_build():
            _load_failed = True
            if not os.path.exists(_LIB_PATH):
                return None
            # Stale but rebuild failed: fall through and use the existing
            # library rather than silently losing the native path entirely.
            _load_failed = False
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _load_failed = True
            return None
        lib.tdx_graph_new.restype = ctypes.c_void_p
        lib.tdx_graph_free.argtypes = [ctypes.c_void_p]
        lib.tdx_graph_add_node.restype = ctypes.c_int
        lib.tdx_graph_add_node.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.tdx_graph_add_dep.restype = ctypes.c_int
        lib.tdx_graph_add_dep.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.tdx_graph_note_write.restype = ctypes.c_int
        lib.tdx_graph_note_write.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64,
        ]
        lib.tdx_graph_num_nodes.restype = ctypes.c_int64
        lib.tdx_graph_num_nodes.argtypes = [ctypes.c_void_p]
        lib.tdx_graph_call_stack.restype = ctypes.c_int64
        lib.tdx_graph_call_stack.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# Native stack utilities (_tdx_stack extension module — the stack_utils.cc
# analog; see src/cc/tdx_core/stack.cc)

_STACK_SRC = os.path.join(_REPO_ROOT, "src", "cc", "tdx_core", "stack.cc")
_STACK_LIB = os.path.join(_PKG_DIR, "lib", "_tdx_stack.so")

_stack_lock = threading.Lock()
_stack_mod = None
_stack_failed = False


def _try_build_stack() -> bool:
    import sysconfig

    if not os.path.exists(_STACK_SRC):
        return False
    include = sysconfig.get_paths()["include"]
    tmp = f"{_STACK_LIB}.{os.getpid()}.tmp"
    try:
        os.makedirs(os.path.dirname(_STACK_LIB), exist_ok=True)
        subprocess.run(
            [
                "g++", "-std=c++17", "-O2", "-fPIC", "-shared",
                f"-I{include}", f"-I{os.path.dirname(_SRC)}",
                "-o", tmp, _STACK_SRC, _SRC,
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _STACK_LIB)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _stale_stack() -> bool:
    # The extension links the graph engine (graph.cc) in — either source
    # being newer triggers a rebuild.
    try:
        lib_mtime = os.path.getmtime(_STACK_LIB)
        return (
            os.path.getmtime(_STACK_SRC) > lib_mtime
            or os.path.getmtime(_SRC) > lib_mtime
        )
    except OSError:
        return True


def stack_ops():
    """The native stack-utils module, or None (pytree fallback).

    On first use, registers ``torch.Tensor`` plus the immutable leaf domain
    (the validation analog of deferred_init.cc:227-253) with the extension.
    """
    global _stack_mod, _stack_failed
    if _stack_mod is not None or _stack_failed:
        return _stack_mod
    with _stack_lock:
        if _stack_mod is not None or _stack_failed:
            return _stack_mod
        if os.environ.get("TDX_DISABLE_NATIVE"):
            _stack_failed = True
            return None
        if (not os.path.exists(_STACK_LIB) or _stale_stack()) \
                and not _try_build_stack():
            if not os.path.exists(_STACK_LIB):
                _stack_failed = True
                return None
        try:
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "_tdx_stack", _STACK_LIB
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception:
            _stack_failed = True
            return None
        import torch

        mod.register_types(
            torch.Tensor,
            (
                torch.dtype, torch.device, torch.layout,
                torch.memory_format, torch.Generator,
            ),
        )
        _stack_mod = mod
        return _stack_mod


class NativeGraph:
    """Owning handle over a tdx_graph, plus the op_nr → OpNode registry the
    Python side needs to map native schedules back to payloads.

    The registry holds nodes *weakly*: every node a call-stack traversal can
    return is also strongly reachable from the target through the Python
    graph edges (OutputRef deps / dependents lists), and a strong registry
    would pin the entire tape for as long as any single node survives —
    defeating the incremental freeing the weakref-based Python writers index
    provides.
    """

    def __init__(self):
        import weakref

        lib = _load()
        if lib is None:
            raise RuntimeError("native core unavailable")
        self._lib = lib
        self._ptr = lib.tdx_graph_new()
        self.nodes = weakref.WeakValueDictionary()  # op_nr -> OpNode

    def __del__(self):
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.tdx_graph_free(ptr)
            self._ptr = None

    def add_node(self, op_nr: int, node) -> None:
        self._lib.tdx_graph_add_node(self._ptr, op_nr)
        self.nodes[op_nr] = node

    def add_dep(self, op_nr: int, producer_op_nr: int) -> None:
        self._lib.tdx_graph_add_dep(self._ptr, op_nr, producer_op_nr)

    def note_write(self, op_nr: int, storage_key: int) -> None:
        self._lib.tdx_graph_note_write(
            self._ptr, op_nr, storage_key & 0xFFFFFFFFFFFFFFFF
        )

    def __len__(self) -> int:
        return int(self._lib.tdx_graph_num_nodes(self._ptr))

    def call_stack(self, target_op_nr: int) -> List[int]:
        # One traversal: the node count bounds the schedule size, so size
        # the buffer up front instead of a sizing call + a fill call.
        cap = int(self._lib.tdx_graph_num_nodes(self._ptr))
        buf = (ctypes.c_int64 * cap)()
        n = self._lib.tdx_graph_call_stack(self._ptr, target_op_nr, buf, cap)
        if n < 0:
            raise KeyError(f"unknown op_nr {target_op_nr}")
        return list(buf[:n])
