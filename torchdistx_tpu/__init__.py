"""torchdistx_tpu — a TPU-native framework with the capabilities of torchdistX.

Features (capability parity with /root/reference, rebuilt TPU-first):

* :mod:`torchdistx_tpu.fake` — fake tensors: storage-less tensors claiming a
  real (possibly absent) device, for zero-memory model construction.
* :mod:`torchdistx_tpu.deferred_init` — deferred module initialization: record
  construction into an op tape, inspect, then materialize.
* :mod:`torchdistx_tpu.materialize` — JAX/XLA materialization: replay the tape
  directly as (sharded) ``jax.Array`` parameters on a TPU mesh.
* :mod:`torchdistx_tpu.parallel` — mesh/sharding plans (FSDP/TP/DP/SP) and the
  SlowMo communication-efficient distributed optimizer over ICI/DCN axes.
* :mod:`torchdistx_tpu.models` — JAX-native model implementations used as
  training-step flagships.

JAX-dependent modules import lazily; ``import torchdistx_tpu`` itself only
needs torch.
"""

__version__ = "0.1.0.dev0"

# Like the reference (src/python/torchdistx/__init__.py), the package init
# stays minimal; features live in submodules (`torchdistx_tpu.fake`,
# `torchdistx_tpu.deferred_init`, ...).  Re-exporting the `deferred_init`
# function here would shadow its submodule.
from . import fake  # noqa: F401
from . import deferred_init  # noqa: F401
from .fake import FakeTensor, fake_mode, is_fake, meta_like  # noqa: F401
