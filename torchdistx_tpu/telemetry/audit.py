"""Audit plane: determinism digests, shadow auditing, divergence latching.

Every hard guarantee the serving stack makes — crash-recovery replay,
QoS preempt-and-resume, fleet failover, hot swap — rests on ONE
invariant: a replay under the ``fold_in(key, n_gen)`` sampling schedule
is **token-identical** to the uninterrupted run.  Until now that
invariant was verified only in tests and chaos soaks; in production it
was unobservable.  This module makes determinism itself a continuously
measured signal:

* :class:`DeterminismDigest` — a rolling blake2b over *(admitted
  prompt, sampling-key schedule, model version, committed token ids)*.
  Every request carries one, updated as tokens commit at chunk
  boundaries; its hex snapshot is stamped into the ``req.first_token``
  and ``req.finished`` lifecycle events (and therefore ``/requests``
  and every flight dump).  The update rule is **per token** — version
  bytes then the token's little-endian bytes — so a digest computed
  from chunked engine commits, a per-token fleet stream, or a flat
  list recomputation all agree bit-for-bit.  Verification against a
  digest is O(1) memory where the pre-audit stack compared buffered
  token lists element-by-element:

  - the **fleet failover prefix check** (``FleetHandle.tokens()``)
    re-hashes the replacement stream's prefix and compares ONE digest
    against the committed one — and because the engine's
    ``model_version`` folds into every token, a deliberately
    version-mixed replay is rejected even when the token ids happen to
    agree;
  - **preempt/replay resume** (drop-and-replay ``_complete_prefill``,
    ``_swap_in_phase``, the crash-recovery supervisor) re-hashes the
    committed stream before feeding it back to the model, so a
    corrupted host-side token buffer can never silently poison a
    resume.

* :class:`ShadowAuditor` — an opt-in (``Engine(audit_sample=p)`` /
  ``TDX_AUDIT_SAMPLE``) background auditor that re-executes a sampled
  fraction of *completed* requests through the engine's own chunked
  prefill + decode programs (zero new compiled geometries) at the
  lowest QoS class, only on ticks where no user work waits.  The
  replay's digest must equal the original's; a mismatch bumps
  ``audit.divergences``, latches the engine's
  ``serve.diverging{engine=}`` gauge (the engine reads OVERLOADED so a
  fleet router routes around it, exactly like a stall or a recompile
  storm — but the latch does NOT self-clear: determinism breaks need a
  human, see :meth:`~torchdistx_tpu.serving.engine.Engine
  .clear_divergence`), and flight-dumps ``reason="divergence"``
  carrying BOTH token streams — the input
  ``scripts/incident_replay.py`` bisects to the first diverging chunk.

* :func:`record_divergence` — the one funnel every divergence
  (auditor mismatch, resume-verification failure) goes through:
  counter + latch + flight dump.

Metrics (docs/observability.md, "Audit plane"): ``audit.checked``,
``audit.divergences``, ``audit.dropped``, ``audit.aborted`` counters
and the per-engine ``serve.diverging{engine=}`` latch gauge.

Like the rest of :mod:`torchdistx_tpu.telemetry`, this module imports
nothing heavy at module level (numpy/jax load lazily inside the
functions that need them) and costs nothing when unused.
"""

from __future__ import annotations

import hashlib
import os
from collections import deque
from typing import Any, Dict, List, Optional

from . import _core as _telemetry

__all__ = [
    "AUDIT_PRIORITY",
    "DeterminismDigest",
    "ShadowAuditor",
    "canonical_key",
    "env_audit_sample",
    "first_divergence",
    "record_divergence",
    "token_chunk",
]

# The shadow auditor's QoS class: strictly below any sane user
# priority, so an audit replay can never preempt (or outqueue) real
# work on a QoS engine.  Inert under the FIFO scheduler — there the
# auditor's only-when-quiet pump is the whole protection.
AUDIT_PRIORITY = -(2**30)

_T_CHECKED = _telemetry.counter("audit.checked")
_T_DIVERGENCES = _telemetry.counter("audit.divergences")
_T_DROPPED = _telemetry.counter("audit.dropped")
_T_ABORTED = _telemetry.counter("audit.aborted")


def env_audit_sample() -> Optional[float]:
    """``TDX_AUDIT_SAMPLE`` as a float in [0, 1], or None when unset.
    A malformed value raises — a mistyped sampling rate silently
    auditing nothing would defeat the whole plane (the ``TDX_FAULT``
    grammar philosophy)."""
    text = os.environ.get("TDX_AUDIT_SAMPLE", "")
    if not text:
        return None
    try:
        value = float(text)
    except ValueError:
        raise ValueError(
            f"TDX_AUDIT_SAMPLE={text!r}: expected a float in [0, 1]"
        ) from None
    if not 0.0 <= value <= 1.0:
        raise ValueError(
            f"TDX_AUDIT_SAMPLE={value}: expected a fraction in [0, 1]"
        )
    return value


def canonical_key(key: Any):
    """The engine's key normalization, importable: an int seed becomes
    ``jax.random.PRNGKey(seed)``, anything array-like becomes the
    ``(2,) uint32`` raw key — so a digest seeded anywhere (engine,
    fleet handle, incident replay) hashes the same bytes for the same
    ``submit(key=...)`` argument."""
    import numpy as np

    if isinstance(key, (int, np.integer)):
        import jax

        key = jax.random.PRNGKey(int(key))
    return np.asarray(key).astype(np.uint32).reshape(2)


def _prompt_bytes(prompt) -> bytes:
    import numpy as np

    return np.ascontiguousarray(prompt, dtype="<i4").tobytes()


def _key_bytes(key) -> bytes:
    import numpy as np

    return np.ascontiguousarray(key, dtype="<u4").tobytes()


class DeterminismDigest:
    """Rolling blake2b over one request's deterministic identity.

    Seeded with the admitted prompt's token bytes and the normalized
    sampling key (the key IS the schedule: every sampling step derives
    ``fold_in(key, n_gen)`` from it); updated per committed token with
    the serving engine's ``model_version`` bytes followed by the token
    id.  Chunk-size invariant by construction, O(1) state however long
    the stream, and snapshot-able at any point (``hexdigest`` copies
    the hash state — the rolling digest keeps accumulating)."""

    __slots__ = ("_h", "n")

    def __init__(self, prompt, key):
        h = hashlib.blake2b(digest_size=16)
        h.update(_prompt_bytes(prompt))
        h.update(_key_bytes(key))
        self._h = h
        self.n = 0  # committed tokens folded in so far

    def update(self, tokens, version: str = "v0") -> None:
        """Fold committed token ids in (one call per chunk boundary in
        the engine; one call per token on the fleet's verify path —
        identical result either way)."""
        v = str(version).encode()
        h = self._h
        for tok in tokens:
            h.update(v)
            h.update(int(tok).to_bytes(8, "little", signed=True))
            self.n += 1

    def hexdigest(self) -> str:
        """Snapshot of the digest so far (the stream keeps rolling)."""
        return self._h.copy().hexdigest()

    @classmethod
    def of_stream(
        cls, prompt, key, tokens, version: str = "v0"
    ) -> "DeterminismDigest":
        """The digest a single-engine stream of ``tokens`` would carry."""
        d = cls(prompt, key)
        d.update(tokens, version)
        return d

    def matches_stream(
        self, prompt, key, tokens, version: str = "v0"
    ) -> bool:
        """O(1)-memory verification that this digest covers exactly
        ``tokens`` (the preempt/replay resume check: re-hash the
        committed buffer, compare one digest — never compare lists)."""
        return (
            self.of_stream(prompt, key, tokens, version).hexdigest()
            == self.hexdigest()
        )


def first_divergence(expected: List[int], got: List[int]) -> int:
    """Index of the first differing token between two streams (the
    shorter stream's end when one is a strict prefix of the other)."""
    n = min(len(expected), len(got))
    for i in range(n):
        if int(expected[i]) != int(got[i]):
            return i
    return n


def token_chunk(index: int, decode_chunk: int) -> int:
    """Map a per-request token index onto the chunk that committed it:
    token 0 is the prefill's first-token sample (chunk 0); decode chunk
    ``j`` (1-based) commits tokens ``1 + (j-1)*decode_chunk ..
    j*decode_chunk``."""
    if index <= 0:
        return 0
    return 1 + (index - 1) // max(1, int(decode_chunk))


def record_divergence(engine, **detail) -> None:
    """The one divergence funnel: bump ``audit.divergences``, latch the
    engine (``serve.diverging{engine=}`` + OVERLOADED so routers route
    around), and flight-dump ``reason="divergence"`` with the caller's
    forensics (both token streams, digests, first diverging chunk)."""
    _T_DIVERGENCES.add()
    mark = getattr(engine, "_mark_diverging", None)
    if mark is not None:
        mark()
    _telemetry.flight_dump(
        "divergence", engine=getattr(engine, "engine_id", None), **detail
    )


class _AuditRecord:
    """One completed request's identity, queued for shadow re-execution."""

    __slots__ = (
        "trace_id", "rid", "prompt", "key", "max_new", "digest", "tokens",
        "model_tag",
    )

    def __init__(self, req, engine_id: str):
        self.trace_id = req.trace_id or f"{engine_id}-r{req.rid}"
        self.rid = req.rid
        self.prompt = req.prompt
        self.key = req.key
        self.max_new = req.max_new_tokens
        self.digest = req.digest.hexdigest()
        self.tokens = list(req.handle._tokens)
        # Model-plane identity: a replay must run the SAME weights (the
        # model_version folds into every token of the digest, so a
        # wrong-model replay reads as a divergence, not a pass).
        self.model_tag = getattr(req, "model_tag", "default")


class ShadowAuditor:
    """Re-execute a sampled fraction of completed requests and compare
    determinism digests (docs/observability.md, "Audit plane").

    Owned by one engine.  ``on_finished`` (called by the engine at
    every retirement) either enqueues the finished request for audit
    (sampling is deterministic off the request's own digest, so a
    replayed trace samples the same requests) or — when the finished
    request IS an audit replay — compares digests and routes any
    mismatch through :func:`record_divergence`.  ``pump`` (called once
    per engine tick) submits at most one pending audit, and only while
    the engine's own queue is empty: shadow traffic must never delay,
    shed, or preempt user work.  An audit replay goes through the
    ordinary ``submit`` path — same chunked prefill, same decode chunk,
    same prefix cache — so auditing compiles **zero** new geometries.

    The pending queue is bounded (``max_pending``): under sustained
    saturation the oldest un-started audits drop (``audit.dropped``)
    rather than growing host memory — coverage degrades, correctness
    doesn't.  Audit replays killed by a drain/close/shed fail with
    their typed errors like any request and are counted
    ``audit.aborted``, never as divergences."""

    def __init__(
        self,
        engine,
        sample: float,
        *,
        priority: int = AUDIT_PRIORITY,
        max_pending: int = 32,
    ):
        sample = float(sample)
        if not 0.0 <= sample <= 1.0:
            raise ValueError(
                f"audit_sample {sample}: expected a fraction in [0, 1]"
            )
        self.engine = engine
        self.sample = sample
        self.priority = int(priority)
        self.max_pending = int(max_pending)
        self._pending: deque = deque()
        self._inflight: Dict[int, tuple] = {}  # audit rid -> (record, handle)
        self.checked = 0
        self.divergences = 0
        self.dropped = 0
        self.aborted = 0
        self.divergence_detail: List[Dict[str, Any]] = []

    # -- engine hooks -------------------------------------------------------

    def backlog(self) -> int:
        """Audits not yet submitted (in-flight ones occupy the engine's
        own queue/slots and are visible there)."""
        return len(self._pending)

    def on_finished(self, req) -> None:
        """Every retirement lands here: enqueue user requests (sampled),
        settle audit replays."""
        if req.audit_of is not None:
            self._compare(req)
            return
        if self.sample <= 0.0 or req.digest is None:
            return
        if not self._sampled(req):
            return
        if len(self._pending) >= self.max_pending:
            self._pending.popleft()
            self.dropped += 1
            _T_DROPPED.add()
        self._pending.append(_AuditRecord(req, self.engine.engine_id))

    def pump(self) -> None:
        """One engine tick's worth of audit progress: reap failed
        replays, then submit at most one pending audit if the engine is
        quiet (empty queue; health still serving)."""
        if self._inflight:
            self._reap_failed()
        if not self._pending:
            return
        eng = self.engine
        if eng.health().value not in ("starting", "ready", "overloaded"):
            # Draining/stopped: these audits will never run.
            self._pending.clear()
            return
        if len(eng.scheduler):
            return  # user work waiting — shadow traffic yields
        rec = self._pending[0]
        try:
            handle = eng.submit(
                rec.prompt,
                max_new_tokens=rec.max_new,
                key=rec.key,
                tenant="_audit",
                priority=self.priority,
                model=None if rec.model_tag == "default" else rec.model_tag,
                _audit_of=rec.trace_id,
            )
        except Exception:  # noqa: BLE001 — overloaded/draining: retry later
            return
        self._pending.popleft()
        self._inflight[handle.rid] = (rec, handle)

    # -- internals ----------------------------------------------------------

    def _sampled(self, req) -> bool:
        if self.sample >= 1.0:
            return True
        # Deterministic per request: the digest's leading 32 bits as a
        # uniform draw — a replayed trace audits the same requests.
        draw = int(req.digest.hexdigest()[:8], 16) / float(0xFFFFFFFF)
        return draw < self.sample

    def _reap_failed(self) -> None:
        for rid in [
            rid
            for rid, (_, handle) in self._inflight.items()
            if handle.done and handle.error is not None
        ]:
            self._inflight.pop(rid)
            self.aborted += 1
            _T_ABORTED.add()

    def _compare(self, req) -> None:
        entry = self._inflight.pop(req.rid, None)
        if entry is None:
            return
        rec, _ = entry
        self.checked += 1
        _T_CHECKED.add()
        got = req.digest.hexdigest()
        if got == rec.digest:
            return
        self.divergences += 1
        replayed = list(req.handle._tokens)
        idx = first_divergence(rec.tokens, replayed)
        detail = {
            "rid": rec.trace_id,
            "audit_rid": req.trace_id,
            "expected_digest": rec.digest,
            "replayed_digest": got,
            "expected_tokens": rec.tokens,
            "replayed_tokens": replayed,
            "first_diverging_token": idx,
            "first_diverging_chunk": token_chunk(
                idx, getattr(self.engine, "decode_chunk", 1)
            ),
        }
        self.divergence_detail.append(detail)
        record_divergence(self.engine, **detail)
