"""Telemetry core: spans, counters, gauges, and sinks.

Dependency-free by design (stdlib only, no torch/jax imports at module
level): `_tape.py`'s per-op record path and `materialize.py`'s phase
boundaries bind counters/spans at import time, so this module must be
importable before either torch or jax and must cost nothing when disabled.

Three primitives:

* :func:`span` / :func:`start_span` — nested, thread-aware timed regions.
  A span *always* measures (two ``perf_counter`` calls — this is how
  ``materialize.last_profile`` keeps working with telemetry off) but only
  *records* when a sink is active: no record dict, no string formatting,
  no JSON when disabled.
* :func:`counter` / :func:`gauge` — named registries of monotonic counts
  and last-value gauges.  Counters always accumulate (they are the
  process-introspection layer, like ``materialize.exec_cache_hits``);
  each carries its own lock so concurrent materialization build pools and
  multi-threaded recorders count exactly.
* sinks — the in-memory collector (bounded deque, queryable via
  :func:`snapshot`/:func:`drain`), a JSON-lines exporter
  (``TDX_TELEMETRY=/path/trace.jsonl`` or ``configure(jsonl=...)``), and
  optional ``jax.profiler`` annotation pass-through
  (``TDX_TELEMETRY_JAX=1``) so spans appear in XLA profiler traces.

Environment (read once, at first telemetry use; :func:`configure` wins):

* ``TDX_TELEMETRY=/path/trace.jsonl`` — enable the JSONL exporter AND the
  in-memory collector.
* ``TDX_TELEMETRY_JAX=1`` — wrap spans in ``jax.profiler``
  ``TraceAnnotation`` (or ``StepTraceAnnotation`` when the span carries a
  ``step`` attribute).
* ``TDX_NO_TELEMETRY=1`` — kill switch: no sink activates regardless of
  the above.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "Span",
    "configure",
    "counter",
    "counters",
    "drain",
    "emit_counters",
    "enabled",
    "gauge",
    "gauges",
    "reset",
    "snapshot",
    "span",
    "start_span",
]

_logger = logging.getLogger(__name__)

_REG_LOCK = threading.Lock()
_tls = threading.local()

_DEFAULT_MAX_SPANS = 4096


class Counter:
    """Monotonic named count.  ``add`` is thread-exact (own lock) and, when
    no sink is ever read, costs one lock round-trip + an int add."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def __repr__(self):
        return f"Counter({self.name}={self._value})"


class Gauge:
    """Last-value named gauge (floats or ints)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value: Any = None

    def set(self, value) -> None:
        self._value = value

    @property
    def value(self):
        return self._value

    def __repr__(self):
        return f"Gauge({self.name}={self._value})"


class _State:
    """Process-wide telemetry configuration + sinks (lazily env-seeded)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.initialized = False
        self.collect = False
        self.jsonl_path: Optional[str] = None
        self.jax_annotations = False
        self.max_spans = _DEFAULT_MAX_SPANS
        self.spans: deque = deque(maxlen=_DEFAULT_MAX_SPANS)
        self.jsonl_file = None
        self.jsonl_lock = threading.Lock()
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}

    # -- configuration ------------------------------------------------------

    def ensure_init(self) -> None:
        if self.initialized:
            return
        with self.lock:
            if self.initialized:
                return
            self.initialized = True
            if os.environ.get("TDX_NO_TELEMETRY"):
                return
            path = os.environ.get("TDX_TELEMETRY")
            if path:
                self.jsonl_path = path
                self.collect = True
            if os.environ.get("TDX_TELEMETRY_JAX"):
                self.jax_annotations = True

    def jsonl_handle(self):
        """Lazily opened append-mode handle; a failed open disables the
        sink (telemetry must never fail the instrumented operation)."""
        if self.jsonl_path is None:
            return None
        if self.jsonl_file is None:
            with self.jsonl_lock:
                if self.jsonl_file is None and self.jsonl_path is not None:
                    try:
                        self.jsonl_file = open(  # noqa: SIM115 — held open
                            self.jsonl_path, "a", encoding="utf-8"
                        )
                    except OSError as e:
                        _logger.warning(
                            "telemetry: cannot open %s (%s); JSONL sink "
                            "disabled", self.jsonl_path, e,
                        )
                        self.jsonl_path = None
                        return None
        return self.jsonl_file

    def close_jsonl(self) -> None:
        with self.jsonl_lock:
            if self.jsonl_file is not None:
                try:
                    self.jsonl_file.close()
                except OSError:
                    pass
                self.jsonl_file = None

    # -- emission -----------------------------------------------------------

    def active(self) -> bool:
        return self.collect or self.jsonl_path is not None

    def record(self, rec: Dict[str, Any]) -> None:
        if self.collect:
            self.spans.append(rec)
        self.write_jsonl(rec)

    def write_jsonl(self, rec: Dict[str, Any]) -> None:
        f = self.jsonl_handle()
        if f is None:
            return
        try:
            line = json.dumps(rec, default=str)
        except (TypeError, ValueError):
            line = json.dumps({k: str(v) for k, v in rec.items()})
        with self.jsonl_lock:
            try:
                f.write(line + "\n")
                f.flush()
            except (OSError, ValueError):
                # Closed/full file: drop the sink, keep the program.
                self.jsonl_path = None


_state = _State()


def _span_stack() -> List["Span"]:
    stack = getattr(_tls, "spans", None)
    if stack is None:
        stack = _tls.spans = []
    return stack


class Span:
    """One timed region.  Use as a context manager (``with span(...)``) or
    via :func:`start_span` + :meth:`end` when the region doesn't nest as a
    ``with`` block (materialize's phase boundaries).

    ``end`` is idempotent — the first call fixes the duration; later calls
    return it unchanged.  The thread-local nesting stack is popped by
    identity and tolerates imbalance (an exception that skips an ``end``
    cannot corrupt later spans' parentage).
    """

    __slots__ = (
        "name", "attrs", "t0", "ts", "duration", "parent", "depth",
        "_annotation", "_recorded",
    )

    def __init__(self, name: str, attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0
        self.ts = 0.0
        self.duration: Optional[float] = None
        self.parent: Optional[str] = None
        self.depth = 0
        self._annotation = None
        self._recorded = False

    def start(self) -> "Span":
        stack = _span_stack()
        if len(stack) > 128:
            # Safety valve: spans abandoned by exceptions (an instrumented
            # operation that raised between start and end) accumulate here;
            # genuine nesting never goes this deep.  Reset rather than let
            # parent attribution degrade without bound.
            for sp in stack:
                sp._close_annotation()
            stack.clear()
        if stack:
            self.parent = stack[-1].name
            self.depth = len(stack)
        stack.append(self)
        if _state.jax_annotations:
            self._enter_annotation()
        self.ts = time.time()
        self.t0 = time.perf_counter()
        return self

    def end(self, **attrs) -> float:
        if self.duration is None:
            self.duration = time.perf_counter() - self.t0
        if attrs:
            self.attrs = {**(self.attrs or {}), **attrs}
        stack = getattr(_tls, "spans", None)
        if stack and self in stack:
            # Identity pop, tolerating spans above us abandoned by
            # exceptions — but their profiler annotations must still exit
            # (innermost first, before ours) or the thread's TraceMe stack
            # goes permanently unbalanced.
            while stack:
                top = stack.pop()
                if top is self:
                    break
                top._close_annotation()
        self._close_annotation()
        if not self._recorded and _state.active():
            self._recorded = True
            rec = {
                "type": "span",
                "name": self.name,
                "ts": self.ts,
                "dur_s": self.duration,
                "thread": threading.get_ident(),
                "depth": self.depth,
            }
            if self.parent is not None:
                rec["parent"] = self.parent
            if self.attrs:
                rec["attrs"] = self.attrs
            _state.record(rec)
        return self.duration

    def cancel(self) -> None:
        """Close the span without recording it (a phase that turned out
        not to apply).  Timing state is finalized; sinks see nothing."""
        self._recorded = True
        self.end()

    def _close_annotation(self) -> None:
        if self._annotation is not None:
            try:
                self._annotation.__exit__(None, None, None)
            except Exception:  # noqa: BLE001 — profiler teardown best-effort
                pass
            self._annotation = None

    def _enter_annotation(self) -> None:
        # jax.profiler pass-through: spans show up in XLA profiler traces
        # (TensorBoard / xprof).  A `step` attribute selects the step-level
        # annotation the profiler's step view keys on.
        try:
            from jax.profiler import StepTraceAnnotation, TraceAnnotation

            attrs = self.attrs or {}
            if "step" in attrs:
                self._annotation = StepTraceAnnotation(
                    self.name, step_num=attrs["step"]
                )
            else:
                self._annotation = TraceAnnotation(self.name)
            self._annotation.__enter__()
        except Exception:  # noqa: BLE001 — no jax / old jax: spans still time
            self._annotation = None

    def __enter__(self) -> "Span":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.end()
        return False


# ---------------------------------------------------------------------------
# Public API


def configure(
    *,
    jsonl: Optional[str] = "__unset__",
    collect: Optional[bool] = None,
    jax_annotations: Optional[bool] = None,
    max_spans: Optional[int] = None,
) -> Dict[str, Any]:
    """Set telemetry sinks programmatically (overrides the env defaults).

    Returns the PREVIOUS settings as a kwargs dict, so a caller (tests,
    a bench scope) can restore them: ``prev = configure(collect=True)``
    ... ``configure(**prev)``.
    """
    _state.ensure_init()
    with _state.lock:
        prev = {
            "jsonl": _state.jsonl_path,
            "collect": _state.collect,
            "jax_annotations": _state.jax_annotations,
            "max_spans": _state.max_spans,
        }
        if jsonl != "__unset__":
            if jsonl != _state.jsonl_path:
                _state.close_jsonl()
            _state.jsonl_path = jsonl
        if collect is not None:
            _state.collect = collect
        if jax_annotations is not None:
            _state.jax_annotations = jax_annotations
        if max_spans is not None and max_spans != _state.max_spans:
            _state.max_spans = max_spans
            _state.spans = deque(_state.spans, maxlen=max_spans)
    return prev


def enabled() -> bool:
    """True when any span sink (collector/JSONL) is active."""
    _state.ensure_init()
    return _state.active()


def span(name: str, **attrs) -> Span:
    """Context-manager span: ``with span("materialize.compile", n=3): ...``.

    Always times; records to the active sinks on exit.  With
    ``TDX_TELEMETRY_JAX=1`` the region is annotated into XLA profiler
    traces (``step=`` attribute → ``StepTraceAnnotation``).
    """
    _state.ensure_init()
    return Span(name, attrs or None)


def start_span(name: str, **attrs) -> Span:
    """Manual-boundary span: ``sp = start_span(...); ...; sp.end()``."""
    _state.ensure_init()
    return Span(name, attrs or None).start()


def counter(name: str) -> Counter:
    """Get-or-create the named counter (bind once at module level on hot
    paths — the lookup takes the registry lock)."""
    c = _state.counters.get(name)
    if c is None:
        with _REG_LOCK:
            c = _state.counters.setdefault(name, Counter(name))
    return c


def gauge(name: str) -> Gauge:
    """Get-or-create the named gauge."""
    g = _state.gauges.get(name)
    if g is None:
        with _REG_LOCK:
            g = _state.gauges.setdefault(name, Gauge(name))
    return g


def counters() -> Dict[str, int]:
    """Current counter values, name → count."""
    return {name: c.value for name, c in sorted(_state.counters.items())}


def gauges() -> Dict[str, Any]:
    """Current gauge values (unset gauges omitted)."""
    return {
        name: g.value
        for name, g in sorted(_state.gauges.items())
        if g.value is not None
    }


def snapshot() -> Dict[str, Any]:
    """The in-memory collector as a plain dict:
    ``{"counters": {...}, "gauges": {...}, "spans": [...]}``."""
    _state.ensure_init()
    return {
        "counters": counters(),
        "gauges": gauges(),
        "spans": list(_state.spans),
    }


def drain() -> List[Dict[str, Any]]:
    """Pop and return all collected span records (oldest first)."""
    _state.ensure_init()
    out = []
    try:
        while True:
            out.append(_state.spans.popleft())
    except IndexError:
        pass
    return out


def emit_counters() -> None:
    """Write one counters+gauges snapshot line to the JSONL sink (no-op
    without one).  Called at natural flush points — the end of each
    ``materialize_module_jax`` and at interpreter exit."""
    _state.ensure_init()
    if _state.jsonl_path is None:
        return
    _state.write_jsonl(
        {
            "type": "counters",
            "ts": time.time(),
            "values": counters(),
            "gauges": gauges(),
        }
    )


def reset() -> None:
    """Zero all counters/gauges and clear collected spans (tests).

    Values are zeroed IN PLACE — instrumented modules bind their Counter
    objects once at import, so dropping registry entries would leave them
    counting into objects :func:`counters` can no longer see."""
    with _REG_LOCK:
        for c in _state.counters.values():
            with c._lock:
                c._value = 0
        for g in _state.gauges.values():
            g._value = None
    _state.spans.clear()


def _flush_at_exit() -> None:  # pragma: no cover — interpreter teardown
    try:
        if _state.jsonl_path is not None and _state.counters:
            emit_counters()
        _state.close_jsonl()
    except Exception:  # noqa: BLE001
        pass


import atexit  # noqa: E402

atexit.register(_flush_at_exit)
