"""Telemetry core: spans, counters, gauges, and sinks.

Dependency-free by design (stdlib only, no torch/jax imports at module
level): `_tape.py`'s per-op record path and `materialize.py`'s phase
boundaries bind counters/spans at import time, so this module must be
importable before either torch or jax and must cost nothing when disabled.

Five primitives:

* :func:`span` / :func:`start_span` — nested, thread-aware timed regions.
  A span *always* measures (two ``perf_counter`` calls — this is how
  ``materialize.last_profile`` keeps working with telemetry off) but only
  *records* when a sink is active: no record dict, no string formatting,
  no JSON when disabled.  ``detached=True`` keeps a long-lived span off
  the thread's nesting stack (it times and records, but never becomes
  another span's parent — the serving engine's drain span, which stays
  open across arbitrary work, uses this).
* :func:`counter` / :func:`gauge` — named registries of monotonic counts
  and last-value gauges.  Counters always accumulate (they are the
  process-introspection layer, like ``materialize.exec_cache_hits``);
  each carries its own lock so concurrent materialization build pools and
  multi-threaded recorders count exactly.
* :func:`histogram` — fixed-bucket latency/size distributions: exact
  counts per bucket under one cheap lock, exact count/sum/min/max, and
  p50/p95/p99 readback interpolated within a bucket.  Like counters,
  histograms always accumulate (``Engine.stats()`` reads its percentiles
  from them) — no per-observation allocation, sink or no sink.
* :func:`event` — request-scoped lifecycle points (``req.submitted``,
  ``req.first_token``, ``req.failed`` ...) carrying the trace context
  ``rid``/``engine``/``hop``.  Zero cost when no sink and no flight
  recorder is active: the function returns before building any record.
* sinks — the in-memory collector (bounded deque, queryable via
  :func:`snapshot`/:func:`drain`), a JSON-lines exporter
  (``TDX_TELEMETRY=/path/trace.jsonl`` or ``configure(jsonl=...)``),
  optional ``jax.profiler`` annotation pass-through
  (``TDX_TELEMETRY_JAX=1``) so spans appear in XLA profiler traces, and
  the **flight recorder** — a bounded ring of recent span/event records
  kept even when no sink is active, dumped to JSONL by
  :func:`flight_dump` when a failure fires, so a post-mortem doesn't
  depend on having had full tracing enabled.

Metric *labels*: ``counter``/``gauge``/``histogram`` accept keyword
labels (``gauge("serve.health", engine="eng0")``) that canonicalize into
the registry name as ``serve.health{engine=eng0}`` — how N fleet
replicas in one process keep per-engine readings without clobbering the
process-global gauge.

Environment (read once, at first telemetry use; :func:`configure` wins):

* ``TDX_TELEMETRY=/path/trace.jsonl`` — enable the JSONL exporter AND the
  in-memory collector.
* ``TDX_TELEMETRY_JAX=1`` — wrap spans in ``jax.profiler``
  ``TraceAnnotation`` (or ``StepTraceAnnotation`` when the span carries a
  ``step`` attribute).
* ``TDX_FLIGHT_RECORDER=1`` — keep the flight-recorder ring, dumping into
  the main JSONL sink; ``=/path/flight.jsonl`` dumps to a dedicated file
  (and needs no ``TDX_TELEMETRY``).
* ``TDX_FLIGHT_CAPACITY=N`` — ring size in records (default 512).
* ``TDX_NO_TELEMETRY=1`` — kill switch: no sink activates regardless of
  the above.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from bisect import bisect_left
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence

__all__ = [
    "Histogram",
    "Span",
    "add_listener",
    "on_reset",
    "configure",
    "counter",
    "counters",
    "drain",
    "emit_counters",
    "enabled",
    "event",
    "events_enabled",
    "flight_dump",
    "flight_records",
    "gauge",
    "gauges",
    "histogram",
    "histograms",
    "registry_view",
    "remove",
    "remove_listener",
    "reset",
    "snapshot",
    "span",
    "start_span",
    "tracing",
]

_logger = logging.getLogger(__name__)

_REG_LOCK = threading.Lock()
_tls = threading.local()

_DEFAULT_MAX_SPANS = 4096


class Counter:
    """Monotonic named count.  ``add`` is thread-exact (own lock) and, when
    no sink is ever read, costs one lock round-trip + an int add."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def __repr__(self):
        return f"Counter({self.name}={self._value})"


class Gauge:
    """Last-value named gauge (floats or ints)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value: Any = None

    def set(self, value) -> None:
        self._value = value

    @property
    def value(self):
        return self._value

    def __repr__(self):
        return f"Gauge({self.name}={self._value})"


# Default bucket edges for latency histograms: 8 per decade, 100 µs to
# 100 s (50 buckets with the overflow).  Resolution is ~33% anywhere in
# the range — tight enough that a p99 readback is actionable, small
# enough that observe() is one bisect over a 49-tuple.
_LATENCY_BOUNDS = tuple(10.0 ** (-4 + i / 8.0) for i in range(49))


class Histogram:
    """Fixed-bucket distribution with exact counts and percentile readback.

    ``bounds`` are the bucket upper edges (strictly increasing); an
    observation lands in the first bucket whose edge is >= the value,
    values beyond the last edge in the overflow bucket.  ``observe`` is
    lock-cheap — one bisect over a tuple, then one lock round-trip for
    the count/sum/min/max updates — and allocates nothing, so it can sit
    on the serving hot path with every sink disabled (it is the
    always-on stats layer, like :class:`Counter`).

    Percentiles interpolate linearly inside the winning bucket and clamp
    to the exact observed min/max, so a readback is never outside the
    data; resolution is the bucket width (default ~33%).
    """

    __slots__ = (
        "name", "bounds", "_counts", "_count", "_sum", "_min", "_max",
        "_lock",
    )

    def __init__(self, name: str, bounds: Optional[Sequence[float]] = None):
        self.name = name
        self.bounds = tuple(float(b) for b in (bounds or _LATENCY_BOUNDS))
        if any(
            b2 <= b1 for b1, b2 in zip(self.bounds, self.bounds[1:])
        ) or not self.bounds:
            raise ValueError("histogram bounds must be strictly increasing")
        self._counts = [0] * (len(self.bounds) + 1)
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._lock = threading.Lock()

    def observe(self, value: float, n: int = 1) -> None:
        """Record ``value`` (``n`` times — one aggregated observation per
        decode chunk is how per-token time is fed without n calls)."""
        i = bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += n
            self._count += n
            self._sum += value * n
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, p: float) -> Optional[float]:
        """The p-th percentile (0..100), or None while empty."""
        with self._lock:
            total = self._count
            if total == 0:
                return None
            counts = list(self._counts)
            lo_obs, hi_obs = self._min, self._max
        target = max(1.0, p / 100.0 * total)
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = self.bounds[i - 1] if i > 0 else min(lo_obs, self.bounds[0])
                hi = self.bounds[i] if i < len(self.bounds) else hi_obs
                frac = (target - cum) / c
                v = lo + (hi - lo) * frac
                return min(max(v, lo_obs), hi_obs)
            cum += c
        return hi_obs  # pragma: no cover — unreachable (cum == total)

    def bucket_counts(self) -> tuple:
        """One consistent snapshot for exposition: ``(bounds, cumulative
        bucket counts, total count, sum)`` taken under the histogram's
        lock, so a concurrent ``observe`` can never tear the invariant
        the Prometheus format promises (the ``+Inf`` cumulative count
        equals ``_count``)."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
        cum = []
        running = 0
        for c in counts:
            running += c
            cum.append(running)
        return self.bounds, cum, total, s

    def summary(self) -> Dict[str, Any]:
        """``{count, sum, min, max, p50, p95, p99}`` (empty → count 0)."""
        if self._count == 0:
            return {"count": 0}
        return {
            "count": self._count,
            "sum": round(self._sum, 6),
            "min": self._min,
            "max": self._max,
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
        }

    def _zero(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None

    def __repr__(self):
        return f"Histogram({self.name}, n={self._count})"


def _label_escape(v: Any) -> str:
    """Escape a label VALUE for the canonical registry name.  Label
    values are free-form (per-user tenant ids reach ``gauge(...,
    tenant=...)``), so the structural characters of the ``name{k=v,...}``
    encoding must not collide with them — a tenant ``"a,b"`` must not
    parse back as two labels.  Percent-encodes exactly the structural
    set; ordinary values round-trip unchanged."""
    return (
        str(v)
        .replace("%", "%25")
        .replace(",", "%2C")
        .replace("=", "%3D")
        .replace("{", "%7B")
        .replace("}", "%7D")
    )


def _label_unescape(v: str) -> str:
    """Inverse of :func:`_label_escape` (exporters split first, then
    unescape each value)."""
    return (
        v
        .replace("%7D", "}")
        .replace("%7B", "{")
        .replace("%3D", "=")
        .replace("%2C", ",")
        .replace("%25", "%")
    )


def _labeled(name: str, labels: Dict[str, Any]) -> str:
    """Canonical registry name for a labeled metric:
    ``name{k1=v1,k2=v2}`` with keys sorted (values escaped via
    :func:`_label_escape`) — the same (name, labels) always resolves to
    the same instrument."""
    if not labels:
        return name
    inner = ",".join(
        f"{k}={_label_escape(labels[k])}" for k in sorted(labels)
    )
    return f"{name}{{{inner}}}"


class _State:
    """Process-wide telemetry configuration + sinks (lazily env-seeded)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.initialized = False
        self.collect = False
        self.jsonl_path: Optional[str] = None
        self.jax_annotations = False
        self.max_spans = _DEFAULT_MAX_SPANS
        self.spans: deque = deque(maxlen=_DEFAULT_MAX_SPANS)
        self.jsonl_file = None
        self.jsonl_lock = threading.Lock()
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, Histogram] = {}
        # Flight recorder: a bounded ring of recent records, kept even
        # with every sink off, dumped on demand (flight_dump).  None =
        # disabled.  flight_path None = dump into the main JSONL sink.
        self.flight: Optional[deque] = None
        self.flight_path: Optional[str] = None
        self.flight_capacity = 512
        # In-process record listeners (the ops plane's SLO monitor):
        # each gets every record as it is emitted.  A registered
        # listener counts as a recording target — events must be built
        # for it even with every sink and the flight ring off.
        self.listeners: List[Any] = []

    # -- configuration ------------------------------------------------------

    def ensure_init(self) -> None:
        if self.initialized:
            return
        with self.lock:
            if self.initialized:
                return
            self.initialized = True
            if os.environ.get("TDX_NO_TELEMETRY"):
                return
            path = os.environ.get("TDX_TELEMETRY")
            if path:
                self.jsonl_path = path
                self.collect = True
            if os.environ.get("TDX_TELEMETRY_JAX"):
                self.jax_annotations = True
            try:
                self.flight_capacity = int(
                    os.environ.get("TDX_FLIGHT_CAPACITY", self.flight_capacity)
                )
            except ValueError:
                pass
            flight = os.environ.get("TDX_FLIGHT_RECORDER", "")
            if flight and flight != "0":
                self.flight = deque(maxlen=self.flight_capacity)
                self.flight_path = None if flight == "1" else flight

    def jsonl_handle(self):
        """Lazily opened append-mode handle; a failed open disables the
        sink (telemetry must never fail the instrumented operation)."""
        if self.jsonl_path is None:
            return None
        if self.jsonl_file is None:
            with self.jsonl_lock:
                if self.jsonl_file is None and self.jsonl_path is not None:
                    try:
                        self.jsonl_file = open(  # noqa: SIM115 — held open
                            self.jsonl_path, "a", encoding="utf-8"
                        )
                    except OSError as e:
                        _logger.warning(
                            "telemetry: cannot open %s (%s); JSONL sink "
                            "disabled", self.jsonl_path, e,
                        )
                        self.jsonl_path = None
                        return None
        return self.jsonl_file

    def close_jsonl(self) -> None:
        with self.jsonl_lock:
            if self.jsonl_file is not None:
                try:
                    self.jsonl_file.close()
                except OSError:
                    pass
                self.jsonl_file = None

    # -- emission -----------------------------------------------------------

    def active(self) -> bool:
        return self.collect or self.jsonl_path is not None

    def recording(self) -> bool:
        """A record built now would land somewhere: a sink, the
        flight-recorder ring (which keeps collecting with every sink
        off — that is its whole point), or an in-process listener."""
        return (
            self.collect
            or self.jsonl_path is not None
            or self.flight is not None
            or bool(self.listeners)
        )

    def record(self, rec: Dict[str, Any]) -> None:
        if self.flight is not None:
            # Ring entries remember whether a main sink exported the
            # record as it happened: a dump into the main sink must
            # backfill the records captured while no sink was active
            # rather than assume the whole window already landed.
            self.flight.append((self.active(), rec))
        if self.collect:
            self.spans.append(rec)
        self.write_jsonl(rec)
        for fn in list(self.listeners):
            try:
                fn(rec)
            except Exception:  # noqa: BLE001 — telemetry never fails the op
                _logger.warning(
                    "telemetry: record listener %r raised", fn, exc_info=True
                )

    def write_jsonl(self, rec: Dict[str, Any]) -> None:
        f = self.jsonl_handle()
        if f is None:
            return
        try:
            line = json.dumps(rec, default=str)
        except (TypeError, ValueError):
            line = json.dumps({k: str(v) for k, v in rec.items()})
        with self.jsonl_lock:
            try:
                f.write(line + "\n")
                f.flush()
            except (OSError, ValueError):
                # Closed/full file: drop the sink, keep the program.
                self.jsonl_path = None


_state = _State()


def _span_stack() -> List["Span"]:
    stack = getattr(_tls, "spans", None)
    if stack is None:
        stack = _tls.spans = []
    return stack


class Span:
    """One timed region.  Use as a context manager (``with span(...)``) or
    via :func:`start_span` + :meth:`end` when the region doesn't nest as a
    ``with`` block (materialize's phase boundaries).

    ``end`` is idempotent — the first call fixes the duration; later calls
    return it unchanged.  The thread-local nesting stack is popped by
    identity and tolerates imbalance (an exception that skips an ``end``
    cannot corrupt later spans' parentage).

    **Thread ownership**: the nesting stack belongs to the thread that
    *started* the span, and only that thread ever mutates it — a span
    ended on another thread (an engine's drain span finalized by a
    reaper, a handle pulled from a worker) records normally but leaves
    the owner's stack alone; the owner prunes finished spans off its
    stack top at its next ``start``.  Two threads can therefore never
    race one list, and depth/parent accounting stays exact under
    concurrent load (the PR 1 collector corrupted depths when a span
    crossed threads).

    ``detached=True`` keeps a long-lived span off the stack entirely: it
    times and records but never parents another span — for regions that
    stay open across arbitrary foreign work (the serving engine's drain
    span).
    """

    __slots__ = (
        "name", "attrs", "t0", "ts", "duration", "parent", "depth",
        "detached", "ctx", "_annotation", "_recorded", "_stack",
    )

    def __init__(
        self,
        name: str,
        attrs: Optional[Dict[str, Any]] = None,
        detached: bool = False,
    ):
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0
        self.ts = 0.0
        self.duration: Optional[float] = None
        self.parent: Optional[str] = None
        self.depth = 0
        self.detached = detached
        self.ctx: Optional[Dict[str, Any]] = None
        self._annotation = None
        self._recorded = False
        self._stack: Optional[List["Span"]] = None

    def start(self) -> "Span":
        self.ctx = _current_ctx()
        if not self.detached:
            stack = _span_stack()
            # Spans ended on ANOTHER thread could not pop this stack
            # (only the owner mutates it); they are finished, so they
            # must not become parents — prune them off the top now.
            while stack and stack[-1].duration is not None:
                stack.pop()
            if len(stack) > 128:
                # Safety valve: spans abandoned by exceptions (an
                # instrumented operation that raised between start and
                # end) accumulate here; genuine nesting never goes this
                # deep.  Reset rather than let parent attribution degrade
                # without bound.
                for sp in stack:
                    sp._close_annotation()
                stack.clear()
            if stack:
                self.parent = stack[-1].name
                self.depth = len(stack)
            stack.append(self)
            self._stack = stack
        if _state.jax_annotations:
            self._enter_annotation()
        self.ts = time.time()
        self.t0 = time.perf_counter()
        return self

    def end(self, **attrs) -> float:
        if self.duration is None:
            self.duration = time.perf_counter() - self.t0
        if attrs:
            self.attrs = {**(self.attrs or {}), **attrs}
        stack = getattr(_tls, "spans", None)
        if stack is not None and stack is self._stack and self in stack:
            # We are on the OWNING thread (its stack is this span's
            # stack): identity pop, tolerating spans above us abandoned
            # by exceptions — but their profiler annotations must still
            # exit (innermost first, before ours) or the thread's TraceMe
            # stack goes permanently unbalanced.  On any other thread the
            # stack is left alone — the owner prunes us (duration is now
            # set) at its next start().
            while stack:
                top = stack.pop()
                if top is self:
                    break
                top._close_annotation()
        self._close_annotation()
        if not self._recorded and _state.recording():
            self._recorded = True
            rec = {
                "type": "span",
                "name": self.name,
                "ts": self.ts,
                "dur_s": self.duration,
                "thread": threading.get_ident(),
                "depth": self.depth,
            }
            if self.parent is not None:
                rec["parent"] = self.parent
            if self.ctx:
                rec.update(self.ctx)
            if self.attrs:
                rec["attrs"] = self.attrs
            _state.record(rec)
        return self.duration

    def cancel(self) -> None:
        """Close the span without recording it (a phase that turned out
        not to apply).  Timing state is finalized; sinks see nothing."""
        self._recorded = True
        self.end()

    def _close_annotation(self) -> None:
        if self._annotation is not None:
            try:
                self._annotation.__exit__(None, None, None)
            except Exception:  # noqa: BLE001 — profiler teardown best-effort
                pass
            self._annotation = None

    def _enter_annotation(self) -> None:
        # jax.profiler pass-through: spans show up in XLA profiler traces
        # (TensorBoard / xprof).  A `step` attribute selects the step-level
        # annotation the profiler's step view keys on.
        try:
            from jax.profiler import StepTraceAnnotation, TraceAnnotation

            attrs = self.attrs or {}
            if "step" in attrs:
                self._annotation = StepTraceAnnotation(
                    self.name, step_num=attrs["step"]
                )
            else:
                self._annotation = TraceAnnotation(self.name)
            self._annotation.__enter__()
        except Exception:  # noqa: BLE001 — no jax / old jax: spans still time
            self._annotation = None

    def __enter__(self) -> "Span":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.end()
        return False


# ---------------------------------------------------------------------------
# Public API


def configure(
    *,
    jsonl: Optional[str] = "__unset__",
    collect: Optional[bool] = None,
    jax_annotations: Optional[bool] = None,
    max_spans: Optional[int] = None,
    flight: Any = "__unset__",
    flight_capacity: Optional[int] = None,
) -> Dict[str, Any]:
    """Set telemetry sinks programmatically (overrides the env defaults).

    ``flight``: ``False``/``None`` disables the flight recorder, ``True``
    keeps the ring and dumps into the main JSONL sink, a path string
    dumps to that dedicated file.  ``flight_capacity`` resizes the ring
    (recent records kept).

    Returns the PREVIOUS settings as a kwargs dict, so a caller (tests,
    a bench scope) can restore them: ``prev = configure(collect=True)``
    ... ``configure(**prev)``.
    """
    _state.ensure_init()
    with _state.lock:
        prev = {
            "jsonl": _state.jsonl_path,
            "collect": _state.collect,
            "jax_annotations": _state.jax_annotations,
            "max_spans": _state.max_spans,
            "flight": (
                (_state.flight_path or True)
                if _state.flight is not None
                else None
            ),
            "flight_capacity": _state.flight_capacity,
        }
        if jsonl != "__unset__":
            if jsonl != _state.jsonl_path:
                _state.close_jsonl()
            _state.jsonl_path = jsonl
        if collect is not None:
            _state.collect = collect
        if jax_annotations is not None:
            _state.jax_annotations = jax_annotations
        if max_spans is not None and max_spans != _state.max_spans:
            _state.max_spans = max_spans
            _state.spans = deque(_state.spans, maxlen=max_spans)
        if flight_capacity is not None:
            _state.flight_capacity = int(flight_capacity)
            if _state.flight is not None:
                _state.flight = deque(
                    _state.flight, maxlen=_state.flight_capacity
                )
        if flight != "__unset__":
            if not flight:
                _state.flight = None
                _state.flight_path = None
            else:
                if _state.flight is None:
                    _state.flight = deque(maxlen=_state.flight_capacity)
                _state.flight_path = (
                    None if flight is True else str(flight)
                )
    return prev


def enabled() -> bool:
    """True when any span sink (collector/JSONL) is active."""
    _state.ensure_init()
    return _state.active()


def events_enabled() -> bool:
    """True when a record built now would land somewhere — a sink or the
    flight-recorder ring.  The guard instrumented hot paths use before
    doing ANY per-record work (trace-id formatting included): with this
    False, :func:`event` is a no-op and the disabled path allocates
    nothing."""
    _state.ensure_init()
    return _state.recording()


def _current_ctx() -> Optional[Dict[str, Any]]:
    stack = getattr(_tls, "ctx", None)
    return stack[-1] if stack else None


@contextmanager
def tracing(rid=None, engine=None, hop=None):
    """Push a request trace context onto the calling thread: every span
    started and every :func:`event` emitted inside the ``with`` block
    carries ``rid``/``engine``/``hop`` top-level on its record.  Nests —
    inner scopes inherit and may override fields — and is thread-local,
    so concurrent requests cannot cross-tag each other's records."""
    stack = getattr(_tls, "ctx", None)
    if stack is None:
        stack = _tls.ctx = []
    ctx = dict(stack[-1]) if stack else {}
    if rid is not None:
        ctx["rid"] = rid
    if engine is not None:
        ctx["engine"] = engine
    if hop is not None:
        ctx["hop"] = hop
    stack.append(ctx)
    try:
        yield ctx
    finally:
        stack.pop()


def event(name: str, *, rid=None, engine=None, hop=None, **attrs) -> None:
    """Emit one request-lifecycle event (``req.submitted``,
    ``req.first_token``, ``req.failed`` ...) carrying the trace context.

    ``rid``/``engine``/``hop`` default from the ambient :func:`tracing`
    scope.  Zero cost when nothing is recording (no sink, no flight
    ring): the function returns before building any record."""
    _state.ensure_init()
    if not _state.recording():
        return
    rec: Dict[str, Any] = {"type": "event", "name": name, "ts": time.time()}
    ctx = _current_ctx()
    if ctx:
        rec.update(ctx)
    if rid is not None:
        rec["rid"] = rid
    if engine is not None:
        rec["engine"] = engine
    if hop is not None:
        rec["hop"] = hop
    if attrs:
        rec["attrs"] = attrs
    _state.record(rec)


def flight_dump(reason: str, **attrs) -> int:
    """Dump the flight-recorder ring: the recent-records snapshot a
    post-mortem reads when full tracing wasn't on.  Returns the number
    of records dumped (0 with the recorder disabled or the ring empty).

    A header line ``{"type": "flight_dump", "reason", "n", ...}`` marks
    the dump.  With a dedicated flight file configured
    (``TDX_FLIGHT_RECORDER=/path``), header + records append there.
    With the recorder dumping into the main JSONL sink, records the sink
    already exported as they happened are not re-written — only the
    header (the marker CI and operators grep for) plus any records
    captured while no sink was active yet (``header["backfilled"]``
    counts those).  The ring clears only once the dump actually landed
    somewhere, so back-to-back failures dump disjoint windows but a
    dump that could not persist (dedicated file unwritable, or no sink
    configured at all) keeps its window for a later retry instead of
    silently destroying the post-mortem.

    Dedicated-file dumps are durable before they are reported: a fresh
    file is written via tmp + fsync + atomic rename, appends fsync
    before the ring clears — a crash right after the dump (the moment
    the file is for) can not leave a torn or empty forensics file."""
    _state.ensure_init()
    ring = _state.flight
    if ring is None or not ring:
        return 0
    records = [rec for _, rec in ring]
    header: Dict[str, Any] = {
        "type": "flight_dump",
        "ts": time.time(),
        "reason": reason,
        "n": len(records),
    }
    if attrs:
        header["attrs"] = attrs
    path = _state.flight_path
    if path is None:
        if not _state.active():
            # Ring-only mode with no main sink: there is nowhere to
            # persist the window — keep it (a sink configured later, or
            # a dedicated flight path, dumps it then) and say so.
            _logger.warning(
                "telemetry: flight dump (%s) has no sink — configure "
                "TDX_TELEMETRY or a dedicated TDX_FLIGHT_RECORDER path; "
                "keeping the %d-record window", reason, len(records),
            )
            return 0
        unexported = [rec for exported, rec in ring if not exported]
        if unexported:
            header["backfilled"] = len(unexported)
        _state.write_jsonl(header)
        if _state.collect:
            _state.spans.append(header)
        for rec in unexported:
            _state.write_jsonl(rec)
            if _state.collect:
                _state.spans.append(rec)
        ring.clear()
        return len(records)
    lines = []
    for rec in [header] + records:
        try:
            lines.append(json.dumps(rec, default=str))
        except (TypeError, ValueError):
            lines.append(json.dumps({k: str(v) for k, v in rec.items()}))
    text = "\n".join(lines) + "\n"
    try:
        # Durable before reported (the ring clears below on the strength
        # of this write): a crash right after a dump is exactly when the
        # forensics file is read, so it must never be torn or empty.  A
        # FIRST dump writes tmp + fsync + atomic rename (no window where
        # the file exists but is incomplete); later dumps append + fsync
        # before the ring clears.
        if not os.path.exists(path):
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(text)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        else:
            with open(path, "a", encoding="utf-8") as f:
                f.write(text)
                f.flush()
                os.fsync(f.fileno())
    except OSError as e:  # telemetry never fails the operation
        _logger.warning(
            "telemetry: flight dump to %s failed (%s); keeping the "
            "%d-record window", path, e, len(records),
        )
        return 0
    ring.clear()
    return len(records)


def span(name: str, *, detached: bool = False, **attrs) -> Span:
    """Context-manager span: ``with span("materialize.compile", n=3): ...``.

    Always times; records to the active sinks on exit.  With
    ``TDX_TELEMETRY_JAX=1`` the region is annotated into XLA profiler
    traces (``step=`` attribute → ``StepTraceAnnotation``).
    ``detached=True`` keeps the span off the thread's nesting stack (it
    never parents another span) — for long-lived regions crossing
    arbitrary work."""
    _state.ensure_init()
    return Span(name, attrs or None, detached=detached)


def start_span(name: str, *, detached: bool = False, **attrs) -> Span:
    """Manual-boundary span: ``sp = start_span(...); ...; sp.end()``."""
    _state.ensure_init()
    return Span(name, attrs or None, detached=detached).start()


def counter(name: str, **labels) -> Counter:
    """Get-or-create the named counter (bind once at module level on hot
    paths — the lookup takes the registry lock).  Keyword labels
    canonicalize into the name (``counter("serve.shed", engine="eng0")``
    → ``serve.shed{engine=eng0}``) so N engines in one process count
    separately."""
    if labels:
        name = _labeled(name, labels)
    c = _state.counters.get(name)
    if c is None:
        with _REG_LOCK:
            c = _state.counters.setdefault(name, Counter(name))
    return c


def gauge(name: str, **labels) -> Gauge:
    """Get-or-create the named gauge (labels as in :func:`counter`)."""
    if labels:
        name = _labeled(name, labels)
    g = _state.gauges.get(name)
    if g is None:
        with _REG_LOCK:
            g = _state.gauges.setdefault(name, Gauge(name))
    return g


def histogram(
    name: str, bounds: Optional[Sequence[float]] = None, **labels
) -> Histogram:
    """Get-or-create the named histogram (labels as in :func:`counter`).
    ``bounds`` applies only at creation; the default is the latency
    ladder (100 µs .. 100 s, ~33% resolution)."""
    if labels:
        name = _labeled(name, labels)
    h = _state.histograms.get(name)
    if h is None:
        with _REG_LOCK:
            h = _state.histograms.setdefault(name, Histogram(name, bounds))
    return h


def remove(name: str, **labels) -> bool:
    """Drop the named instrument (counter, gauge, or histogram — labels
    as in :func:`counter`) from the registry.  Returns True when
    something was removed.

    This is the bounded-cardinality valve for *dynamic label families*
    (``gauge("serve.queue_depth", tenant=...)``, the per-tenant SLO
    gauges): a long-lived engine serving free-form tenant ids prunes a
    tenant's instruments when it goes idle, so the registry — and every
    exported counters snapshot and ``/metrics`` scrape — tracks ACTIVE
    labels, not labels ever seen.

    Do NOT remove an instrument a module bound at import time (the
    reason :func:`reset` zeroes in place instead of clearing): the
    binder would keep counting into an object the registry can no
    longer see.  Removal is for instruments looked up fresh at each
    use."""
    if labels:
        name = _labeled(name, labels)
    with _REG_LOCK:
        found = _state.counters.pop(name, None) is not None
        found = (_state.gauges.pop(name, None) is not None) or found
        found = (_state.histograms.pop(name, None) is not None) or found
    return found


def add_listener(fn) -> None:
    """Register an in-process record listener: ``fn(rec)`` is called
    with every span/event record as it is emitted (exceptions are
    swallowed — telemetry never fails the instrumented operation).  A
    registered listener counts as a recording target
    (:func:`events_enabled` goes True), so lifecycle events are built
    for it even with every sink and the flight ring off — the ops
    plane's SLO monitor consumes the stream this way.  Listeners run
    on the emitting thread: keep them cheap."""
    _state.ensure_init()
    if fn not in _state.listeners:
        _state.listeners.append(fn)


def remove_listener(fn) -> None:
    """Unregister a record listener (no-op if absent)."""
    try:
        _state.listeners.remove(fn)
    except ValueError:
        pass


def flight_records() -> List[Dict[str, Any]]:
    """Snapshot of the flight-recorder ring's records, oldest first
    (empty with the recorder off).  Read-only: the ring is untouched —
    this is the live view the ops plane's ``/requests`` endpoint
    reconstructs timelines from, between (and without) dumps."""
    ring = _state.flight
    if ring is None:
        return []
    return [rec for _, rec in list(ring)]


def registry_view() -> tuple:
    """One consistent view of the live instrument registries for an
    exporter: ``(counters, gauges, histograms)`` as shallow dict copies
    (name → instrument OBJECT, not value) taken under the registry
    lock.  Values are read from the objects afterwards — each carries
    its own lock where torn reads could matter."""
    with _REG_LOCK:
        return (
            dict(_state.counters),
            dict(_state.gauges),
            dict(_state.histograms),
        )


def histograms() -> Dict[str, Dict[str, Any]]:
    """Current histogram summaries, name → ``{count, sum, min, max,
    p50, p95, p99}`` (empty histograms report ``{"count": 0}``)."""
    return {
        name: h.summary()
        for name, h in sorted(_state.histograms.items())
    }


def counters() -> Dict[str, int]:
    """Current counter values, name → count."""
    return {name: c.value for name, c in sorted(_state.counters.items())}


def gauges() -> Dict[str, Any]:
    """Current gauge values (unset gauges omitted)."""
    return {
        name: g.value
        for name, g in sorted(_state.gauges.items())
        if g.value is not None
    }


def snapshot() -> Dict[str, Any]:
    """The in-memory collector as a plain dict:
    ``{"counters": {...}, "gauges": {...}, "histograms": {...},
    "spans": [...]}`` (``spans`` holds every collected record — span
    AND event lines, in emission order)."""
    _state.ensure_init()
    return {
        "counters": counters(),
        "gauges": gauges(),
        "histograms": histograms(),
        "spans": list(_state.spans),
    }


def drain() -> List[Dict[str, Any]]:
    """Pop and return all collected span records (oldest first)."""
    _state.ensure_init()
    out = []
    try:
        while True:
            out.append(_state.spans.popleft())
    except IndexError:
        pass
    return out


def emit_counters() -> None:
    """Write one counters+gauges snapshot line to the JSONL sink (no-op
    without one).  Called at natural flush points — the end of each
    ``materialize_module_jax`` and at interpreter exit."""
    _state.ensure_init()
    if _state.jsonl_path is None:
        return
    rec = {
        "type": "counters",
        "ts": time.time(),
        "values": counters(),
        "gauges": gauges(),
    }
    if _state.histograms:
        # Additive key: pre-histogram consumers of the counters schema
        # (type/ts/values/gauges) parse unchanged.
        rec["histograms"] = histograms()
    _state.write_jsonl(rec)


# Sibling modules holding derived telemetry state (the perf plane's
# storm windows and HBM ledger) register a hook here so reset() clears
# them with the registries — a storm latched by one test must not stay
# latched into the next.
_RESET_HOOKS: List[Any] = []


def on_reset(fn) -> None:
    """Register ``fn()`` to run at the end of every :func:`reset`
    (idempotent per function; exceptions are swallowed — reset is test
    plumbing, not a failure path)."""
    if fn not in _RESET_HOOKS:
        _RESET_HOOKS.append(fn)


def reset() -> None:
    """Zero all counters/gauges/histograms and clear collected spans and
    the flight ring (tests).

    Values are zeroed IN PLACE — instrumented modules bind their Counter
    (and Histogram) objects once at import, so dropping registry entries
    would leave them counting into objects :func:`counters` can no
    longer see.  Dynamic label families (per-tenant gauges, per-engine
    histograms) are looked up fresh at each use instead — those prune
    via :func:`remove` when their label goes idle, which is what keeps
    the registry bounded under free-form label values."""
    with _REG_LOCK:
        for c in _state.counters.values():
            with c._lock:
                c._value = 0
        for g in _state.gauges.values():
            g._value = None
        for h in _state.histograms.values():
            h._zero()
    _state.spans.clear()
    if _state.flight is not None:
        _state.flight.clear()
    # Listeners clear too: a monitor leaked by one test must not keep
    # events_enabled() True (and the disabled-path pins red) in the
    # next.  Live ops planes re-subscribe nothing — close them first.
    _state.listeners.clear()
    # The CALLING thread's nesting/trace stacks clear too: a span
    # abandoned by one test (started, never ended) must not become a
    # phantom parent in the next.
    for attr in ("spans", "ctx"):
        stack = getattr(_tls, attr, None)
        if stack:
            stack.clear()
    for fn in list(_RESET_HOOKS):
        try:
            fn()
        except Exception:  # noqa: BLE001 — reset is test plumbing
            pass


def _flush_at_exit() -> None:  # pragma: no cover — interpreter teardown
    try:
        if _state.jsonl_path is not None and _state.counters:
            emit_counters()
        _state.close_jsonl()
    except Exception:  # noqa: BLE001
        pass


import atexit  # noqa: E402

atexit.register(_flush_at_exit)
